"""Probability-weighted mixing of multiple readers.

Reference parity: petastorm/weighted_sampling_reader.py (106 LoC) -
WeightedSamplingReader draws the next element from reader i with probability
probabilities[i], with schema/ngram/batched compatibility checks
(weighted_sampling_reader.py:26-92).

Differences: the draw is seeded (reproducible mixing), ``iter_batches``
mixing is supported for the columnar path, and the mixer participates in
the stream-certificate layer (docs/operations.md "Reproducibility"): every
draw folds into an order-sensitive **mixture digest**, so a mixed N-corpus
run diffs in O(1) exactly like a single-reader one - the draw sequence is
certified alongside each sub-reader's own StreamDigest
(:meth:`WeightedSamplingReader.diagnostics`).  Multi-corpus sampling is the
least reproducible stage of real LLM ingest (the reproducible-pipelines
paper, PAPERS.md); ``deterministic='auto'`` therefore derives a mixer seed
from the first reader's seed root whenever every sub-reader already runs
seed-stable delivery but the mixer itself was left unseeded.
"""

from __future__ import annotations

import logging
import struct
import zlib
from typing import List, Optional, Sequence

import numpy as np

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.seeding import derive_seed, seed_stream

logger = logging.getLogger(__name__)


class WeightedSamplingReader:
    """Mix several compatible readers into one stream, drawing each next
    row/batch from reader ``i`` with probability ``probabilities[i]``
    (normalized; seeded for reproducibility).  Schemas must agree on the
    delivered fields; exhausted readers drop out and the remaining weights
    renormalize (reference weighted_sampling_reader semantics).

    ``deterministic`` (the mixer-side analog of ``make_reader``'s knob):
    under ``'auto'`` (default), when EVERY sub-reader runs
    ``deterministic='seed'`` delivery but ``seed`` is None, an unseeded
    mixer would be the one stage defeating stream reproducibility - so the
    mixer seed is derived from the first reader's ``shuffle_seed``
    (``seeding.derive_seed``, domain ``'weighted_sampling.auto'``), with
    one warning naming the derivation.  ``'off'`` keeps ``seed=None``
    unseeded (each run mixes differently) and warns once that the mix
    defeats reproducibility when the sub-readers were all seeded.  An
    explicit ``seed`` always wins and silences both.

    Every draw (including the draws that discover an exhausted reader)
    folds into the **mixture digest** - see :attr:`diagnostics`.
    """

    def __init__(self, readers: Sequence, probabilities: Sequence[float],
                 seed: Optional[int] = None, deterministic: str = "auto"):
        if len(readers) != len(probabilities) or not readers:
            raise PetastormTpuError("readers and probabilities must be same non-zero length")
        if deterministic not in ("auto", "off"):
            raise PetastormTpuError(
                f"deterministic must be 'auto' or 'off'; got"
                f" {deterministic!r}")
        p = np.asarray(probabilities, dtype=np.float64)
        if (p < 0).any() or p.sum() <= 0:
            raise PetastormTpuError(f"Invalid probabilities {probabilities}")
        self._p = p / p.sum()
        self._readers = list(readers)
        all_seeded = all(getattr(r, "deterministic", "off") == "seed"
                         for r in self._readers)
        if seed is None and all_seeded:
            if deterministic == "auto":
                # the sub-readers each deliver a seed-stable stream; an
                # unseeded mixer would be the single stage making the MIXED
                # stream irreproducible.  Derive the mixer seed from the
                # first reader's seed root so the whole mix is a pure
                # function of it (pass an explicit seed to pin, or
                # deterministic='off' to keep unseeded mixing).
                root = getattr(self._readers[0], "shuffle_seed", None)
                seed = derive_seed(root, 0, "weighted_sampling.auto")
                logger.warning(
                    "WeightedSamplingReader: every sub-reader runs"
                    " deterministic='seed' delivery but the mixer got"
                    " seed=None, which would defeat stream reproducibility;"
                    " deriving the mixer seed from the first reader's"
                    " shuffle_seed (%r). Pass seed=... to pin it, or"
                    " deterministic='off' to keep unseeded mixing.", root)
            else:
                logger.warning(
                    "WeightedSamplingReader: every sub-reader runs"
                    " deterministic='seed' delivery but the mix is unseeded"
                    " (seed=None, deterministic='off') - the MIXED stream"
                    " differs every run, defeating stream reproducibility."
                    " Pass seed=... for a reproducible mixture.")
        #: the resolved mixer seed (None = unseeded); diagnostics surface it
        self.seed = seed
        #: downstream-adapter surface, mirroring Reader's: delivery through
        #: this mixer is seed-stable exactly when the mixer is seeded AND
        #: every sub-reader runs seed-stable delivery.  ``shuffle_seed`` is
        #: the seed ROOT adapters derive their buffer RNGs from
        #: (seeding.reader_buffer_seed) - without these, a JaxDataLoader
        #: over a fully-seeded mixture would silently fall back to
        #: unseeded shuffle buffers
        self.deterministic = ("seed" if seed is not None and all_seeded
                              else "off")
        self.shuffle_seed = seed if self.deterministic == "seed" else None
        # centralized derivation (petastorm_tpu.seeding): a seeded mix draws
        # a PYTHONHASHSEED-stable stream independent of every other seeded
        # stage; None keeps the unseeded each-run-differs behavior
        self._rng = (seed_stream(seed, 0, "weighted_sampling")
                     if seed is not None else np.random.default_rng())
        # readers not yet exhausted by __next__; persists across calls so dead
        # readers are not re-drawn/re-polled on every remaining row
        self._alive: List[int] = list(range(len(self._readers)))
        # mixture certificate: order-sensitive crc chain over the draw
        # sequence (draw ordinal, chosen reader, exhaustion markers) - the
        # certified record of WHICH corpus each delivered unit came from
        self._draw_crc = 0
        self._draw_count = 0

        first = readers[0]
        self.batched_output = first.batched_output
        self.ngram = getattr(first, "ngram", None)
        self.schema = first.schema
        self.output_schema = getattr(first, "output_schema", first.schema)
        #: decode_placement='device' fields propagate so JaxDataLoader finds
        #: and finishes the coefficient-plane columns; every sub-reader must
        #: agree (mixing a planes stream with a pixels stream cannot batch)
        self.device_decode_fields = list(
            getattr(first, "device_decode_fields", ()) or ())
        self.device_decode_mixed = frozenset(
            getattr(first, "device_decode_mixed", ()) or ())
        for r in readers[1:]:
            if r.batched_output != self.batched_output:
                raise PetastormTpuError("All readers must share batched_output mode")
            if getattr(r, "ngram", None) != self.ngram:
                raise PetastormTpuError(
                    "All readers must share an identical NGram spec (same"
                    " offsets, fields, delta_threshold, timestamp settings)")
            if list(r.schema.fields) != list(self.schema.fields):
                raise PetastormTpuError(
                    f"Schema mismatch: {list(r.schema.fields)} vs"
                    f" {list(self.schema.fields)}")
            if (list(getattr(r, "device_decode_fields", ()) or ())
                    != self.device_decode_fields
                    or frozenset(getattr(r, "device_decode_mixed", ()) or ())
                    != self.device_decode_mixed):
                raise PetastormTpuError(
                    "All readers must share the same decode_placement: one"
                    f" ships {self.device_decode_fields or 'pixels'} and"
                    f" another {getattr(r, 'device_decode_fields', []) or 'pixels'}"
                    " (mixed-geometry mode must also match)")

    @property
    def last_row_consumed(self) -> bool:
        """True once every underlying reader finished its epochs."""
        return all(r.last_row_consumed for r in self._readers)

    @property
    def telemetry(self):
        """The first sub-reader's recorder (downstream adapters - the jax
        loader, the sequence packer - observe the mix through it)."""
        return getattr(self._readers[0], "telemetry", None)

    # -- mixture certificate (docs/operations.md "Reproducibility") ----------

    def _record_draw(self, reader_index: int, exhausted: bool = False) -> None:
        self._draw_crc = zlib.crc32(
            struct.pack("<3q", self._draw_count, int(reader_index),
                        1 if exhausted else 0), self._draw_crc)
        self._draw_count += 1

    @property
    def mixture_digest(self) -> dict:
        """The mixture-side stream certificate: the draw-sequence chain plus
        a combined value folding every sub-reader's own StreamDigest - two
        mixed runs are diffed in O(1) like single-reader ones.  ``combined``
        is only configuration-stable when the mixer is seeded and every
        sub-reader runs ``deterministic='seed'``."""
        combined = self._draw_crc
        readers = []
        for r in self._readers:
            sub = None
            diag = getattr(r, "diagnostics", None)
            if isinstance(diag, dict):
                sub = (diag.get("stream_digest") or {}).get("combined")
            readers.append(sub)
            combined = zlib.crc32(
                (sub or "-").encode("ascii", "replace"), combined)
        return {"draws": f"{self._draw_crc:08x}",
                "draw_count": self._draw_count,
                "readers": readers,
                "combined": f"{combined:08x}"}

    @property
    def diagnostics(self) -> dict:
        """Mixer diagnostics: the mixture digest, resolved seed and
        per-reader aliveness (sub-reader diagnostics stay on the readers)."""
        return {"mixture_digest": self.mixture_digest,
                "seed": self.seed,
                "alive_readers": list(self._alive),
                "num_readers": len(self._readers)}

    def __iter__(self):
        return self

    def __next__(self):
        if self.device_decode_fields:
            raise PetastormTpuError(
                f"fields {self.device_decode_fields} use"
                " decode_placement='device' (coefficient planes, not pixels);"
                " consume through petastorm_tpu.jax.JaxDataLoader or use"
                " decode_placement='host'")
        while self._alive:
            weights = self._p[self._alive] / self._p[self._alive].sum()
            i = int(self._rng.choice(len(self._alive), p=weights))
            try:
                row = next(self._readers[self._alive[i]])
            except StopIteration:
                self._record_draw(self._alive[i], exhausted=True)
                self._alive.pop(i)
            else:
                self._record_draw(self._alive[i])
                return row
        raise StopIteration

    def iter_batches(self):
        """Columnar batches drawn from the mixed stream (device-feed path).
        Shares the aliveness ledger with ``__next__`` (one consumption mode
        per instance), so ``diagnostics['alive_readers']`` stays truthful
        for batch consumers too."""
        sources = [r.iter_batches() for r in self._readers]
        alive = self._alive
        while alive:
            weights = self._p[alive] / self._p[alive].sum()
            i = int(self._rng.choice(len(alive), p=weights))
            try:
                batch = next(sources[alive[i]])
            except StopIteration:
                self._record_draw(alive[i], exhausted=True)
                alive.pop(i)
            else:
                self._record_draw(alive[i])
                yield batch

    def stop(self) -> None:
        """Stop every underlying reader."""
        for r in self._readers:
            r.stop()

    def join(self) -> None:
        """Wait for every underlying reader to exit (after stop())."""
        for r in self._readers:
            r.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        self.join()
