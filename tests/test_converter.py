"""Converter tests (reference: tests/test_spark_dataset_converter.py, JVM-free)."""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest
import torch

from petastorm_tpu.converter import (CACHE_DIR_ENV_VAR, _registered_converters,
                                     make_converter)
from petastorm_tpu.errors import PetastormTpuError


def _df(n=64):
    return pd.DataFrame({
        "id": np.arange(n, dtype=np.int64),
        "x": np.linspace(0, 1, n).astype(np.float64),
        "label": (np.arange(n) % 3).astype(np.int32),
    })


def test_requires_cache_dir(monkeypatch):
    monkeypatch.delenv(CACHE_DIR_ENV_VAR, raising=False)
    with pytest.raises(PetastormTpuError, match="cache"):
        make_converter(_df())


def test_materialize_and_read_back(tmp_path):
    conv = make_converter(_df(), cache_dir_url=str(tmp_path / "cache"))
    try:
        assert len(conv) == 64
        with conv.make_reader(reader_pool_type="serial",
                              shuffle_row_groups=False, num_epochs=1) as r:
            rows = list(r)
        assert len(rows) == 64
        assert [row.id for row in rows] == list(range(64))
    finally:
        conv.delete()
    assert not os.path.exists(conv.cache_url)


def test_float64_downcast_default_and_opt_out(tmp_path):
    conv32 = make_converter(_df(), cache_dir_url=str(tmp_path / "c32"))
    conv64 = make_converter(_df(), cache_dir_url=str(tmp_path / "c64"),
                            dtype=None)
    try:
        assert conv32.schema["x"].dtype == np.float32
        assert conv64.schema["x"].dtype == np.float64
    finally:
        conv32.delete(), conv64.delete()


def test_dedup_by_content(tmp_path):
    cache = str(tmp_path / "cache")
    a = make_converter(_df(), cache_dir_url=cache)
    b = make_converter(_df(), cache_dir_url=cache)        # same content
    c = make_converter(_df(32), cache_dir_url=cache)      # different content
    d = make_converter(_df(), cache_dir_url=cache, row_group_size_mb=1)
    try:
        assert a is b  # shared handle: delete() on one cannot orphan the other
        assert a.cache_url != c.cache_url
        assert a.cache_url != d.cache_url  # params are part of the fingerprint
    finally:
        for conv in (a, b, c, d):
            conv.delete()
    # a fresh conversion after delete() re-materializes rather than reusing a
    # dead handle
    e = make_converter(_df(), cache_dir_url=cache)
    try:
        assert e is not a
        with e.make_reader(num_epochs=1) as r:
            assert len(list(r)) == 64
    finally:
        e.delete()


def test_env_var_cache_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(CACHE_DIR_ENV_VAR, str(tmp_path / "envcache"))
    conv = make_converter(_df())
    try:
        assert str(tmp_path / "envcache") in conv.cache_url
    finally:
        conv.delete()


def test_arrow_table_input(tmp_path):
    table = pa.table({"id": np.arange(10, dtype=np.int64),
                      "y": np.ones(10, np.float32)})
    conv = make_converter(table, cache_dir_url=str(tmp_path / "cache"))
    try:
        with conv.make_reader(num_epochs=1) as r:
            assert len(list(r)) == 10
    finally:
        conv.delete()


def test_unsupported_input_rejected(tmp_path):
    with pytest.raises(PetastormTpuError, match="Unsupported input"):
        make_converter([1, 2, 3], cache_dir_url=str(tmp_path / "cache"))


def test_make_torch_dataloader(tmp_path):
    conv = make_converter(_df(), cache_dir_url=str(tmp_path / "cache"))
    try:
        with conv.make_torch_dataloader(
                batch_size=16,
                reader_kwargs={"num_epochs": 1}) as loader:
            batches = list(loader)
        assert sum(len(b["id"]) for b in batches) == 64
        assert isinstance(batches[0]["x"], torch.Tensor)
    finally:
        conv.delete()


def test_make_jax_loader(tmp_path):
    import jax

    conv = make_converter(_df(), cache_dir_url=str(tmp_path / "cache"))
    try:
        with conv.make_jax_loader(
                batch_size=16,
                reader_kwargs={"num_epochs": 1}) as loader:
            batch = next(iter(loader))
        assert isinstance(batch["x"], jax.Array)
        assert batch["x"].shape == (16,)
    finally:
        conv.delete()


def test_rank_mismatch_warns(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_RANK", "1")
    monkeypatch.setenv("HOROVOD_SIZE", "4")
    conv = make_converter(_df(2000), cache_dir_url=str(tmp_path / "cache"),
                          row_group_size_mb=0.001)
    try:
        with pytest.warns(UserWarning, match="disagrees"):
            with conv.make_reader(cur_shard=0, shard_count=4,
                                  num_epochs=1) as r:
                next(iter(r))
        with pytest.warns(UserWarning, match="ALL the data"):
            with conv.make_reader(num_epochs=1) as r:
                next(iter(r))
    finally:
        conv.delete()


def test_atexit_registration(tmp_path):
    conv = make_converter(_df(), cache_dir_url=str(tmp_path / "cache"))
    assert conv in _registered_converters
    conv.delete()
    assert conv not in _registered_converters
    keep = make_converter(_df(), cache_dir_url=str(tmp_path / "cache2"),
                          delete_at_exit=False)
    assert keep not in _registered_converters
    # delete() on a non-owning converter must not remove the files
    keep.delete()
    assert os.path.exists(keep.cache_url)


def test_make_tf_dataset(tmp_path):
    conv = make_converter(_df(), cache_dir_url=str(tmp_path / "cache"))
    try:
        cm = conv.make_tf_dataset(
            reader_kwargs={"num_epochs": 1, "reader_pool_type": "serial",
                           "shuffle_row_groups": False})
        with cm as dataset:
            ids = [int(item.id) for item in dataset.as_numpy_iterator()]
        assert ids == list(range(64))
        assert cm._reader._stopped  # reader released on exit
    finally:
        conv.delete()


def test_slices_get_distinct_fingerprints(tmp_path):
    """Zero-copy slices share buffers; the fingerprint must still distinguish
    them (regression: slice(0,50) and slice(50,50) collided, returning the
    wrong cached dataset)."""
    t = pa.table({"x": np.arange(100, dtype=np.int64)})
    c1 = make_converter(t.slice(0, 50), str(tmp_path), dtype=None)
    c2 = make_converter(t.slice(50, 50), str(tmp_path), dtype=None)
    c3 = make_converter(t, str(tmp_path), dtype=None)
    assert len({c1.cache_url, c2.cache_url, c3.cache_url}) == 3
    with c2.make_reader(shuffle_row_groups=False) as r:
        assert sorted(row.x for row in r) == list(range(50, 100))


def test_dedup_persistence_wins(tmp_path):
    """A later delete_at_exit=False on the same content un-registers cleanup."""
    conv1 = make_converter(_df(), str(tmp_path))
    assert conv1 in _registered_converters
    conv2 = make_converter(_df(), str(tmp_path), delete_at_exit=False)
    assert conv2 is conv1
    assert conv1 not in _registered_converters
    assert not conv1._owns_cache
    # asking to delete again warns but keeps the persistent choice
    with pytest.warns(UserWarning, match="delete_at_exit=False"):
        make_converter(_df(), str(tmp_path), delete_at_exit=True)
    assert conv1 not in _registered_converters


def test_explicit_snappy_reuses_default_cache(tmp_path):
    c1 = make_converter(_df(), str(tmp_path))
    c2 = make_converter(_df(), str(tmp_path), compression_codec="snappy")
    assert c2 is c1


def test_loader_factory_failure_does_not_leak_reader(tmp_path):
    import threading

    conv = make_converter(_df(), str(tmp_path))
    before = threading.active_count()
    with pytest.raises(Exception):
        conv.make_jax_loader(batch_size=0)
    deadline = 50
    while threading.active_count() > before and deadline:
        import time
        time.sleep(0.1)
        deadline -= 1
    assert threading.active_count() <= before
