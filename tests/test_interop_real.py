"""Round-trip against the GENUINE reference petastorm package.

tests/test_interop.py exercises the legacy-pickle decoder against simulated
streams; here the pickles come from the real ``petastorm.unischema`` /
``petastorm.codecs`` / ``petastorm.etl.rowgroup_indexers`` classes imported
from /root/reference, so a layout drift between our shims and the genuine
classes fails loudly instead of silently.

The reference package cannot fully import on modern pyarrow (its reader stack
needs the removed ``pyarrow.filesystem`` legacy API), so only the modules
whose PICKLED FORMS matter are loaded, through a synthetic package whose
``__init__`` is empty - the submodules themselves import cleanly.  Data files
and ``_common_metadata`` are laid out exactly as the reference writes them:
schema pickled under ``dataset-toolkit.unischema.v1``
(etl/dataset_metadata.py:195-206), per-file rowgroup counts as JSON
(etl/dataset_metadata.py:209-242), indexers pickled at HIGHEST_PROTOCOL under
``dataset-toolkit.rowgroups_index.v1`` (etl/rowgroup_indexing.py:30,74-80).
"""

import json
import os
import pickle
import sys
import types

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

REFERENCE = "/root/reference"

if not os.path.isdir(os.path.join(REFERENCE, "petastorm")):
    pytest.skip("reference petastorm checkout not available",
                allow_module_level=True)

cv2 = pytest.importorskip("cv2")


@pytest.fixture(scope="module")
def ref():
    """Genuine reference modules via a synthetic package (empty __init__)."""
    saved = {k: sys.modules.get(k) for k in list(sys.modules)
             if k == "petastorm" or k.startswith("petastorm.")
             or k == "pyspark" or k.startswith("pyspark.")}
    for k in saved:
        sys.modules.pop(k, None)
    pkg = types.ModuleType("petastorm")
    pkg.__path__ = [os.path.join(REFERENCE, "petastorm")]
    sys.modules["petastorm"] = pkg
    # minimal pyspark.sql.types: ScalarCodec pickles an INSTANCE of one of
    # these classes; __module__ must read 'pyspark.sql.types' so the pickle
    # GLOBAL matches what a real petastorm+pyspark install produces
    pys = types.ModuleType("pyspark")
    pys_sql = types.ModuleType("pyspark.sql")
    pys_types = types.ModuleType("pyspark.sql.types")
    for tname in ("ByteType", "ShortType", "IntegerType", "LongType",
                  "FloatType", "DoubleType", "BooleanType", "StringType"):
        cls = type(tname, (), {"__module__": "pyspark.sql.types"})
        setattr(pys_types, tname, cls)
    pys_sql.types = pys_types
    pys.sql = pys_sql
    sys.modules["pyspark"] = pys
    sys.modules["pyspark.sql"] = pys_sql
    sys.modules["pyspark.sql.types"] = pys_types

    from petastorm.codecs import (CompressedImageCodec, NdarrayCodec,
                                  ScalarCodec)
    from petastorm.etl.rowgroup_indexers import (FieldNotNullIndexer,
                                                 SingleFieldIndexer)
    from petastorm.unischema import Unischema, UnischemaField

    ns = types.SimpleNamespace(
        Unischema=Unischema, UnischemaField=UnischemaField,
        NdarrayCodec=NdarrayCodec, ScalarCodec=ScalarCodec,
        CompressedImageCodec=CompressedImageCodec,
        SingleFieldIndexer=SingleFieldIndexer,
        FieldNotNullIndexer=FieldNotNullIndexer,
        IntegerType=pys_types.IntegerType)
    yield ns
    for k in ("petastorm", "pyspark", "pyspark.sql", "pyspark.sql.types"):
        sys.modules.pop(k, None)
    for k, v in saved.items():
        if v is not None:
            sys.modules[k] = v


UNISCHEMA_KEY = b"dataset-toolkit.unischema.v1"
ROW_GROUPS_KEY = b"dataset-toolkit.num_row_groups_per_file.v1"
INDEX_KEY = b"dataset-toolkit.rowgroups_index.v1"

ROWS, GROUP = 24, 8


def _smooth_rgb(h, w, seed=0):
    x, y = np.meshgrid(np.arange(w), np.arange(h))
    img = np.stack([(np.sin(x / (9.0 + seed)) + np.cos(y / 7.0)) * 60 + 120,
                    (np.sin(x / 5.0) + seed * 0.1) * 50 + 128,
                    np.cos(x / 11.0) * np.sin(y / 13.0) * 55 + 120], -1)
    return img.clip(0, 255).astype(np.uint8)


@pytest.fixture(scope="module")
def legacy_ds(ref, tmp_path_factory):
    """A dataset whose metadata pickles are produced by the GENUINE classes."""
    schema = ref.Unischema("RealLegacy", [
        ref.UnischemaField("id", np.int64, (), ref.ScalarCodec(ref.IntegerType()),
                           False),
        ref.UnischemaField("image", np.uint8, (32, 48, 3),
                           ref.CompressedImageCodec("png"), False),
        ref.UnischemaField("vec", np.float32, (5,), ref.NdarrayCodec(), False),
    ])
    rows = []
    for i in range(ROWS):
        # encode with the genuine codecs - the exact bytes a reference-written
        # dataset stores
        rows.append({
            "id": int(i),
            "image": bytes(schema.fields["image"].codec.encode(
                schema.fields["image"], _smooth_rgb(32, 48, seed=i))),
            "vec": bytes(schema.fields["vec"].codec.encode(
                schema.fields["vec"], np.full(5, i, np.float32))),
        })
    arrow_schema = pa.schema([pa.field("id", pa.int64()),
                              pa.field("image", pa.binary()),
                              pa.field("vec", pa.binary())])
    root = str(tmp_path_factory.mktemp("real_legacy") / "ds")
    os.makedirs(root)
    table = pa.Table.from_pylist(rows, schema=arrow_schema)
    path = os.path.join(root, "part-00000.parquet")
    pq.write_table(table, path, row_group_size=GROUP)

    # indexes over rowgroup ordinals, built with the genuine indexer classes
    # (attribute layout of rowgroup_indexers.py:28-31,83-86)
    single = ref.SingleFieldIndexer("by_bucket", "id")
    notnull = ref.FieldNotNullIndexer("vec_not_null", "vec")
    n_groups = pq.ParquetFile(path).metadata.num_row_groups
    for g in range(n_groups):
        for i in range(g * GROUP, min((g + 1) * GROUP, ROWS)):
            single._index_data[i % 3].add(g)
        notnull._index_data.add(g)

    kv = {
        UNISCHEMA_KEY: pickle.dumps(schema),
        ROW_GROUPS_KEY: json.dumps(
            {"part-00000.parquet": n_groups}).encode(),
        INDEX_KEY: pickle.dumps({"by_bucket": single, "vec_not_null": notnull},
                                pickle.HIGHEST_PROTOCOL),
    }
    pq.write_metadata(arrow_schema.with_metadata(kv),
                      os.path.join(root, "_common_metadata"))
    return root


def test_make_reader_reads_genuine_legacy_dataset(legacy_ds):
    from petastorm_tpu.reader import make_reader

    with make_reader(legacy_ds, reader_pool_type="serial", num_epochs=1,
                     shuffle_row_groups=False) as r:
        rows = list(r)
    assert [row.id for row in rows] == list(range(ROWS))
    assert rows[0].image.shape == (32, 48, 3) and rows[0].image.dtype == np.uint8
    # PNG is lossless: decoded pixels equal the source exactly
    np.testing.assert_array_equal(rows[7].image, _smooth_rgb(32, 48, seed=7))
    np.testing.assert_array_equal(rows[3].vec, np.full(5, 3, np.float32))


def test_schema_conversion_from_genuine_pickle(legacy_ds):
    from petastorm_tpu.codecs import CompressedImageCodec as OurImage
    from petastorm_tpu.etl.metadata import open_dataset
    from petastorm_tpu.schema import Schema

    info = open_dataset(legacy_ds)
    from petastorm_tpu.etl.metadata import infer_or_load_schema

    schema = infer_or_load_schema(info)
    assert isinstance(schema, Schema)
    assert schema["image"].shape == (32, 48, 3)
    assert isinstance(schema["image"].codec, OurImage)
    assert schema["vec"].dtype == np.float32


def test_index_selectors_from_genuine_pickle(legacy_ds):
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.selectors import SingleIndexSelector

    with make_reader(legacy_ds, reader_pool_type="serial", num_epochs=1,
                     shuffle_row_groups=False,
                     rowgroup_selector=SingleIndexSelector("by_bucket", [1])
                     ) as r:
        rows = list(r)
    # every rowgroup contains ids with bucket 1, so selection keeps all groups
    assert len(rows) == ROWS


def test_pseudorandom_split_reference_compat(legacy_ds, ref):
    """compat='reference' reproduces the genuine _string_to_bucket membership
    (reference predicates.py:39-41,171-182) for a migrating split."""
    import importlib.util

    spec = importlib.util.find_spec("petastorm.predicates")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    ref_pred = mod.in_pseudorandom_split([0.5, 0.5], 0, "id")

    from petastorm_tpu.predicates import in_pseudorandom_split

    ours = in_pseudorandom_split([0.5, 0.5], 0, "id", compat="reference")
    native = in_pseudorandom_split([0.5, 0.5], 0, "id")
    ids = np.arange(500, dtype=np.int64)
    ref_mask = np.array([ref_pred.do_include({"id": v}) for v in ids])
    our_mask = ours.do_include_vectorized({"id": ids})
    np.testing.assert_array_equal(our_mask, ref_mask)
    # sanity: the native mode is a DIFFERENT membership (documented)
    assert not np.array_equal(native.do_include_vectorized({"id": ids}),
                              ref_mask)
