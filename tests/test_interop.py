"""Legacy-Petastorm interop tests.

A "legacy writer" is simulated with throwaway fake ``petastorm``/``pyspark``
modules whose classes have the exact module paths + attribute layouts the
reference pickles (unischema.py:51-85,179-197; codecs.py:54-63,192-197;
rowgroup_indexers.py:28-31,83-86), so ``pickle.dumps`` produces byte streams
indistinguishable from a real reference-written ``_common_metadata``.
Reference test model: petastorm/tests/test_reading_legacy_datasets.py.
"""

import io
import pickle
import sys
import types
from collections import OrderedDict, defaultdict

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import interop
from petastorm_tpu.codecs import CompressedImageCodec as OurImageCodec
from petastorm_tpu.codecs import NdarrayCodec as OurNdarrayCodec
from petastorm_tpu.errors import MetadataError
from petastorm_tpu.etl import get_row_group_indexes, open_dataset
from petastorm_tpu.reader import make_reader
from petastorm_tpu.schema import Field
from petastorm_tpu.selectors import SingleIndexSelector


# ---------------------------------------------------------------------------
# Fake legacy-petastorm modules (pickle-layout-identical to the reference)
# ---------------------------------------------------------------------------

def _install_fake_petastorm():
    from collections import namedtuple as _nt

    uni = types.ModuleType("petastorm.unischema")

    class UnischemaField(_nt("UnischemaField",
                             ["name", "numpy_dtype", "shape", "codec", "nullable"])):
        pass

    UnischemaField.__new__.__defaults__ = (None, False)
    UnischemaField.__module__ = "petastorm.unischema"
    UnischemaField.__qualname__ = "UnischemaField"

    class Unischema(object):
        def __init__(self, name, fields):
            self._name = name
            self._fields = OrderedDict((f.name, f) for f in fields)
            for f in fields:
                if not hasattr(self, f.name):
                    setattr(self, f.name, f)

    Unischema.__module__ = "petastorm.unischema"
    Unischema.__qualname__ = "Unischema"
    uni.UnischemaField, uni.Unischema = UnischemaField, Unischema

    cod = types.ModuleType("petastorm.codecs")

    class NdarrayCodec(object):
        pass

    class CompressedNdarrayCodec(object):
        pass

    class CompressedImageCodec(object):
        def __init__(self, image_codec="png", quality=80):
            self._image_codec = "." + image_codec
            self._quality = quality

    class ScalarCodec(object):
        def __init__(self, spark_type):
            self._spark_type = spark_type

    for cls in (NdarrayCodec, CompressedNdarrayCodec, CompressedImageCodec, ScalarCodec):
        cls.__module__ = "petastorm.codecs"
        cls.__qualname__ = cls.__name__
        setattr(cod, cls.__name__, cls)

    idxm = types.ModuleType("petastorm.etl.rowgroup_indexers")

    class SingleFieldIndexer(object):
        def __init__(self, index_name, index_field):
            self._index_name = index_name
            self._column_name = index_field
            self._index_data = defaultdict(set)

    class FieldNotNullIndexer(object):
        def __init__(self, index_name, index_field):
            self._index_name = index_name
            self._column_name = index_field
            self._index_data = set()

    for cls in (SingleFieldIndexer, FieldNotNullIndexer):
        cls.__module__ = "petastorm.etl.rowgroup_indexers"
        cls.__qualname__ = cls.__name__
        setattr(idxm, cls.__name__, cls)

    spark = types.ModuleType("pyspark.sql.types")
    for tname in ("IntegerType", "LongType", "StringType", "DoubleType",
                  "BooleanType", "DecimalType"):
        cls = type(tname, (object,), {"__module__": "pyspark.sql.types",
                                      "__init__": lambda self, *a, **k: None})
        setattr(spark, tname, cls)

    pkg = types.ModuleType("petastorm")
    etl = types.ModuleType("petastorm.etl")
    pysparkm = types.ModuleType("pyspark")
    sqlm = types.ModuleType("pyspark.sql")
    mods = {"petastorm": pkg, "petastorm.unischema": uni, "petastorm.codecs": cod,
            "petastorm.etl": etl, "petastorm.etl.rowgroup_indexers": idxm,
            "pyspark": pysparkm, "pyspark.sql": sqlm, "pyspark.sql.types": spark}
    saved = {k: sys.modules.get(k) for k in mods}
    sys.modules.update(mods)
    return mods, saved


@pytest.fixture()
def fake_petastorm():
    mods, saved = _install_fake_petastorm()
    yield mods
    for k, v in saved.items():
        if v is None:
            sys.modules.pop(k, None)
        else:
            sys.modules[k] = v


def _npy_bytes(arr):
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


@pytest.fixture()
def legacy_dataset(tmp_path, fake_petastorm):
    """Parquet dataset laid out exactly like a reference-written one."""
    uni = fake_petastorm["petastorm.unischema"]
    cod = fake_petastorm["petastorm.codecs"]
    spark = fake_petastorm["pyspark.sql.types"]

    schema = uni.Unischema("LegacySchema", [
        uni.UnischemaField("id", np.int64, (), cod.ScalarCodec(spark.LongType()), False),
        uni.UnischemaField("name", np.str_, (), cod.ScalarCodec(spark.StringType()), False),
        uni.UnischemaField("embedding", np.float32, (4,), cod.NdarrayCodec(), False),
        uni.UnischemaField("image", np.uint8, (6, 5, 3), cod.CompressedImageCodec("png"), False),
    ])

    rng = np.random.default_rng(7)
    n = 20
    ids = np.arange(n, dtype=np.int64)
    names = [f"row_{i}" for i in range(n)]
    embeddings = [rng.standard_normal(4).astype(np.float32) for _ in range(n)]
    images = [rng.integers(0, 255, size=(6, 5, 3), dtype=np.uint8) for _ in range(n)]
    img_field = Field("image", np.uint8, (6, 5, 3))
    img_codec = OurImageCodec("png")

    table = pa.table({
        "id": pa.array(ids),
        "name": pa.array(names),
        "embedding": pa.array([_npy_bytes(e) for e in embeddings], type=pa.binary()),
        "image": pa.array([img_codec.encode(img_field, im) for im in images],
                          type=pa.binary()),
    })
    root = tmp_path / "legacy_ds"
    root.mkdir()
    pq.write_table(table, root / "part-00000.parquet", row_group_size=5)

    idxm = fake_petastorm["petastorm.etl.rowgroup_indexers"]
    single = idxm.SingleFieldIndexer("by_name", "name")
    for i, nm in enumerate(names):
        single._index_data[nm].add(i // 5)
    notnull = idxm.FieldNotNullIndexer("name_not_null", "name")
    notnull._index_data.update(range(4))
    kv = {
        interop.LEGACY_UNISCHEMA_KEY: pickle.dumps(schema),
        interop.LEGACY_ROW_GROUPS_KEY: b'{"part-00000.parquet": 4}',
        interop.LEGACY_INDEX_KEY: pickle.dumps(
            {"by_name": single, "name_not_null": notnull}, pickle.HIGHEST_PROTOCOL),
    }
    pq.write_metadata(table.schema.with_metadata(
        {k: v for k, v in kv.items()}), root / "_common_metadata")
    rows = {"ids": ids, "names": names, "embeddings": embeddings, "images": images}
    return str(root), rows


# ---------------------------------------------------------------------------
# Schema conversion
# ---------------------------------------------------------------------------

def test_legacy_schema_loads(legacy_dataset):
    url, _ = legacy_dataset
    info = open_dataset(url)
    schema = info.stored_schema
    assert schema is not None and schema.name == "LegacySchema"
    assert list(schema.fields) == ["id", "name", "embedding", "image"]
    assert schema["embedding"].shape == (4,)
    assert isinstance(schema["embedding"].codec, OurNdarrayCodec)
    assert isinstance(schema["image"].codec, OurImageCodec)
    assert schema["image"].codec.image_codec == "png"
    assert schema["name"].dtype == np.dtype("object")


def test_legacy_end_to_end_read(legacy_dataset):
    url, rows = legacy_dataset
    seen = {}
    with make_reader(url, workers_count=2) as reader:
        for row in reader:
            seen[int(row.id)] = row
    assert sorted(seen) == list(range(20))
    for i in range(20):
        row = seen[i]
        assert row.name == f"row_{i}"
        np.testing.assert_array_equal(row.embedding, rows["embeddings"][i])
        np.testing.assert_array_equal(row.image, rows["images"][i])


def test_legacy_stale_row_group_counts_warn(tmp_path, fake_petastorm, caplog):
    """A legacy counts payload disagreeing with real footers flags stale metadata."""
    import logging

    uni = fake_petastorm["petastorm.unischema"]
    schema = uni.Unischema("S", [uni.UnischemaField("x", np.int64, (), None, False)])
    table = pa.table({"x": pa.array(np.arange(10, dtype=np.int64))})
    root = tmp_path / "stale"
    root.mkdir()
    pq.write_table(table, root / "part-0.parquet", row_group_size=5)  # 2 rowgroups
    pq.write_metadata(table.schema.with_metadata({
        interop.LEGACY_UNISCHEMA_KEY: pickle.dumps(schema),
        interop.LEGACY_ROW_GROUPS_KEY: b'{"part-0.parquet": 7}',
    }), root / "_common_metadata")
    with caplog.at_level(logging.WARNING, logger="petastorm_tpu.etl.metadata"):
        info = open_dataset(str(root))
    assert len(info.row_groups) == 2  # footers win
    assert any("stale" in rec.message for rec in caplog.records)


def test_legacy_index_selector(legacy_dataset):
    url, _ = legacy_dataset
    info = open_dataset(url)
    indexes = get_row_group_indexes(info)
    assert set(indexes) == {"by_name", "name_not_null"}
    assert indexes["by_name"].get_row_group_indexes("row_7") == {1}
    assert indexes["name_not_null"].get_row_group_indexes() == {0, 1, 2, 3}
    with make_reader(url, rowgroup_selector=SingleIndexSelector("by_name", ["row_12"])) as r:
        ids = sorted(int(row.id) for row in r)
    assert ids == [10, 11, 12, 13, 14]  # the whole containing rowgroup


def test_legacy_package_names(fake_petastorm):
    """Pre-petastorm module paths (etl/legacy.py:31-33) resolve too."""
    uni = fake_petastorm["petastorm.unischema"]
    cod = fake_petastorm["petastorm.codecs"]
    schema = uni.Unischema("Old", [uni.UnischemaField("x", np.int32, (), None, False)])
    # old streams are protocol <= 2 with text-framed module names, which is what
    # made the reference's byte-level module rename possible (etl/legacy.py:38-45)
    blob = pickle.dumps(schema, protocol=0)
    blob = blob.replace(b"petastorm.unischema", b"av.ml.dataset_toolkit.unischema")
    blob = blob.replace(b"petastorm.codecs", b"av.ml.dataset_toolkit.codecs")
    out = interop.load_legacy_schema(blob)
    assert out.name == "Old" and out["x"].dtype == np.dtype("int32")
    assert cod is not None  # keep the fixture referenced


def test_decimal_and_dtype_instances(fake_petastorm):
    from decimal import Decimal

    uni = fake_petastorm["petastorm.unischema"]
    schema = uni.Unischema("D", [
        uni.UnischemaField("d", Decimal, (), None, False),
        uni.UnischemaField("f", np.dtype("float64"), (), None, False),
        uni.UnischemaField("s", np.dtype("U10"), (), None, False),
    ])
    out = interop.load_legacy_schema(pickle.dumps(schema))
    assert out["d"].dtype == np.dtype("object")
    assert out["f"].dtype == np.dtype("float64")
    assert out["s"].dtype == np.dtype("object")


# ---------------------------------------------------------------------------
# Restricted unpickler security
# ---------------------------------------------------------------------------

def test_unpickler_rejects_arbitrary_callables():
    import os

    with pytest.raises(pickle.UnpicklingError, match="disallowed"):
        interop._restricted_loads(pickle.dumps(os.system))


def test_unpickler_rejects_reduce_payloads():
    class Evil:
        def __reduce__(self):
            return (eval, ("1+1",))

    with pytest.raises(pickle.UnpicklingError, match="disallowed"):
        interop._restricted_loads(pickle.dumps(Evil()))


def test_unpickler_rejects_petastorm_named_classes_elsewhere():
    """A class *named* Unischema in an unrelated module must not resolve."""
    parent = types.ModuleType("evil")
    mod = types.ModuleType("evil.unischema")
    cls = type("Unischema", (object,), {"__module__": "evil.unischema"})
    mod.Unischema = cls
    parent.unischema = mod
    sys.modules["evil"] = parent
    sys.modules["evil.unischema"] = mod
    try:
        with pytest.raises(pickle.UnpicklingError, match="disallowed"):
            interop._restricted_loads(pickle.dumps(cls()))
    finally:
        del sys.modules["evil.unischema"]
        del sys.modules["evil"]


def test_non_unischema_payload_raises():
    with pytest.raises(MetadataError, match="expected a Unischema"):
        interop.load_legacy_schema(pickle.dumps({"not": "a schema"}))
