"""URL -> filesystem resolution.

Reference parity: petastorm/fs_utils.py (FilesystemResolver, fs_utils.py:39-196;
get_filesystem_and_path_or_paths fs_utils.py:199-228; normalize_dir_url fs_utils.py:231)
plus the HDFS namenode HA machinery (petastorm/hdfs/namenode.py) and gcsfs wrapper
(petastorm/gcsfs_helpers/).

TPU-first difference: GCS is the primary remote store for TPU pods, and modern
pyarrow.fs handles gs/s3/hdfs natively (the reference predates pyarrow.fs and had to
hand-roll libhdfs3 namenode resolution and gcsfs shims).  Resolution order:

1. no scheme or ``file://`` -> LocalFileSystem
2. ``hdfs://`` with a configured HA nameservice -> petastorm_tpu.hdfs failover
   client (python-level namenode resolution + reconnect, like the reference's
   HAHdfsClient); otherwise falls through to
3. ``pyarrow.fs.FileSystem.from_uri`` (gs, s3, plain hdfs - C++ implementations)
4. fsspec fallback wrapped in ``PyFileSystem(FSSpecHandler)`` for any other scheme

Everything returned is picklable-by-construction via ``FilesystemFactory`` so worker
processes can re-open the filesystem (reference: serializable ``filesystem_factory``,
fs_utils.py:42-196).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence, Tuple, Union
from urllib.parse import urlparse

import pyarrow.fs as pafs

#: serializes memory:// open+read pairs (the underlying MemoryFile has ONE
#: process-global seek position; see _IsolatedOpenHandler)
_ISOLATED_OPEN_LOCK = threading.Lock()


class _IsolatedOpenHandler(pafs.FSSpecHandler):
    """FSSpecHandler subclass giving each ``open_input_*`` an INDEPENDENT
    stream (a BytesIO snapshot of the file), for fsspec filesystems whose
    opens share one file object/seek position (the memory:// singleton).
    Everything else behaves exactly like FSSpecHandler."""

    def __init__(self, inner: "pafs.FSSpecHandler"):
        super().__init__(inner.fs)

    def _snapshot(self, path):
        import io

        import pyarrow as pa

        with _ISOLATED_OPEN_LOCK:
            f = self.fs.open(path, "rb")
            f.seek(0)
            data = f.read()
        return pa.PythonFile(io.BytesIO(data), mode="r")

    def open_input_file(self, path):
        return self._snapshot(path)

    def open_input_stream(self, path):
        return self._snapshot(path)

from petastorm_tpu.errors import PetastormTpuError

logger = logging.getLogger(__name__)


def normalize_dir_url(url: str) -> str:
    """Strip trailing slashes from a dataset directory URL (fs_utils.py:231)."""
    if not isinstance(url, str):
        raise PetastormTpuError(f"Dataset URL must be a string, got {type(url)}")
    return url.rstrip("/") if url != "/" else url


def get_filesystem_and_path(url: str,
                            storage_options: Optional[dict] = None,
                            filesystem: Optional[pafs.FileSystem] = None,
                            ) -> Tuple[pafs.FileSystem, str]:
    """Resolve a dataset URL to (pyarrow FileSystem, path-within-fs)."""
    url = normalize_dir_url(url)
    parsed = urlparse(url)
    if filesystem is not None:
        # match FileSystem.from_uri's path convention per scheme: bucket-based
        # stores (s3/gs) prefix the bucket, while an hdfs authority is a
        # host/nameservice and is NOT part of the path
        if parsed.scheme == "hdfs":
            path = parsed.path
        elif parsed.scheme:
            path = parsed.netloc + parsed.path
        else:
            path = url
        return filesystem, path
    if parsed.scheme in ("", "file"):
        return pafs.LocalFileSystem(), (parsed.path or url)
    if parsed.scheme == "hdfs":
        # logical HA nameservices resolve through the failover client; plain
        # host[:port] authorities and unconfigured environments fall through to
        # pyarrow's native hdfs (libhdfs reads the cluster config itself).
        # A RESOLVED nameservice whose namenodes all refuse connections is a
        # real outage: HdfsConnectError propagates (libhdfs would not fare
        # better, and falling through would bury the cause).
        from petastorm_tpu import hdfs as hdfs_ha

        namenodes = hdfs_ha.resolve_url_namenodes(url)
        if namenodes:
            return (hdfs_ha.connect_to_either_namenode(
                        namenodes, user=(storage_options or {}).get("user")),
                    parsed.path)
        logger.debug("%r is not a configured HA nameservice; using pyarrow"
                     " native hdfs", url)
    try:
        fs, path = pafs.FileSystem.from_uri(url)
        return fs, path
    except (OSError, ValueError, NotImplementedError) as exc:
        native_error = exc  # pa.ArrowInvalid subclasses ValueError
    try:
        import fsspec

        fs = fsspec.filesystem(parsed.scheme, **(storage_options or {}))
        handler = pafs.FSSpecHandler(fs)
        if parsed.scheme == "memory":
            # fsspec's memory filesystem hands EVERY concurrent open the
            # same MemoryFile object - a shared seek position, so two pool
            # workers reading one parquet file corrupt each other's reads
            # (footer reads land mid-file: "magic bytes not found").  Real
            # object stores open independent streams; give memory:// the
            # same semantics by serving each open an independent BytesIO
            # view of the bytes (test-sized data by definition).
            handler = _IsolatedOpenHandler(handler)
        return pafs.PyFileSystem(handler), parsed.netloc + parsed.path
    except Exception as fsspec_error:
        raise PetastormTpuError(
            f"Cannot resolve filesystem for {url!r}: pyarrow said"
            f" {native_error!r}; fsspec said {fsspec_error!r}") from native_error


def get_filesystem_and_path_or_paths(
        url_or_urls: Union[str, Sequence[str]],
        storage_options: Optional[dict] = None,
        filesystem: Optional[pafs.FileSystem] = None,
) -> Tuple[pafs.FileSystem, Union[str, list]]:
    """Resolve one URL or a homogeneous list of URLs (fs_utils.py:199-228).

    All URLs in a list must share scheme+authority (they are read by one FS).
    """
    if isinstance(url_or_urls, str):
        return get_filesystem_and_path(url_or_urls, storage_options, filesystem)
    urls = list(url_or_urls)
    if not urls:
        raise PetastormTpuError("Empty URL list")
    schemes = {(urlparse(u).scheme, urlparse(u).netloc) for u in urls}
    if len(schemes) > 1:
        raise PetastormTpuError(f"URLs must share scheme and authority, got {schemes}")
    fs, first = get_filesystem_and_path(urls[0], storage_options, filesystem)
    paths = [first] + [get_filesystem_and_path(u, storage_options, fs)[1] for u in urls[1:]]
    return fs, paths


class FilesystemFactory:
    """Picklable callable re-resolving the filesystem in a worker process.

    Reference: the serializable ``filesystem_factory`` closure (fs_utils.py:42-196) -
    pyarrow filesystems themselves may hold unpicklable native handles.

    When the user supplied an explicit ``filesystem`` (one that cannot be
    re-derived from the URL - credentialed S3, in-memory/mock fs), it is carried
    along and handed back verbatim; such readers require a thread/serial pool
    unless the filesystem object itself pickles.
    """

    def __init__(self, url: str, storage_options: Optional[dict] = None,
                 filesystem: Optional[pafs.FileSystem] = None):
        self._url = normalize_dir_url(url)
        self._storage_options = storage_options
        self._filesystem = filesystem

    def __call__(self) -> pafs.FileSystem:
        if self._filesystem is not None:
            return self._filesystem
        return get_filesystem_and_path(self._url, self._storage_options)[0]

    @property
    def url(self) -> str:
        """The dataset URL this factory re-resolves in worker processes."""
        return self._url
