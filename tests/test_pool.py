"""Executor pool tests across all flavors with stub workers.

Reference model: petastorm/workers_pool/tests/test_workers_pool.py:19-60 - one
shared test impl parametrized over pools; exception propagation; ventilator
semantics tested separately (test_ventilator.py).
"""

import queue
import time

import pytest

from petastorm_tpu.errors import ReaderClosedError
from petastorm_tpu.etl.metadata import RowGroupRef
from petastorm_tpu.plan import ReadPlan
from petastorm_tpu.pool import (SerialExecutor, ThreadedExecutor, Ventilator,
                                WorkerError, make_executor)
from petastorm_tpu.test_util.stub_workers import (ExplodingWorker, MultiplierWorker,
                                                  PidWorker, SleepyWorker)

ALL_KINDS = ["serial", "thread", "process"]
FAST_KINDS = ["serial", "thread"]


def _collect(executor, n, timeout=30):
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n:
        remaining = deadline - time.monotonic()
        assert remaining > 0, f"timed out with {len(out)}/{n} results"
        try:
            out.append(executor.get(timeout=min(remaining, 0.5)))
        except queue.Empty:
            continue
    return out


@pytest.mark.parametrize("kind", ALL_KINDS)
def test_roundtrip_all_flavors(kind):
    with make_executor(kind, workers_count=2) as ex:
        ex.start(MultiplierWorker(3))
        for i in range(10):
            ex.put(i)
        results = _collect(ex, 10)
    assert sorted(results) == [i * 3 for i in range(10)]


@pytest.mark.parametrize("kind", ALL_KINDS)
def test_exception_propagates(kind):
    with make_executor(kind, workers_count=2) as ex:
        ex.start(ExplodingWorker(trigger=3))
        for i in range(5):
            ex.put(i)
        with pytest.raises((WorkerError, RuntimeError)) as ei:
            _collect(ex, 5)
        assert "boom" in str(ei.value)


def test_process_pool_real_isolation():
    import os
    with make_executor("process", workers_count=2) as ex:
        ex.start(PidWorker())
        for i in range(4):
            ex.put(i)
        pids = set(_collect(ex, 4))
    assert os.getpid() not in pids
    assert 1 <= len(pids) <= 2


def test_thread_pool_parallelism():
    with ThreadedExecutor(workers_count=4) as ex:
        ex.start(SleepyWorker(0.05))
        t0 = time.monotonic()
        for i in range(8):
            ex.put(i)
        _collect(ex, 8)
        elapsed = time.monotonic() - t0
    assert elapsed < 8 * 0.05  # must overlap sleeps


def test_put_after_stop_raises():
    ex = SerialExecutor()
    ex.start(MultiplierWorker(1))
    ex.stop()
    with pytest.raises(ReaderClosedError):
        ex.put(1)


def test_diagnostics():
    with ThreadedExecutor(workers_count=2) as ex:
        ex.start(MultiplierWorker(1))
        ex.put(1)
        ex.get(timeout=5)
        d = ex.diagnostics
        assert d["ventilated"] == 1 and d["consumed"] == 1
        assert d["workers_count"] == 2


def test_workers_busy_heartbeat_names_stuck_item():
    """A wedged worker is attributable: diagnostics report (worker index,
    item ordinal, seconds stuck) while it is inside fn (RESULTS.md hang
    watch item -> stall diagnostics)."""
    import threading

    from petastorm_tpu.pool import VentilatedItem
    from petastorm_tpu.test_util.stub_workers import BlockingWorker

    release = threading.Event()
    with ThreadedExecutor(workers_count=2) as ex:
        ex.start(BlockingWorker(release, trigger=7))
        ex.put(VentilatedItem(7, 7))
        deadline = time.monotonic() + 10
        busy = []
        while time.monotonic() < deadline:
            busy = ex.diagnostics["workers_busy"]
            if busy:
                break
            time.sleep(0.02)
        assert busy, "stuck worker never appeared in workers_busy"
        (_idx, ordinal, stuck_s) = busy[0]
        assert ordinal == 7 and stuck_s >= 0
        release.set()
        got = ex.get(timeout=10)
        assert got.item == 7
        # after completion the heartbeat clears
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and ex.diagnostics["workers_busy"]:
            time.sleep(0.02)
        assert ex.diagnostics["workers_busy"] == []


def test_process_pool_workers_busy_heartbeat():
    """The heartbeat contract crosses the process boundary: a worker busy
    inside fn shows up in workers_busy with its item ordinal, via the
    lock-free shared slots (docs/operations.md stall diagnostics)."""
    from petastorm_tpu.pool import VentilatedItem, _ProcessExecutor
    from petastorm_tpu.test_util.stub_workers import SleepyWorker

    with _ProcessExecutor(workers_count=1) as ex:
        ex.start(SleepyWorker(4.0))
        ex.put(VentilatedItem(9, "x"))
        deadline = time.monotonic() + 30
        busy = []
        while time.monotonic() < deadline:
            busy = ex.diagnostics.get("workers_busy", [])
            if busy:
                break
            time.sleep(0.1)
        assert busy and busy[0][:2] == (0, 9) and busy[0][2] >= 0, busy
        got = ex.get(timeout=60)
        assert got.item == "x"
        # idle again once the result is delivered
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and ex.diagnostics.get("workers_busy")):
            time.sleep(0.05)
        assert ex.diagnostics.get("workers_busy") == []


def test_reader_stall_warns_and_aborts(tmp_path, monkeypatch, caplog):
    """A pipeline that stops producing results warns with the pipeline state
    and (with PETASTORM_TPU_STALL_ABORT_S) raises instead of wedging."""
    import logging
    import threading

    import numpy as np

    from petastorm_tpu import reader as reader_mod
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema
    from petastorm_tpu.transform import TransformSpec

    url = str(tmp_path / "ds")
    schema = Schema("S", [Field("x", np.int64)])
    write_dataset(url, schema, [{"x": i} for i in range(8)],
                  row_group_size_rows=4)

    release = threading.Event()

    def wedge(cols):
        release.wait()
        return cols

    monkeypatch.setattr(reader_mod, "_STALL_WARN_S", 0.3)
    monkeypatch.setattr(reader_mod, "_STALL_ABORT_S", 1.5)
    t0 = time.monotonic()
    try:
        with make_batch_reader(url, reader_pool_type="thread",
                               workers_count=1, shuffle_row_groups=False,
                               transform_spec=TransformSpec(wedge)) as r:
            with caplog.at_level(logging.WARNING,
                                 logger="petastorm_tpu.reader"):
                with pytest.raises(WorkerError) as ei:
                    next(iter(r.iter_batches()))
            assert "workers_busy" in str(ei.value)
            assert any("no batch" in rec.message for rec in caplog.records)
        # the exit above must NOT wedge on joining the still-blocked worker:
        # after a stall abort the executor join is bounded and abandons it
        # (daemonic), logging what it abandoned
        assert time.monotonic() - t0 < 30
    finally:
        release.set()  # let the abandoned daemon thread finish and exit


def _plan(n=6):
    rgs = [RowGroupRef(f"/f{i}", 0, 5, i) for i in range(n)]
    return ReadPlan(rgs, shuffle_row_groups=False)


def test_ventilator_single_epoch():
    with ThreadedExecutor(workers_count=2) as ex:
        ex.start(SleepyWorker(0))
        vent = Ventilator(ex, _plan(6), num_epochs=1)
        assert vent.total_items == 6
        vent.start()
        results = _collect(ex, 6)
        vent.join()
    assert len(results) == 6


def test_ventilator_multi_epoch():
    with ThreadedExecutor(workers_count=2) as ex:
        ex.start(SleepyWorker(0))
        vent = Ventilator(ex, _plan(4), num_epochs=3)
        assert vent.total_items == 12
        vent.start()
        results = _collect(ex, 12)
        vent.join()
    assert len(results) == 12


def test_ventilator_infinite_stops_cleanly():
    with ThreadedExecutor(workers_count=2) as ex:
        ex.start(SleepyWorker(0))
        vent = Ventilator(ex, _plan(4), num_epochs=None)
        assert vent.total_items is None
        vent.start()
        _collect(ex, 20)  # well past one epoch
        vent.stop()
        ex.stop()
        vent.join()


def test_ventilator_backpressure():
    # bounded in-queue: ventilator must not race ahead of consumption
    ex = ThreadedExecutor(workers_count=1, in_queue_size=2, results_queue_size=2)
    with ex:
        ex.start(SleepyWorker(0))
        vent = Ventilator(ex, _plan(50), num_epochs=1)
        vent.start()
        time.sleep(0.3)
        # at most in_queue(2) + results(2) + 1 in-hand can be in flight
        assert ex.diagnostics["ventilated"] <= 6
        _collect(ex, 50)
        vent.join()


def test_process_pool_hard_crash_surfaces_not_hangs():
    """A worker process dying WITHOUT a traceback (OOM-kill, segfault) must
    surface as a WorkerError at the consumer, not an indefinite hang
    (reference has no coverage for this; its zmq pool would wait forever)."""
    from petastorm_tpu.test_util.stub_workers import HardCrashWorker

    ex = make_executor("process", workers_count=2)
    try:
        ex.start(HardCrashWorker(trigger=7))
        for _ in range(4):   # both workers eventually eat a poison item
            ex.put(7)
        with pytest.raises(WorkerError, match="died"):
            _collect(ex, 4, timeout=60)
    finally:
        ex.stop()
        ex.join()
