// Native batched PNG/JPEG decode for the hot ingest path.
//
// Why this exists: every Python-side decoder available here (cv2.imdecode,
// PIL) holds the GIL for the whole decode, so the thread-pool ingest plane
// serializes on image decode (the dominant cost of the reference's
// CompressedImageCodec path, petastorm/codecs.py:92-101).  This shim decodes a
// whole column of encoded cells in one C call — ctypes releases the GIL for
// the call, and the batch can additionally fan out over an internal thread
// pool — writing straight into a preallocated contiguous numpy buffer (the
// exact layout ColumnBatch wants, no per-cell Python objects at all).
//
// C ABI only (no pybind11 in this image); loaded via ctypes (native/image.py).
// Output is always interleaved row-major uint8, RGB channel order for 3-channel
// images (stored streams are standard RGB files; reference parity with
// petastorm/codecs.py:96-101).

#include <atomic>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>

namespace {

// ---------------------------------------------------------------------------
// PNG via the full libpng 1.6 API (not the "simplified" one): full control
// over transforms and CRC policy.  Color-source -> grayscale-target uses
// png_set_rgb_to_gray(0.299, 0.587, 0.114) - the exact call OpenCV's PNG
// reader makes for IMREAD_GRAYSCALE - so native and cv2 fallback paths yield
// bit-identical tensors.  (The simplified API's PNG_FORMAT_GRAY uses libpng's
// default BT.709 + gamma handling, which differs by up to ~50/255.)
//
// In-stream CRC checking is skipped (PNG_CRC_QUIET_USE): inflate of
// incompressible image data is near-memcpy speed, leaving CRC as a large
// fraction of decode time.  Storage integrity is the parquet layer's job -
// the writer stamps page checksums (etl/writer.py) and the reader can verify
// them (make_reader(verify_checksums=True)); a decode-time CRC on every read
// would re-pay that cost on the hot path.
// ---------------------------------------------------------------------------
struct PngMemSrc {
  const uint8_t* data;
  size_t len;
  size_t pos;
};

void png_mem_read(png_structp png, png_bytep dst, png_size_t n) {
  PngMemSrc* s = static_cast<PngMemSrc*>(png_get_io_ptr(png));
  if (s->pos + n > s->len) {
    png_error(png, "read past end");
    return;
  }
  std::memcpy(dst, s->data + s->pos, n);
  s->pos += n;
}

// special setup() return: re-dispatch to the cv2-gray path (not an error)
constexpr int kPngRedirectGray = 1;

// Shared full-API read skeleton: open + mem source + CRC policy + dimension
// check, then the caller's transform setup (given the source color_type),
// then rowbytes validation and the row read.  Any libpng error longjmps to
// the setjmp here and returns -5.
template <typename SetupFn>
int read_png(const uint8_t* src, size_t len, uint8_t* out, int height,
             int width, size_t stride, SetupFn setup) {
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr,
                                           nullptr, nullptr);
  if (!png) return -2;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return -2;
  }
  // fully built before setjmp: longjmp must not skip over mutations of
  // non-volatile locals
  std::vector<png_bytep> rows(height);
  for (int y = 0; y < height; ++y) rows[y] = out + (size_t)y * stride;
  int rc = 0;
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return -5;
  }
  PngMemSrc mem{src, len, 0};
  png_set_read_fn(png, &mem, png_mem_read);
  png_set_crc_action(png, PNG_CRC_QUIET_USE, PNG_CRC_QUIET_USE);
  png_read_info(png, info);
  if ((int)png_get_image_width(png, info) != width ||
      (int)png_get_image_height(png, info) != height) {
    png_destroy_read_struct(&png, &info, nullptr);
    return -3;
  }
  rc = setup(png, png_get_color_type(png, info));
  if (rc != 0) {
    png_destroy_read_struct(&png, &info, nullptr);
    return rc;
  }
  (void)png_set_interlace_handling(png);
  png_read_update_info(png, info);
  if (png_get_rowbytes(png, info) != stride) {
    png_destroy_read_struct(&png, &info, nullptr);
    return -4;
  }
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  return 0;
}

int decode_png_gray_cv2(const uint8_t* src, size_t len, uint8_t* out,
                        int height, int width) {
  return read_png(src, len, out, height, width, (size_t)width,
                  [](png_structp png, png_byte) {
                    png_set_expand(png);    // palette->rgb, low-bit gray->8
                    png_set_strip_16(png);  // 16-bit->8-bit
                    png_set_strip_alpha(png);
                    // (red, green) weights; blue = 1 - red - green = 0.114
                    png_set_rgb_to_gray(png, PNG_ERROR_ACTION_NONE, 0.299,
                                        0.587);
                    return 0;
                  });
}

int decode_png(const uint8_t* src, size_t len, uint8_t* out, int height,
               int width, int channels) {
  if (channels != 1 && channels != 3 && channels != 4) return -4;
  int rc = read_png(
      src, len, out, height, width, (size_t)width * channels,
      [channels](png_structp png, png_byte color_type) {
        if (channels == 1 && (color_type & PNG_COLOR_MASK_COLOR))
          return kPngRedirectGray;  // needs cv2-matching gray weights
        png_set_expand(png);    // palette->rgb, low-bit gray->8, tRNS->alpha
        png_set_strip_16(png);  // 16-bit->8-bit
        if (channels >= 3) png_set_gray_to_rgb(png);
        if (channels == 4) {
          if (!(color_type & PNG_COLOR_MASK_ALPHA))
            png_set_add_alpha(png, 0xFF, PNG_FILLER_AFTER);
        } else {
          png_set_strip_alpha(png);
        }
        return 0;
      });
  if (rc == kPngRedirectGray)
    return decode_png_gray_cv2(src, len, out, height, width);
  return rc;
}

// ---------------------------------------------------------------------------
// JPEG via libjpeg with setjmp error trap (libjpeg's error model).
// ---------------------------------------------------------------------------
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jb, 1);
}

int decode_jpeg(const uint8_t* src, size_t len, uint8_t* out, int height,
                int width, int channels) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(src), len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -3;
  }
  cinfo.out_color_space = (channels == 1) ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if ((int)cinfo.output_width != width || (int)cinfo.output_height != height ||
      (int)cinfo.output_components != channels) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -4;
  }
  const size_t stride = (size_t)width * channels;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + (size_t)cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int decode_one(const uint8_t* src, size_t len, uint8_t* out, int height,
               int width, int channels) {
  if (len >= 8 && src[0] == 0x89 && src[1] == 'P' && src[2] == 'N' &&
      src[3] == 'G')
    return decode_png(src, len, out, height, width, channels);
  if (len >= 2 && src[0] == 0xFF && src[1] == 0xD8)
    return decode_jpeg(src, len, out, height, width, channels);
  return -1;  // unknown magic
}

// ---------------------------------------------------------------------------
// ROI (partial) decode: augment-crop pipelines keep only a (crop_h, crop_w)
// window, so decoding the full image just to throw most of it away wastes the
// dominant ingest cost.  Both codecs are sequential-scanline formats, so the
// honest savings are: rows BELOW the crop are never entropy-decoded or
// IDCT'd/inflated (the decode aborts after the last needed scanline), rows
// ABOVE it are decoded into a small discard buffer (required by the stream
// format - plain libjpeg has no jpeg_skip_scanlines; with libjpeg-turbo that
// could skip their IDCT too), and only the crop's columns are copied to the
// output.  For a centered/random crop this cuts roughly half the row work
// plus the full-image copy; the output is byte-identical to slicing a full
// decode (same decoder, same rows).
// ---------------------------------------------------------------------------

int decode_jpeg_roi(const uint8_t* src, size_t len, uint8_t* out, int height,
                    int width, int channels, int crop_y, int crop_x,
                    int crop_h, int crop_w) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  // heap buffers built before setjmp (longjmp must not skip destructors)
  std::vector<uint8_t> rowbuf;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(src), len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -3;
  }
  cinfo.out_color_space = (channels == 1) ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if ((int)cinfo.output_width != width || (int)cinfo.output_height != height ||
      (int)cinfo.output_components != channels) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -4;
  }
  const size_t full_stride = (size_t)width * channels;
  const size_t out_stride = (size_t)crop_w * channels;
  rowbuf.resize(full_stride);
  const int last = crop_y + crop_h;  // first row we do NOT need
  while ((int)cinfo.output_scanline < last) {
    int y = (int)cinfo.output_scanline;
    JSAMPROW row = rowbuf.data();
    jpeg_read_scanlines(&cinfo, &row, 1);
    if (y >= crop_y)
      std::memcpy(out + (size_t)(y - crop_y) * out_stride,
                  rowbuf.data() + (size_t)crop_x * channels, out_stride);
  }
  // rows below the crop are never decoded: abort skips straight to cleanup
  jpeg_abort_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int decode_png_roi(const uint8_t* src, size_t len, uint8_t* out, int height,
                   int width, int channels, int crop_y, int crop_x,
                   int crop_h, int crop_w) {
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr,
                                           nullptr, nullptr);
  if (!png) return -2;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return -2;
  }
  std::vector<uint8_t> rowbuf;
  std::vector<uint8_t> full;     // interlaced fallback only
  std::vector<png_bytep> rows;   // interlaced fallback only
  bool redirect_gray = false;
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return -5;
  }
  PngMemSrc mem{src, len, 0};
  png_set_read_fn(png, &mem, png_mem_read);
  png_set_crc_action(png, PNG_CRC_QUIET_USE, PNG_CRC_QUIET_USE);
  png_read_info(png, info);
  if ((int)png_get_image_width(png, info) != width ||
      (int)png_get_image_height(png, info) != height) {
    png_destroy_read_struct(&png, &info, nullptr);
    return -3;
  }
  png_byte color_type = png_get_color_type(png, info);
  if (channels == 1 && (color_type & PNG_COLOR_MASK_COLOR)) {
    // needs the cv2-matching gray weights path; handled by the caller via a
    // full gray decode + crop (rare: color stream into a grayscale field)
    redirect_gray = true;
  } else {
    png_set_expand(png);
    png_set_strip_16(png);
    if (channels >= 3) png_set_gray_to_rgb(png);
    if (channels == 4) {
      if (!(color_type & PNG_COLOR_MASK_ALPHA))
        png_set_add_alpha(png, 0xFF, PNG_FILLER_AFTER);
    } else {
      png_set_strip_alpha(png);
    }
  }
  if (redirect_gray) {
    png_destroy_read_struct(&png, &info, nullptr);
    return kPngRedirectGray;
  }
  const bool interlaced =
      png_get_interlace_type(png, info) != PNG_INTERLACE_NONE;
  (void)png_set_interlace_handling(png);
  png_read_update_info(png, info);
  const size_t full_stride = (size_t)width * channels;
  const size_t out_stride = (size_t)crop_w * channels;
  if (png_get_rowbytes(png, info) != full_stride) {
    png_destroy_read_struct(&png, &info, nullptr);
    return -4;
  }
  if (interlaced) {
    // Adam7 delivers every row on every pass: no early-out is possible, so
    // decode whole rows and crop afterwards (correctness over savings)
    full.resize(full_stride * height);
    rows.resize(height);
    for (int y = 0; y < height; ++y) rows[y] = full.data() + y * full_stride;
    png_read_image(png, rows.data());
    for (int y = 0; y < crop_h; ++y)
      std::memcpy(out + (size_t)y * out_stride,
                  full.data() + (size_t)(crop_y + y) * full_stride
                      + (size_t)crop_x * channels,
                  out_stride);
  } else {
    rowbuf.resize(full_stride);
    const int last = crop_y + crop_h;
    for (int y = 0; y < last; ++y) {
      png_read_row(png, rowbuf.data(), nullptr);
      if (y >= crop_y)
        std::memcpy(out + (size_t)(y - crop_y) * out_stride,
                    rowbuf.data() + (size_t)crop_x * channels, out_stride);
    }
    // rows below the crop are never inflated: destroy without png_read_end
  }
  png_destroy_read_struct(&png, &info, nullptr);
  return 0;
}

int decode_one_roi(const uint8_t* src, size_t len, uint8_t* out, int height,
                   int width, int channels, int crop_y, int crop_x,
                   int crop_h, int crop_w) {
  if (crop_y < 0 || crop_x < 0 || crop_h < 1 || crop_w < 1 ||
      crop_y + crop_h > height || crop_x + crop_w > width)
    return -8;  // crop outside the image
  if (crop_y == 0 && crop_x == 0 && crop_h == height && crop_w == width)
    return decode_one(src, len, out, height, width, channels);
  if (len >= 8 && src[0] == 0x89 && src[1] == 'P' && src[2] == 'N' &&
      src[3] == 'G') {
    int rc = decode_png_roi(src, len, out, height, width, channels, crop_y,
                            crop_x, crop_h, crop_w);
    if (rc == kPngRedirectGray) {
      // color->gray needs the weighted transform over full rows: decode the
      // full gray image to a scratch buffer, then crop (rare path)
      std::vector<uint8_t> scratch((size_t)height * width);
      rc = decode_png_gray_cv2(src, len, scratch.data(), height, width);
      if (rc != 0) return rc;
      for (int y = 0; y < crop_h; ++y)
        std::memcpy(out + (size_t)y * crop_w,
                    scratch.data() + (size_t)(crop_y + y) * width + crop_x,
                    (size_t)crop_w);
    }
    return rc;
  }
  if (len >= 2 && src[0] == 0xFF && src[1] == 0xD8)
    return decode_jpeg_roi(src, len, out, height, width, channels, crop_y,
                           crop_x, crop_h, crop_w);
  return -1;  // unknown magic
}

// ---------------------------------------------------------------------------
// Hybrid JPEG decode, host half: entropy (Huffman) decode only, no IDCT.
// jpeg_read_coefficients stops after the entropy decoder, yielding quantized
// DCT coefficient blocks; the FLOP-heavy rest (dequant + 8x8 IDCT + chroma
// upsample + YCbCr->RGB) runs on the TPU as batched matmuls
// (petastorm_tpu/ops/jpeg.py).  Coefficient blocks and quant tables are both
// in natural (row-major) order - libjpeg un-zigzags during entropy decode.
// ---------------------------------------------------------------------------

constexpr int kJpegMaxComps = 4;

// meta layout (int32): [ncomp, width, height,
//   then per component (kJpegMaxComps slots):
//   h_samp, v_samp, blocks_w, blocks_h]
constexpr int kJpegMetaLen = 3 + 4 * kJpegMaxComps;

int jpeg_coef_open(jpeg_decompress_struct* cinfo, JpegErr* jerr,
                   const uint8_t* src, size_t len) {
  cinfo->err = jpeg_std_error(&jerr->mgr);
  jerr->mgr.error_exit = jpeg_err_exit;
  jpeg_create_decompress(cinfo);
  jpeg_mem_src(cinfo, const_cast<unsigned char*>(src), len);
  if (jpeg_read_header(cinfo, TRUE) != JPEG_HEADER_OK) return -3;
  if (cinfo->num_components < 1 || cinfo->num_components > kJpegMaxComps)
    return -4;
  return 0;
}

}  // namespace

extern "C" {

// Probe geometry without entropy-decoding.  Returns 0 and fills meta
// (kJpegMetaLen int32s) on success.
int pst_jpeg_coef_layout(const uint8_t* src, uint64_t len, int32_t* meta) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  int rc = jpeg_coef_open(&cinfo, &jerr, src, (size_t)len);
  if (rc != 0) {
    jpeg_destroy_decompress(&cinfo);
    return rc;
  }
  // block geometry comes from the coefficient-access path; compute the same
  // values jpeg_read_coefficients would without running entropy decode
  meta[0] = cinfo.num_components;
  meta[1] = (int32_t)cinfo.image_width;
  meta[2] = (int32_t)cinfo.image_height;
  for (int c = 0; c < cinfo.num_components; ++c) {
    jpeg_component_info* ci = &cinfo.comp_info[c];
    int32_t* m = meta + 3 + 4 * c;
    m[0] = ci->h_samp_factor;
    m[1] = ci->v_samp_factor;
    // ceil(comp_width/8), comp_width = ceil(image_width * h_samp / max_h / 1)
    long cw = ((long)cinfo.image_width * ci->h_samp_factor +
               cinfo.max_h_samp_factor - 1) / cinfo.max_h_samp_factor;
    long ch = ((long)cinfo.image_height * ci->v_samp_factor +
               cinfo.max_v_samp_factor - 1) / cinfo.max_v_samp_factor;
    m[2] = (int32_t)((cw + 7) / 8);
    m[3] = (int32_t)((ch + 7) / 8);
  }
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Entropy-decode coefficients.  outs[c] must hold blocks_h*blocks_w*64
// int16s (natural order within each block); qtabs must hold
// num_components*64 uint16s (natural order).  When expected_meta is non-null
// the image's geometry must match it exactly (batch-stacking contract).
static int jpeg_read_coefs_one(const uint8_t* src, uint64_t len,
                               int16_t* const* outs, uint16_t* qtabs,
                               const int32_t* expected_meta) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  int rc = jpeg_coef_open(&cinfo, &jerr, src, (size_t)len);
  if (rc != 0) {
    jpeg_destroy_decompress(&cinfo);
    return rc;
  }
  if (expected_meta &&
      (expected_meta[0] != cinfo.num_components ||
       expected_meta[1] != (int32_t)cinfo.image_width ||
       expected_meta[2] != (int32_t)cinfo.image_height)) {
    jpeg_destroy_decompress(&cinfo);
    return -7;  // geometry mismatch within a batch
  }
  jvirt_barray_ptr* barrays = jpeg_read_coefficients(&cinfo);
  if (!barrays) {
    jpeg_destroy_decompress(&cinfo);
    return -5;
  }
  for (int c = 0; c < cinfo.num_components; ++c) {
    jpeg_component_info* ci = &cinfo.comp_info[c];
    if (!ci->quant_table) {
      jpeg_destroy_decompress(&cinfo);
      return -6;
    }
    if (expected_meta) {
      const int32_t* m = expected_meta + 3 + 4 * c;
      if (m[0] != ci->h_samp_factor || m[1] != ci->v_samp_factor ||
          m[2] != (int32_t)ci->width_in_blocks ||
          m[3] != (int32_t)ci->height_in_blocks) {
        jpeg_destroy_decompress(&cinfo);
        return -7;
      }
    }
    for (int k = 0; k < DCTSIZE2; ++k)
      qtabs[c * DCTSIZE2 + k] = ci->quant_table->quantval[k];
    const JDIMENSION bw = ci->width_in_blocks;
    const JDIMENSION bh = ci->height_in_blocks;
    int16_t* dst = outs[c];
    for (JDIMENSION row = 0; row < bh; ++row) {
      JBLOCKARRAY rows = (*cinfo.mem->access_virt_barray)(
          (j_common_ptr)&cinfo, barrays[c], row, 1, FALSE);
      static_assert(sizeof(JCOEF) == sizeof(int16_t), "JCOEF must be int16");
      std::memcpy(dst + (size_t)row * bw * DCTSIZE2, rows[0],
                  (size_t)bw * DCTSIZE2 * sizeof(int16_t));
    }
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int pst_jpeg_read_coefs(const uint8_t* src, uint64_t len,
                        int16_t* const* outs, uint16_t* qtabs) {
  return jpeg_read_coefs_one(src, len, outs, qtabs, nullptr);
}

// Batched entropy decode in ONE GIL-released call.  outs[c] points to a
// stacked (n, blocks_h, blocks_w, 64) int16 array whose per-image stride is
// plane_strides[c] int16 elements; qtabs holds n*ncomp*64 uint16s; meta is
// the kJpegMetaLen layout every image must match.  Returns 0, or (1 + index)
// of the first failing image.
int pst_jpeg_coef_batch(const uint8_t* const* srcs, const uint64_t* lens,
                        int n, int16_t* const* outs,
                        const uint64_t* plane_strides, uint16_t* qtabs,
                        const int32_t* meta, int nthreads) {
  const int ncomp = meta[0];
  std::atomic<int> failed{0};
  auto run = [&](int lo, int hi) {
    std::vector<int16_t*> dsts(ncomp);
    for (int i = lo; i < hi; ++i) {
      if (failed.load(std::memory_order_relaxed)) return;
      for (int c = 0; c < ncomp; ++c)
        dsts[c] = outs[c] + (uint64_t)i * plane_strides[c];
      int rc = jpeg_read_coefs_one(srcs[i], lens[i], dsts.data(),
                                   qtabs + (size_t)i * ncomp * DCTSIZE2, meta);
      if (rc != 0) {
        int expected = 0;
        failed.compare_exchange_strong(expected, 1 + i);
        return;
      }
    }
  };
  if (nthreads <= 1 || n <= 1) {
    run(0, n);
  } else {
    int workers = nthreads < n ? nthreads : n;
    std::vector<std::thread> threads;
    threads.reserve(workers);
    int chunk = (n + workers - 1) / workers;
    for (int w = 0; w < workers; ++w) {
      int lo = w * chunk;
      int hi = lo + chunk < n ? lo + chunk : n;
      if (lo >= hi) break;
      threads.emplace_back(run, lo, hi);
    }
    for (auto& t : threads) t.join();
  }
  return failed.load();
}

}  // extern "C"

namespace {

}  // namespace

extern "C" {

// Decode n images into out (contiguous, one image every `stride` bytes).
// srcs[i] = pointer to encoded stream i of length lens[i].  All images must
// decode to exactly (height, width, channels) uint8.  nthreads <= 1 decodes
// inline; otherwise an internal thread pool splits the batch.
// Returns 0 on success, or (1 + index) of the first failing image.
int pst_decode_image_batch(const uint8_t* const* srcs, const uint64_t* lens,
                           int n, uint8_t* out, uint64_t stride, int height,
                           int width, int channels, int nthreads) {
  std::atomic<int> failed{0};  // 1 + index of first failure, 0 = ok
  auto run = [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      if (failed.load(std::memory_order_relaxed)) return;
      int rc = decode_one(srcs[i], (size_t)lens[i], out + (uint64_t)i * stride,
                          height, width, channels);
      if (rc != 0) {
        int expected = 0;
        failed.compare_exchange_strong(expected, 1 + i);
        return;
      }
    }
  };
  if (nthreads <= 1 || n <= 1) {
    run(0, n);
  } else {
    int workers = nthreads < n ? nthreads : n;
    std::vector<std::thread> threads;
    threads.reserve(workers);
    int chunk = (n + workers - 1) / workers;
    for (int w = 0; w < workers; ++w) {
      int lo = w * chunk;
      int hi = lo + chunk < n ? lo + chunk : n;
      if (lo >= hi) break;
      threads.emplace_back(run, lo, hi);
    }
    for (auto& t : threads) t.join();
  }
  return failed.load();
}

// Single-image probe used by tests and the per-cell fallback.
int pst_decode_image(const uint8_t* src, uint64_t len, uint8_t* out, int height,
                     int width, int channels) {
  return decode_one(src, (size_t)len, out, height, width, channels);
}

// Batched ROI decode: like pst_decode_image_batch, but each image i decodes
// only its (crop_h, crop_w) window anchored at (crop_ys[i], crop_xs[i]) -
// out rows are (crop_h, crop_w, channels), one every `stride` bytes.  Every
// stream must still decode to exactly (height, width, channels); the crop
// need not be 8x8-block aligned (the copy is scanline-level, so the result
// is byte-identical to slicing a full decode).  Returns 0, or (1 + index)
// of the first failing image.
int pst_decode_image_batch_roi(const uint8_t* const* srcs,
                               const uint64_t* lens, int n, uint8_t* out,
                               uint64_t stride, int height, int width,
                               int channels, const int32_t* crop_ys,
                               const int32_t* crop_xs, int crop_h, int crop_w,
                               int nthreads) {
  std::atomic<int> failed{0};
  auto run = [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      if (failed.load(std::memory_order_relaxed)) return;
      int rc = decode_one_roi(srcs[i], (size_t)lens[i],
                              out + (uint64_t)i * stride, height, width,
                              channels, crop_ys[i], crop_xs[i], crop_h,
                              crop_w);
      if (rc != 0) {
        int expected = 0;
        failed.compare_exchange_strong(expected, 1 + i);
        return;
      }
    }
  };
  if (nthreads <= 1 || n <= 1) {
    run(0, n);
  } else {
    int workers = nthreads < n ? nthreads : n;
    std::vector<std::thread> threads;
    threads.reserve(workers);
    int chunk = (n + workers - 1) / workers;
    for (int w = 0; w < workers; ++w) {
      int lo = w * chunk;
      int hi = lo + chunk < n ? lo + chunk : n;
      if (lo >= hi) break;
      threads.emplace_back(run, lo, hi);
    }
    for (auto& t : threads) t.join();
  }
  return failed.load();
}

}  // extern "C"
