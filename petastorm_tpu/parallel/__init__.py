"""Mesh/process-aware sharding helpers (the only layer that talks to jax.distributed).

Reference parity: the reference's distributed story is externally-supplied rank
(reader.py:508) plus Horovod/MPI env sniffing (spark_dataset_converter.py:124-163).
Here shard assignment is derived from the JAX runtime itself.
"""

from petastorm_tpu.parallel.mesh import (data_parallel_mesh, local_data_slice,
                                         shard_options_from_jax, sharding_for_batch)
from petastorm_tpu.parallel.write import distributed_write_dataset

__all__ = ["data_parallel_mesh", "shard_options_from_jax", "sharding_for_batch",
           "local_data_slice", "distributed_write_dataset"]
