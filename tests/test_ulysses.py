"""Ulysses (all-to-all) sequence parallelism on the virtual 8-device mesh.

The second context-parallel strategy beside ring attention
(tests/test_ring_attention.py): two all_to_all collectives re-shard
sequence<->heads around dense local attention.  Same loader delivery
contract, same exactness bar (matches replicated full attention).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from petastorm_tpu.ops.ulysses import ulysses_attention


def _mesh(data=2, seq=4):
    devs = np.asarray(jax.devices()[:data * seq]).reshape(data, seq)
    return Mesh(devs, ("data", "seq"))


def _reference_attention(q, k, v, causal, scale=None):
    d = q.shape[-1]
    scale = scale or 1.0 / (d ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s = q.shape[2]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, axis=-1), v)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(causal):
    mesh = _mesh()
    rng = np.random.default_rng(0)
    b, h, s, d = 2, 4, 32, 16  # h divisible by seq axis size 4
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
               for _ in range(3))
    out = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
    ref = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_matches_ring_attention():
    from petastorm_tpu.ops.ring_attention import ring_attention

    mesh = _mesh()
    rng = np.random.default_rng(2)
    b, h, s, d = 2, 4, 32, 8
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
               for _ in range(3))
    u = ulysses_attention(q, k, v, mesh=mesh, causal=True)
    r = ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(u), np.asarray(r),
                               rtol=2e-5, atol=2e-5)


def test_differentiable():
    mesh = _mesh()
    rng = np.random.default_rng(1)
    b, h, s, d = 2, 4, 16, 8
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
               for _ in range(3))

    def loss_u(q, k, v):
        return ulysses_attention(q, k, v, mesh=mesh, causal=True).sum()

    def loss_ref(q, k, v):
        return _reference_attention(q, k, v, True).sum()

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_u, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_indivisible_heads_rejected():
    mesh = _mesh()
    rng = np.random.default_rng(3)
    q = k = v = jnp.asarray(rng.standard_normal((2, 3, 32, 8)), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh=mesh)


def test_loader_feeds_ulysses_end_to_end(tmp_path):
    """Sequence-sharded loader delivery -> embedding -> ulysses attention."""
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema

    mesh = _mesh()
    seq_len, vocab, heads, hdim = 32, 64, 4, 8
    rng = np.random.default_rng(4)
    url = str(tmp_path / "seqs")
    write_dataset(url, Schema("S", [Field("tokens", np.int32, (seq_len,))]),
                  [{"tokens": rng.integers(0, vocab, seq_len).astype(np.int32)}
                   for _ in range(16)], row_group_size_rows=8)
    emb = jnp.asarray(rng.standard_normal((vocab, heads * hdim)), jnp.float32)

    def apply(tokens):
        b, s = tokens.shape
        x = emb[tokens].reshape(b, s, heads, hdim).transpose(0, 2, 1, 3)
        return ulysses_attention(x, x, x, mesh=mesh, causal=True)

    with make_batch_reader(url, shuffle_row_groups=False, num_epochs=1) as r:
        with JaxDataLoader(r, batch_size=8, mesh=mesh,
                           shardings={"tokens": P("data", "seq")}) as loader:
            batch = next(iter(loader))
            out = jax.jit(apply)(batch["tokens"])
    assert out.shape == (8, heads, seq_len, hdim)
    assert np.isfinite(np.asarray(out)).all()


def test_bf16_inputs_match_ring_numerics():
    """Softmax accumulates in float32 for both CP strategies, so swapping one
    for the other must not change bf16 training numerics."""
    from petastorm_tpu.ops.ring_attention import ring_attention

    mesh = _mesh()
    rng = np.random.default_rng(5)
    b, h, s, d = 2, 4, 32, 8
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
               for _ in range(3))
    u = ulysses_attention(q, k, v, mesh=mesh, causal=True)
    r = ring_attention(q, k, v, mesh=mesh, causal=True)
    assert u.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(u, dtype=np.float32),
                               np.asarray(r, dtype=np.float32),
                               rtol=2e-2, atol=2e-2)
