"""Read-through caches for decoded rowgroup batches.

Reference parity: petastorm/cache.py (CacheBase.get contract, cache.py:20-33;
NullCache cache.py:35-39) and petastorm/local_disk_cache.py (LocalDiskCache over
diskcache.FanoutCache, local_disk_cache.py:22-63).

Difference: ``diskcache`` is not a dependency - LocalDiskCache here is a small
self-contained file-per-key store (sha1-named pickle files, best-effort LRU eviction
by mtime against a size cap).  Entries are whole decoded *columnar batches*, not
rows, so a hit skips parquet IO + decode for an entire rowgroup.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import tempfile
from abc import ABC, abstractmethod
from typing import Any, Callable

from petastorm_tpu.telemetry import resolve as _resolve_telemetry

logger = logging.getLogger(__name__)

_MISSING = object()  # sentinel: cache miss vs a legitimately-None entry


class CacheBase(ABC):
    #: True when the cache may hold REFERENCES to values it served or was
    #: filled with (vs private copies / serialized bytes).  The worker only
    #: arms arena batch-slot decode (decode output allocated directly in the
    #: process-pool transport's shared memory) when this is False - a cache
    #: retaining a reference to a slot-backed array would serve a dangling
    #: view after the consumer frees the block.  Conservative default for
    #: unknown subclasses; every cache in this module stores copies.
    retains_value_references = True

    @abstractmethod
    def get(self, key: str, fill_cache_func: Callable[[], Any]) -> Any:
        """Return cached value or compute+store via ``fill_cache_func``."""

    def cleanup(self) -> None:
        """Release the cache's resources (files, memory); the cache is
        unusable afterwards.  No-op by default."""
        pass

    def _record_lookup(self, hit: bool) -> None:
        """Count a get() as cache.hits / cache.misses (no-op recorder by
        default; see petastorm_tpu.telemetry)."""
        tele = getattr(self, "_telemetry", None)
        if tele is not None and tele.enabled:
            tele.counter("cache.hits" if hit else "cache.misses").add(1)

    def __getstate__(self):
        # a live Telemetry is not picklable (locks, trace buffer); the
        # process-pool worker's copy re-resolves from its own env
        state = dict(self.__dict__)
        state.pop("_telemetry", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._telemetry = _resolve_telemetry(None)


class NullCache(CacheBase):
    """No-op cache (reference cache.py:35-39)."""

    retains_value_references = False  # retains nothing at all

    def get(self, key: str, fill_cache_func: Callable[[], Any]) -> Any:
        return fill_cache_func()


class InMemoryCache(CacheBase):
    """Process-local LRU cache of decoded batches, capped by estimated bytes.

    No reference analog (its only cache is disk-backed) - on TPU host VMs with
    hundreds of GB of RAM, caching decoded columnar batches in memory turns
    repeated epochs over medium datasets into pure memory traffic (no parquet
    IO, no decode).  Size accounting uses ``ColumnBatch`` array nbytes when
    available, else ``sys.getsizeof``.
    """

    # both directions cross through _copy_value: stored entries and served
    # hits are private copies, never references to pipeline arrays
    retains_value_references = False

    def __init__(self, size_limit_bytes: int = 4 * 2 ** 30, telemetry=None):
        from collections import OrderedDict as _OD

        self._entries: "_OD[str, Any]" = _OD()
        self._sizes: dict = {}
        self._size_limit = size_limit_bytes
        self._total = 0
        self._telemetry = _resolve_telemetry(telemetry)
        import threading

        self._lock = threading.Lock()

    @staticmethod
    def _array_size(col: Any) -> int:
        import sys as _sys

        import numpy as _np

        if isinstance(col, _np.ndarray):
            if col.dtype == object:
                # nbytes counts 8 bytes/pointer for object arrays; sum the
                # payloads (ragged/variable-shape cells) or the cap is a no-op
                return int(col.nbytes) + sum(
                    int(c.nbytes) if isinstance(c, _np.ndarray)
                    else _sys.getsizeof(c) for c in col.ravel())
            return int(col.nbytes)
        return _sys.getsizeof(col)

    @classmethod
    def _estimate_size(cls, value: Any) -> int:
        import sys as _sys

        columns = getattr(value, "columns", None)
        if isinstance(columns, dict):
            return sum(cls._array_size(col) for col in columns.values())
        return _sys.getsizeof(value)

    @staticmethod
    def _copy_value(value: Any) -> Any:
        """Defensive copy so in-place consumer mutations (e.g. a TransformSpec
        normalizing pixels in place) cannot corrupt cached entries - disk
        caches get this isolation for free from their pickle round-trip."""
        import copy as _copy

        import numpy as _np

        def _copy_col(c):
            if isinstance(c, _np.ndarray):
                if c.dtype == object:
                    # .copy() on an object array copies pointers only; the
                    # cells themselves must be duplicated
                    out = _np.empty(len(c), dtype=object)
                    for i, cell in enumerate(c):
                        out[i] = cell.copy() if isinstance(cell, _np.ndarray) else cell
                    return out
                return c.copy()
            return _copy.deepcopy(c)

        columns = getattr(value, "columns", None)
        if isinstance(columns, dict):
            copied = {n: _copy_col(c) for n, c in columns.items()}
            return type(value)(copied, getattr(value, "num_rows", len(value)))
        return _copy.deepcopy(value)

    def get(self, key: str, fill_cache_func: Callable[[], Any]) -> Any:
        # copy OUTSIDE the lock: entries are immutable once stored (eviction
        # only drops references), and the defensive copy of a big image batch
        # is exactly the work that must not serialize all pool workers
        with self._lock:
            entry = self._entries.get(key, _MISSING)
            if entry is not _MISSING:
                self._entries.move_to_end(key)
        self._record_lookup(entry is not _MISSING)
        if entry is not _MISSING:
            return self._copy_value(entry)
        value = fill_cache_func()
        size = self._estimate_size(value)
        if size > self._size_limit:
            return value  # single entry over the cap: serve uncached
        stored = self._copy_value(value)
        with self._lock:
            if key not in self._entries:
                self._entries[key] = stored
                self._sizes[key] = size
                self._total += size
                while self._total > self._size_limit and len(self._entries) > 1:
                    old_key, _ = self._entries.popitem(last=False)
                    self._total -= self._sizes.pop(old_key)
        return value

    def cleanup(self) -> None:
        with self._lock:
            self._entries.clear()
            self._sizes.clear()
            self._total = 0


class LocalDiskCache(CacheBase):
    """File-per-key pickle cache with a byte-size cap.

    Reference semantics (local_disk_cache.py:22-63): persistent across runs unless
    ``cleanup()`` is called; sized eviction.  Keys are hashed, so any string key
    works.  Safe under concurrent MULTI-PROCESS readers/writers sharing one
    directory (the shared warm tier's L2, docs/operations.md "Warm cache"):
    entries appear atomically (temp-file + rename), in-flight ``.tmp`` files
    are never evicted young (a partner deleting a writer's temp would fail
    the writer's rename) but ARE swept once orphan-aged (a crashed writer
    must not leak them forever), and every path tolerates a partner having
    deleted the entry first.
    """

    # values cross a pickle round-trip in both directions: nothing served or
    # stored aliases a pipeline array (batch-slot decode stays armed)
    retains_value_references = False

    #: a ``.tmp`` older than this is a crashed writer's orphan: evictable
    ORPHAN_TMP_S = 300.0
    #: stores between full eviction sweeps (the sweep lists + stats the whole
    #: directory - O(entries); per-store it would put a linear scan on every
    #: cold-decode miss and go quadratic over a cold epoch).  The cap may
    #: overshoot by up to SWEEP_EVERY entries between sweeps - it is
    #: best-effort by contract.
    SWEEP_EVERY = 16

    def __init__(self, path: str, size_limit_bytes: int = 10 * 2 ** 30,
                 telemetry=None):
        self._dir = path
        self._size_limit = size_limit_bytes
        self._telemetry = _resolve_telemetry(telemetry)
        # GIL-atomic counter; a race just shifts the sweep cadence by one
        self._stores_since_sweep = 0
        os.makedirs(path, exist_ok=True)

    def _entry_path(self, key: str) -> str:
        return os.path.join(self._dir, hashlib.sha1(key.encode()).hexdigest() + ".bin")

    def lookup(self, key: str) -> Any:
        """Probe-only half of :meth:`get`: the stored value, or the module's
        ``_MISSING`` sentinel (never fills).  The shared warm tier uses this
        to compose L2 behind its shared-memory L1."""
        path = self._entry_path(key)
        try:
            with open(path, "rb") as f:
                value = pickle.load(f)
        except FileNotFoundError:
            return _MISSING
        except Exception as exc:  # corrupt entry: recompute
            logger.warning("Dropping corrupt cache entry %s: %s", path, exc)
            try:
                os.remove(path)
            except OSError:
                pass
            return _MISSING
        try:
            os.utime(path)  # LRU touch
        except OSError:
            # a concurrent evictor deleted the entry between our open and
            # the touch - the value we already read is still good
            pass
        return value

    def store(self, key: str, value: Any) -> None:
        """Fill-only half of :meth:`get`: atomically publish ``value`` under
        ``key`` (temp file + rename; concurrent writers of one key are safe,
        last rename wins) and run the best-effort eviction sweep (amortized:
        every ``SWEEP_EVERY`` stores)."""
        tmp_fd, tmp_path = tempfile.mkstemp(dir=self._dir, suffix=".tmp")
        try:
            with os.fdopen(tmp_fd, "wb") as f:
                pickle.dump(value, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp_path, self._entry_path(key))
        except Exception:
            try:
                os.remove(tmp_path)
            except OSError:
                pass
            raise
        self._stores_since_sweep += 1
        if self._stores_since_sweep >= self.SWEEP_EVERY:
            self._stores_since_sweep = 0
            self._maybe_evict()

    def get(self, key: str, fill_cache_func: Callable[[], Any]) -> Any:
        value = self.lookup(key)
        if value is not _MISSING:
            self._record_lookup(True)
            return value
        self._record_lookup(False)
        value = fill_cache_func()
        self.store(key, value)
        return value

    def _maybe_evict(self) -> None:
        import time as _time

        entries = []
        total = 0
        now = _time.time()
        for name in os.listdir(self._dir):
            p = os.path.join(self._dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue  # a partner evicted it between listdir and stat
            if name.endswith(".tmp") and now - st.st_mtime < self.ORPHAN_TMP_S:
                # a LIVE concurrent writer's temp file: deleting it would
                # fail that writer's rename.  Old ones are crashed-writer
                # orphans and sweep like any entry.
                continue
            total += st.st_size
            entries.append((st.st_mtime, st.st_size, p))
        if total <= self._size_limit:
            return
        entries.sort()  # oldest first
        for _mtime, size, p in entries:
            try:
                os.remove(p)
                total -= size
            except OSError:
                continue  # a partner's sweep got there first: same outcome
            if total <= self._size_limit:
                return

    def cleanup(self) -> None:
        import shutil

        shutil.rmtree(self._dir, ignore_errors=True)


def make_cache(cache_type: str = "null", cache_location: str = None,
               cache_size_limit: int = None, telemetry=None) -> CacheBase:
    """'null' | 'local-disk' | 'memory' | 'shared' (reference:
    reader.py:126-131; 'memory' and 'shared' are new here).

    'shared' is the host-wide warm tier (petastorm_tpu.cache_shared,
    docs/operations.md "Warm cache"): decoded rowgroups in a shared-memory
    arena every worker/reader/job on the host can hit, backed by a bounded
    disk tier.  ``cache_location`` names the tier (same location = same
    tier host-wide; also the disk tier's directory); ``cache_size_limit``
    sizes the shared-memory arena.  ``telemetry``: optional
    petastorm_tpu.telemetry recorder for the cache.* series."""
    if cache_type in (None, "null", "none"):
        return NullCache()
    if cache_type == "local-disk":
        if not cache_location:
            cache_location = os.path.join(tempfile.gettempdir(), "petastorm_tpu_cache")
        return LocalDiskCache(cache_location, cache_size_limit or 10 * 2 ** 30,
                              telemetry=telemetry)
    if cache_type == "memory":
        return InMemoryCache(cache_size_limit or 4 * 2 ** 30,
                             telemetry=telemetry)
    if cache_type == "shared":
        from petastorm_tpu.cache_shared import DEFAULT_L1_BYTES, SharedWarmCache

        return SharedWarmCache(location=cache_location,
                               l1_bytes=cache_size_limit or DEFAULT_L1_BYTES,
                               telemetry=telemetry)
    raise ValueError(f"Unknown cache_type {cache_type!r}")
