"""Read the hello-world dataset three ways: rows, columnar batches, device feed.

Reference parity: examples/hello_world/petastorm_dataset/python_hello_world.py
plus the tf/pytorch variants - the device-feed path replaces both.
"""

import argparse

from petastorm_tpu.jax import JaxDataLoader
from petastorm_tpu.reader import make_batch_reader, make_reader


def python_hello_world(dataset_url: str) -> None:
    with make_reader(dataset_url, num_epochs=1) as reader:
        for row in reader:
            print(f"row id={row.id}: image1 {row.image1.shape}"
                  f" array_4d {row.array_4d.shape}")


def columnar_hello_world(dataset_url: str) -> None:
    with make_batch_reader(dataset_url, num_epochs=1,
                           schema_fields=["id"]) as reader:
        for batch in reader:
            print(f"columnar batch: ids {list(batch.id)}")


def jax_hello_world(dataset_url: str) -> None:
    reader = make_reader(dataset_url, num_epochs=1)
    # images land on the device; the ragged 4-D field stays out of the feed
    with JaxDataLoader(reader, batch_size=4, fields=["id", "image1"],
                       drop_last=False) as loader:
        for batch in loader:
            img = batch["image1"]
            print(f"device batch: image1 {img.shape} {img.dtype}"
                  f" on {list(img.devices())[0].platform}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("dataset_url", nargs="?", default="/tmp/hello_world_dataset")
    args = parser.parse_args()
    python_hello_world(args.dataset_url)
    columnar_hello_world(args.dataset_url)
    jax_hello_world(args.dataset_url)
