"""Dataset writer/metadata/indexing tests.

Reference models: petastorm/tests/test_dataset_metadata.py, test_generate_metadata.py,
test_parquet_reader.py (plain-parquet inference), rowgroup indexing tests.
"""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu.errors import MetadataError, SchemaError
from petastorm_tpu.etl import (FieldNotNullIndexer, SingleFieldIndexer,
                               build_rowgroup_index, get_row_group_indexes,
                               infer_or_load_schema, open_dataset)
from petastorm_tpu.etl.metadata import ROW_GROUPS_METADATA_KEY
from petastorm_tpu.etl.writer import (materialize_dataset, stamp_dataset_metadata,
                                      write_dataset)
from petastorm_tpu.schema import SCHEMA_METADATA_KEY, Field, Schema
from petastorm_tpu.test_util.synthetic import TEST_SCHEMA, create_test_dataset


@pytest.fixture(scope="module")
def small_dataset(tmp_path_factory):
    path = tmp_path_factory.mktemp("ds") / "small"
    rows = create_test_dataset(str(path), num_rows=50, row_group_size_rows=10)
    return str(path), rows


def test_write_open_roundtrip(small_dataset):
    url, rows = small_dataset
    info = open_dataset(url)
    assert info.stored_schema == TEST_SCHEMA
    assert sum(rg.num_rows for rg in info.row_groups) == 50
    assert all(rg.num_rows == 10 for rg in info.row_groups)
    # deterministic global ordering: files path-sorted, rowgroups in file order
    assert [rg.global_index for rg in info.row_groups] == list(range(len(info.row_groups)))


def test_cached_rowgroup_counts_present(small_dataset):
    url, _ = small_dataset
    info = open_dataset(url)
    assert ROW_GROUPS_METADATA_KEY in info.kv_metadata
    payload = json.loads(info.kv_metadata[ROW_GROUPS_METADATA_KEY])
    assert sum(sum(v) for v in payload["files"].values()) == 50


def test_corrupt_counts_falls_back_to_footers(small_dataset, tmp_path):
    url, _ = small_dataset
    info = open_dataset(url)
    from petastorm_tpu.etl.metadata import load_row_groups
    bad_kv = dict(info.kv_metadata)
    bad_kv[ROW_GROUPS_METADATA_KEY] = b"{not json"
    refs = load_row_groups(info.filesystem, info.root_path, info.files, bad_kv)
    assert sum(r.num_rows for r in refs) == 50


def test_open_dataset_missing_path():
    with pytest.raises(MetadataError):
        open_dataset("/nonexistent/nope")


def test_require_stored_schema_on_plain_parquet(tmp_path):
    plain = tmp_path / "plain"
    plain.mkdir()
    pq.write_table(pa.table({"a": [1, 2, 3], "b": ["x", "y", "z"]}),
                   str(plain / "f.parquet"))
    info = open_dataset(str(plain))
    assert info.stored_schema is None
    schema = infer_or_load_schema(info)
    assert schema.a.dtype == np.int64 and schema.b.dtype == np.dtype("object")
    with pytest.raises(MetadataError):
        open_dataset(str(plain), require_stored_schema=True)


def test_materialize_context_manager(tmp_path):
    schema = Schema("M", [Field("x", np.int32), Field("v", np.float32, (3,))])
    url = str(tmp_path / "mat")
    os.makedirs(url)
    with materialize_dataset(url, schema):
        rows = [schema.encode_row({"x": i, "v": np.full(3, i, np.float32)})
                for i in range(20)]
        table = pa.Table.from_pylist(rows, schema=schema.as_arrow_schema())
        pq.write_table(table, os.path.join(url, "data.parquet"), row_group_size=5)
    info = open_dataset(url, require_stored_schema=True)
    assert info.stored_schema == schema
    assert len(info.row_groups) == 4 and all(r.num_rows == 5 for r in info.row_groups)


def test_partitioned_write_and_discovery(tmp_path):
    schema = Schema("P", [Field("label", np.dtype("object")), Field("x", np.int64)])
    url = str(tmp_path / "part")
    write_dataset(url, schema, ({"label": "ab"[i % 2], "x": i} for i in range(40)),
                  row_group_size_rows=5, partition_by=["label"])
    info = open_dataset(url)
    keys = {rg.partition_values for rg in info.row_groups}
    assert keys == {(("label", "a"),), (("label", "b"),)}
    assert info.partition_keys == ["label"]
    assert sum(rg.num_rows for rg in info.row_groups) == 40


def test_partition_by_validation(tmp_path):
    schema = Schema("P", [Field("m", np.float32, (2,))])
    with pytest.raises(SchemaError):
        write_dataset(str(tmp_path / "x"), schema, [], partition_by=["m"])
    with pytest.raises(SchemaError):
        write_dataset(str(tmp_path / "x"), schema, [], partition_by=["nope"])


def test_open_explicit_file_list(small_dataset):
    url, _ = small_dataset
    info0 = open_dataset(url)
    some_files = info0.files[:1]
    info = open_dataset(some_files)
    assert sum(rg.num_rows for rg in info.row_groups) == 50  # single file holds all


def test_stamp_metadata_regeneration(tmp_path):
    # simulate lost _common_metadata, regenerate from file footers
    schema = Schema("R", [Field("x", np.int32)])
    url = str(tmp_path / "regen")
    write_dataset(url, schema, [{"x": i} for i in range(10)], row_group_size_rows=2)
    os.remove(os.path.join(url, "_common_metadata"))
    info = open_dataset(url)
    assert info.stored_schema == schema  # recovered from data-file footer KV
    stamp_dataset_metadata(url)
    info2 = open_dataset(url, require_stored_schema=True)
    assert len(info2.row_groups) == 5


def test_rows_per_file_split(tmp_path):
    schema = Schema("F", [Field("x", np.int64)])
    url = str(tmp_path / "многоfile")
    files = write_dataset(url, schema, [{"x": i} for i in range(100)],
                          row_group_size_rows=10, rows_per_file=30)
    assert len(files) >= 3
    info = open_dataset(url)
    assert sum(rg.num_rows for rg in info.row_groups) == 100


# -- indexing -----------------------------------------------------------------

def test_single_field_index_build_and_lookup(tmp_path):
    schema = Schema("I", [Field("id", np.int64), Field("label", np.dtype("object"))])
    url = str(tmp_path / "ix")
    write_dataset(url, schema,
                  [{"id": i, "label": "ab"[i // 10 % 2]} for i in range(40)],
                  row_group_size_rows=10)
    build_rowgroup_index(url, [SingleFieldIndexer("by_label", "label")])
    info = open_dataset(url)
    indexes = get_row_group_indexes(info)
    assert set(indexes) == {"by_label"}
    a_groups = indexes["by_label"].get_row_group_indexes("a")
    b_groups = indexes["by_label"].get_row_group_indexes("b")
    assert a_groups == {0, 2} and b_groups == {1, 3}
    assert indexes["by_label"].indexed_values() == ["a", "b"]


def test_not_null_index(tmp_path):
    schema = Schema("N", [Field("id", np.int64),
                          Field("opt", np.float64, nullable=True)])
    url = str(tmp_path / "nn")
    rows = [{"id": i, "opt": None if i < 20 else 1.0} for i in range(40)]
    write_dataset(url, schema, rows, row_group_size_rows=10)
    build_rowgroup_index(url, [FieldNotNullIndexer("opt_nn", "opt")])
    indexes = get_row_group_indexes(open_dataset(url))
    assert indexes["opt_nn"].get_row_group_indexes() == {2, 3}


def test_index_rebuild_merges(tmp_path):
    schema = Schema("I", [Field("id", np.int64), Field("k", np.int32)])
    url = str(tmp_path / "merge")
    write_dataset(url, schema, [{"id": i, "k": i % 3} for i in range(30)],
                  row_group_size_rows=10)
    build_rowgroup_index(url, [SingleFieldIndexer("by_k", "k")])
    build_rowgroup_index(url, [FieldNotNullIndexer("k_nn", "k")])
    indexes = get_row_group_indexes(open_dataset(url))
    assert set(indexes) == {"by_k", "k_nn"}  # second build preserved the first


def test_partitioned_write_no_runt_rowgroups(tmp_path):
    # per-partition buffering: interleaved partition values must still produce
    # full-size rowgroups, not one runt group per encode chunk
    schema = Schema("P", [Field("tag", np.dtype("object")), Field("x", np.int64)])
    url = str(tmp_path / "runt")
    write_dataset(url, schema, ({"tag": "abc"[i % 3], "x": i} for i in range(75)),
                  row_group_size_rows=5, partition_by=["tag"])
    info = open_dataset(url)
    assert len(info.row_groups) == 15  # 25 rows/partition / 5 = 5 groups x 3
    assert all(rg.num_rows == 5 for rg in info.row_groups)


def test_empty_write_returns_no_files(tmp_path):
    schema = Schema("E", [Field("x", np.int64)])
    assert write_dataset(str(tmp_path / "empty"), schema, []) == []


def test_index_unknown_field(tmp_path):
    schema = Schema("I", [Field("id", np.int64)])
    url = str(tmp_path / "uf")
    write_dataset(url, schema, [{"id": 1}])
    with pytest.raises(MetadataError):
        build_rowgroup_index(url, [SingleFieldIndexer("x", "missing")])


def test_index_on_partition_column(tmp_path):
    schema = Schema("P", [Field("label", np.dtype("object")), Field("x", np.int64)])
    url = str(tmp_path / "ixpart")
    write_dataset(url, schema, [{"label": "ab"[i // 10], "x": i} for i in range(20)],
                  row_group_size_rows=10, partition_by=["label"])
    build_rowgroup_index(url, [SingleFieldIndexer("by_label", "label")])
    indexes = get_row_group_indexes(open_dataset(url))
    a = indexes["by_label"].get_row_group_indexes("a")
    b = indexes["by_label"].get_row_group_indexes("b")
    assert a and b and not (a & b) and len(a | b) == 2


def test_explicit_file_list_keeps_partition_values(tmp_path):
    schema = Schema("P", [Field("label", np.dtype("object")), Field("x", np.int64)])
    url = str(tmp_path / "flist")
    write_dataset(url, schema, [{"label": "ab"[i % 2], "x": i} for i in range(20)],
                  row_group_size_rows=5, partition_by=["label"])
    all_files = open_dataset(url).files
    info = open_dataset(all_files)
    labels = {dict(rg.partition_values).get("label") for rg in info.row_groups}
    assert labels == {"a", "b"}  # first file's partition must not be swallowed


def test_partition_value_escaping(tmp_path):
    schema = Schema("P", [Field("label", np.dtype("object")), Field("x", np.int64)])
    url = str(tmp_path / "esc")
    write_dataset(url, schema, [{"label": "a/b=c%d", "x": 1}], partition_by=["label"])
    info = open_dataset(url)
    assert dict(info.row_groups[0].partition_values)["label"] == "a/b=c%d"


def test_partition_value_none_rejected(tmp_path):
    schema = Schema("P", [Field("label", np.dtype("object"), nullable=True),
                          Field("x", np.int64)])
    with pytest.raises(SchemaError):
        write_dataset(str(tmp_path / "pn"), schema, [{"label": None, "x": 1}],
                      partition_by=["label"])


def test_sanitize_bool_exact():
    from petastorm_tpu.dtypes import sanitize_value
    assert sanitize_value(1, np.dtype("bool")) is True
    with pytest.raises(SchemaError):
        sanitize_value(2, np.dtype("bool"))
    with pytest.raises(SchemaError):
        sanitize_value(2 ** 70, np.dtype("int64"))


def test_write_dataset_mode_guard(tmp_path):
    """Writing into a non-empty dataset dir errors by default; overwrite and
    append are explicit (regression: silent append mixed old+new rows)."""
    import pytest

    from petastorm_tpu.errors import SchemaError
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("ModeGuard", [Field("id", np.int64)])
    url = str(tmp_path / "ds")
    write_dataset(url, schema, [{"id": i} for i in range(5)])
    with pytest.raises(SchemaError, match="already contains"):
        write_dataset(url, schema, [{"id": 99}])
    write_dataset(url, schema, [{"id": i} for i in range(10, 15)],
                  mode="overwrite")
    with make_reader(url, shuffle_row_groups=False) as r:
        assert sorted(row.id for row in r) == list(range(10, 15))
    write_dataset(url, schema, [{"id": 20}], mode="append")
    with make_reader(url, shuffle_row_groups=False) as r:
        assert sorted(row.id for row in r) == [10, 11, 12, 13, 14, 20]


def test_page_checksums_detect_corruption(tmp_path):
    """The writer stamps parquet page checksums; verify_checksums=True turns a
    flipped byte into a read error instead of silent garbage (the native image
    decoder skips in-stream PNG CRCs and relies on this layer)."""
    import os

    import pyarrow.parquet as pq
    import pytest

    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.pool import WorkerError
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema

    rng = np.random.default_rng(7)
    schema = Schema("Crc", [
        Field("id", np.int64),
        Field("img", np.uint8, (32, 32, 3), CompressedImageCodec("png")),
    ])
    rows = [{"id": i, "img": rng.integers(0, 255, (32, 32, 3), dtype=np.uint8)}
            for i in range(8)]
    url = str(tmp_path / "ds")
    [path] = write_dataset(url, schema, rows, row_group_size_rows=8)

    # clean read passes verification
    with make_reader(url, verify_checksums=True, shuffle_row_groups=False) as r:
        assert len(list(r)) == 8

    # flip one byte inside the img column's data pages (past the page header)
    col = next(c for c in
               (pq.ParquetFile(path).metadata.row_group(0).column(i)
                for i in range(2))
               if c.path_in_schema == "img")
    chunk_start = (col.dictionary_page_offset
                   if col.dictionary_page_offset is not None
                   else col.data_page_offset)
    target = chunk_start + col.total_compressed_size // 2
    with open(path, "r+b") as f:
        f.seek(target)
        b = f.read(1)
        f.seek(target)
        f.write(bytes([b[0] ^ 0xFF]))

    with make_reader(url, verify_checksums=True, shuffle_row_groups=False) as r:
        with pytest.raises(WorkerError):
            list(r)


def test_parallel_encode_writes_identical_dataset(tmp_path):
    """encode_workers parallelizes the codec encodes without changing the
    written bytes: same rows, same order, same rowgroup layout."""
    import numpy as np

    from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("Par", [
        Field("id", np.int64),
        Field("img", np.uint8, (24, 24, 3), CompressedImageCodec("png")),
        Field("vec", np.float32, (5,), NdarrayCodec()),
    ])
    rng = np.random.default_rng(3)
    rows = [{"id": i,
             "img": rng.integers(0, 255, (24, 24, 3), dtype=np.uint8),
             "vec": rng.standard_normal(5).astype(np.float32)}
            for i in range(48)]
    a, b = str(tmp_path / "serial"), str(tmp_path / "parallel")
    write_dataset(a, schema, rows, row_group_size_rows=8)
    write_dataset(b, schema, rows, row_group_size_rows=8, encode_workers=4)

    def read_all(url):
        with make_reader(url, reader_pool_type="serial", num_epochs=1,
                         shuffle_row_groups=False) as r:
            return list(r)

    ra, rb = read_all(a), read_all(b)
    assert [x.id for x in ra] == [x.id for x in rb] == list(range(48))
    for x, y in zip(ra, rb):
        np.testing.assert_array_equal(x.img, y.img)
        np.testing.assert_array_equal(x.vec, y.vec)


def test_write_failure_closes_open_writers(tmp_path):
    """A mid-stream encode failure must not leak open parquet writers (their
    output streams would hold unfinalized uploads on object stores)."""
    import gc

    import numpy as np

    from petastorm_tpu.errors import SchemaError
    from petastorm_tpu.etl import writer as writer_mod
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema

    closed = []
    orig_writer = writer_mod.pq.ParquetWriter

    class TrackingWriter(orig_writer):
        def close(self):
            closed.append(True)
            return super().close()

    writer_mod.pq.ParquetWriter = TrackingWriter
    try:
        schema = Schema("F", [Field("id", np.int64)])

        def rows():
            yield {"id": 0}
            yield {"id": "not-an-int"}  # encode fails mid-stream

        with pytest.raises(Exception):
            write_dataset(str(tmp_path / "ds"), schema, rows(),
                          row_group_size_rows=1)
    finally:
        writer_mod.pq.ParquetWriter = orig_writer
    gc.collect()
    assert closed, "no writer was closed on the failure path"
    # close() wrote a footer, making the debris parse as valid parquet; the
    # failure path must delete it so mode='append'/stamp cannot adopt it
    leftovers = [p for p in (tmp_path / "ds").rglob("*.parquet")]
    assert not leftovers, f"failed write left adoptable parquet files: {leftovers}"


def test_happy_path_close_failure_deletes_all_output(tmp_path):
    """A footer flush failing in the final close loop must delete the files
    earlier writers closed successfully - the call failed as a whole, so none
    of its output may survive to be adopted by a later append/stamp."""
    import numpy as np

    from petastorm_tpu.etl import writer as writer_mod
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema

    orig_writer = writer_mod.pq.ParquetWriter
    n_closed = [0]

    class SecondCloseFails(orig_writer):
        def close(self):
            n_closed[0] += 1
            if n_closed[0] == 2:
                raise OSError("simulated footer flush failure")
            return super().close()

    writer_mod.pq.ParquetWriter = SecondCloseFails
    try:
        schema = Schema("P", [Field("part", np.int64), Field("id", np.int64)])
        rows = [{"part": i % 2, "id": i} for i in range(8)]
        with pytest.raises(OSError, match="footer flush"):
            write_dataset(str(tmp_path / "ds"), schema, rows,
                          partition_by=["part"], row_group_size_rows=2)
    finally:
        writer_mod.pq.ParquetWriter = orig_writer
    leftovers = list((tmp_path / "ds").rglob("*.parquet"))
    assert not leftovers, f"close failure left adoptable files: {leftovers}"
