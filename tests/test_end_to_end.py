"""End-to-end reader tests across executor flavors.

Reference model: petastorm/tests/test_end_to_end.py (~50 tests, 862 LoC) -
parametrized over pool factories (test_end_to_end.py:44-59), covering read/
transform/predicate/shard/shuffle/cache/epochs/selectors.
"""

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.errors import (EpochNotFinishedError, MetadataError,
                                  NoDataAvailableError, PetastormTpuError)
from petastorm_tpu.etl import SingleFieldIndexer, build_rowgroup_index
from petastorm_tpu.predicates import in_lambda, in_pseudorandom_split, in_set
from petastorm_tpu.selectors import SingleIndexSelector
from petastorm_tpu.test_util.synthetic import TEST_SCHEMA, create_test_dataset
from petastorm_tpu.transform import TransformSpec

# serial + thread on every test; process pool is slow to spawn (1-core CI), so it
# gets one dedicated smoke test (reference runs the full matrix incl. process x2
# serializers, test_end_to_end.py:44-59)
POOLS = ["serial", "thread"]


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("e2e") / "ds")
    rows = create_test_dataset(path, num_rows=60, row_group_size_rows=10)
    return path, rows


@pytest.mark.parametrize("pool", POOLS)
def test_read_all_rows_row_path(dataset, pool):
    url, rows = dataset
    with make_reader(url, reader_pool_type=pool, workers_count=2,
                     shuffle_row_groups=False) as reader:
        seen = {r.id: r for r in reader}
    assert set(seen) == {r["id"] for r in rows}
    want = next(r for r in rows if r["id"] == 7)
    got = seen[7]
    np.testing.assert_array_equal(got.matrix, want["matrix"])
    np.testing.assert_array_equal(got.image_png, want["image_png"])
    np.testing.assert_array_equal(got.matrix_var, want["matrix_var"])
    assert got.sensor_name == want["sensor_name"]


@pytest.mark.parametrize("pool", POOLS)
def test_read_batch_path(dataset, pool):
    url, rows = dataset
    with make_batch_reader(url, reader_pool_type=pool, workers_count=2,
                           shuffle_row_groups=False) as reader:
        batches = list(reader)
    assert sum(len(b.id) for b in batches) == 60
    assert all(b.matrix.shape[1:] == (4, 5) for b in batches)  # stacked contiguous


def test_process_pool_smoke(dataset):
    url, rows = dataset
    with make_reader(url, reader_pool_type="process", workers_count=2,
                     shuffle_row_groups=False,
                     schema_fields=["id", "matrix"]) as reader:
        ids = sorted(r.id for r in reader)
    assert ids == sorted(r["id"] for r in rows)


@pytest.mark.parametrize("pool", POOLS)
def test_schema_fields_subset_and_regex(dataset, pool):
    url, _ = dataset
    with make_reader(url, reader_pool_type=pool, schema_fields=["id", "matrix.*"],
                     shuffle_row_groups=False) as reader:
        row = next(reader)
    assert set(row._fields) == {"id", "matrix", "matrix_compressed", "matrix_var"}


@pytest.mark.parametrize("pool", POOLS)
def test_predicate_pushdown(dataset, pool):
    url, rows = dataset
    keep = {3, 10, 44}
    with make_reader(url, reader_pool_type=pool, predicate=in_set(keep, "id"),
                     shuffle_row_groups=False) as reader:
        got = sorted(r.id for r in reader)
    assert got == sorted(keep)


def test_predicate_lambda_vectorized(dataset):
    url, rows = dataset
    pred = in_lambda(["id"], lambda cols: cols["id"] % 2 == 0, vectorized=True)
    with make_reader(url, predicate=pred, shuffle_row_groups=False) as reader:
        got = sorted(r.id for r in reader)
    assert got == [r["id"] for r in rows if r["id"] % 2 == 0]


def test_pseudorandom_split_partitions_disjoint(dataset):
    url, rows = dataset
    split = [0.5, 0.5]
    with make_reader(url, predicate=in_pseudorandom_split(split, 0, "sensor_name"),
                     shuffle_row_groups=False) as r0:
        ids0 = {r.id for r in r0}
    with make_reader(url, predicate=in_pseudorandom_split(split, 1, "sensor_name"),
                     shuffle_row_groups=False) as r1:
        ids1 = {r.id for r in r1}
    assert not (ids0 & ids1)
    assert ids0 | ids1 == {r["id"] for r in rows}


@pytest.mark.parametrize("pool", POOLS)
def test_sharding_disjoint_and_complete(dataset, pool):
    url, rows = dataset
    shards = []
    for shard in range(3):
        with make_reader(url, reader_pool_type=pool, cur_shard=shard, shard_count=3,
                         shuffle_row_groups=False) as reader:
            shards.append({r.id for r in reader})
    assert set().union(*shards) == {r["id"] for r in rows}
    assert sum(len(s) for s in shards) == 60


def test_too_many_shards(dataset):
    url, _ = dataset
    with pytest.raises(NoDataAvailableError):
        make_reader(url, cur_shard=0, shard_count=100)


def test_shuffle_changes_order_deterministically(dataset):
    url, _ = dataset

    def read_ids(seed):
        with make_reader(url, shuffle_row_groups=True, shuffle_seed=seed,
                         reader_pool_type="serial") as reader:
            return [r.id for r in reader]

    assert read_ids(1) == read_ids(1)
    assert read_ids(1) != read_ids(2)


def test_multiple_epochs(dataset):
    url, rows = dataset
    with make_reader(url, num_epochs=3, shuffle_row_groups=False) as reader:
        ids = [r.id for r in reader]
    assert len(ids) == 180
    assert sorted(set(ids)) == [r["id"] for r in rows]


def test_reset_after_epoch(dataset):
    url, _ = dataset
    with make_reader(url, shuffle_row_groups=False,
                     reader_pool_type="serial") as reader:
        first = [r.id for r in reader]
        assert reader.last_row_consumed
        reader.reset()
        second = [r.id for r in reader]
    assert first == second


def test_reset_mid_epoch_raises(dataset):
    url, _ = dataset
    with make_reader(url, shuffle_row_groups=False) as reader:
        next(reader)
        with pytest.raises(EpochNotFinishedError):
            reader.reset()


def test_transform_spec(dataset):
    url, _ = dataset

    def double(cols):
        return {**cols, "matrix": cols["matrix"] * 2.0}

    spec = TransformSpec(double, removed_fields=["image_png"])
    with make_reader(url, transform_spec=spec, shuffle_row_groups=False,
                     schema_fields=["id", "matrix", "image_png"]) as reader:
        row = next(reader)
    assert not hasattr(row, "image_png")


def test_transform_row_count_change(dataset):
    url, _ = dataset

    def drop_half(cols):
        return {k: v[: len(v) // 2] for k, v in cols.items()}

    with make_reader(url, transform_spec=TransformSpec(drop_half),
                     schema_fields=["id"], shuffle_row_groups=False) as reader:
        ids = [r.id for r in reader]
    assert len(ids) == 30


def test_rowgroup_selector(dataset):
    url, rows = dataset
    build_rowgroup_index(url, [SingleFieldIndexer("by_pk", "partition_key")])
    values = sorted({r["partition_key"] for r in rows})
    target = values[0]
    with make_reader(url, rowgroup_selector=SingleIndexSelector("by_pk", [target]),
                     shuffle_row_groups=False) as reader:
        got_ids = {r.id for r in reader}
    # selector is rowgroup-granular: must cover all rows with the value, may include more
    want_ids = {r["id"] for r in rows if r["partition_key"] == target}
    assert want_ids <= got_ids


def test_local_disk_cache_roundtrip(dataset, tmp_path):
    url, rows = dataset
    for _pass in range(2):  # second pass served from cache
        with make_reader(url, cache_type="local-disk",
                         cache_location=str(tmp_path / "cache"),
                         shuffle_row_groups=False, workers_count=1) as reader:
            ids = sorted(r.id for r in reader)
        assert ids == [r["id"] for r in rows]


def test_memory_cache_roundtrip(dataset):
    url, rows = dataset
    with make_reader(url, cache_type="memory", shuffle_row_groups=False,
                     workers_count=1, num_epochs=2) as reader:
        ids = sorted(r.id for r in reader)
    assert ids == sorted([r["id"] for r in rows] * 2)


def test_memory_cache_lru_eviction_and_hits():
    from petastorm_tpu.batch import ColumnBatch
    from petastorm_tpu.cache import InMemoryCache

    calls = {"n": 0}

    def make_batch(tag):
        def fill():
            calls["n"] += 1
            return ColumnBatch({"x": np.full(1000, tag, np.int64)}, 1000)
        return fill

    cache = InMemoryCache(size_limit_bytes=20_000)  # fits 2 x 8KB batches
    cache.get("a", make_batch(1))
    cache.get("b", make_batch(2))
    cache.get("a", make_batch(1))          # hit
    assert calls["n"] == 2
    cache.get("c", make_batch(3))          # evicts 'b' (LRU)
    cache.get("a", make_batch(1))          # still cached
    assert calls["n"] == 3
    cache.get("b", make_batch(2))          # miss again after eviction
    assert calls["n"] == 4
    # oversized entries are served uncached, not stored
    big = InMemoryCache(size_limit_bytes=100)
    big.get("huge", make_batch(9))
    big.get("huge", make_batch(9))
    assert calls["n"] == 6


def test_cache_with_predicate_rejected(dataset, tmp_path):
    url, _ = dataset
    with pytest.raises(PetastormTpuError):
        make_reader(url, cache_type="local-disk",
                    cache_location=str(tmp_path / "c2"),
                    predicate=in_set({1}, "id"))


def test_row_drop_partitions(dataset):
    url, rows = dataset
    with make_reader(url, shuffle_row_drop_partitions=3, shuffle_seed=0) as reader:
        ids = sorted(r.id for r in reader)
    assert ids == sorted(r["id"] for r in rows)  # all rows exactly once


def test_make_reader_on_plain_parquet_raises(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    plain = tmp_path / "plain"
    plain.mkdir()
    pq.write_table(pa.table({"a": [1, 2]}), str(plain / "x.parquet"))
    with pytest.raises(MetadataError) as ei:
        make_reader(str(plain))
    assert "make_batch_reader" in str(ei.value)


def test_batch_reader_on_plain_parquet(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    plain = tmp_path / "plainb"
    plain.mkdir()
    pq.write_table(pa.table({"a": list(range(20)),
                             "b": [float(i) for i in range(20)],
                             "v": [[i, i + 1] for i in range(20)]}),
                   str(plain / "x.parquet"), row_group_size=5)
    with make_batch_reader(str(plain), shuffle_row_groups=False) as reader:
        batches = list(reader)
    assert sum(len(b.a) for b in batches) == 20
    assert batches[0].v.shape == (5, 2)  # fixed-width lists vstack


def test_partitioned_dataset_reads_partition_column(tmp_path):
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("P", [Field("label", np.dtype("object")), Field("x", np.int64)])
    url = str(tmp_path / "pread")
    write_dataset(url, schema, [{"label": "ab"[i % 2], "x": i} for i in range(20)],
                  row_group_size_rows=5, partition_by=["label"])
    with make_reader(url, shuffle_row_groups=False) as reader:
        rows = list(reader)
    assert len(rows) == 20
    labels = {r.label for r in rows}
    assert labels == {"a", "b"}
    for r in rows:
        assert r.label == "ab"[r.x % 2]


def test_partition_predicate_pushdown_driver_side(tmp_path):
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("P", [Field("label", np.dtype("object")), Field("x", np.int64)])
    url = str(tmp_path / "ppd")
    write_dataset(url, schema, [{"label": "ab"[i % 2], "x": i} for i in range(20)],
                  row_group_size_rows=5, partition_by=["label"])
    with make_reader(url, predicate=in_set({"a"}, "label"),
                     shuffle_row_groups=False) as reader:
        rows = list(reader)
    assert all(r.label == "a" for r in rows) and len(rows) == 10


def test_partition_pushdown_typed_values(tmp_path):
    # hive path values are strings; pushdown must compare with the field's dtype
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("P", [Field("day", np.int32), Field("x", np.int64)])
    url = str(tmp_path / "typed")
    write_dataset(url, schema, [{"day": i % 3, "x": i} for i in range(30)],
                  row_group_size_rows=5, partition_by=["day"])
    with make_reader(url, predicate=in_set([1, 2], "day"),
                     shuffle_row_groups=False) as reader:
        rows = list(reader)
    assert rows and all(r.day in (1, 2) for r in rows)
    assert len(rows) == 20


def test_open_single_partition_file_list(tmp_path):
    # explicit file list drawn from ONE partition must keep partition values
    from petastorm_tpu.etl import open_dataset
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("P", [Field("label", np.dtype("object")), Field("x", np.int64)])
    url = str(tmp_path / "single")
    write_dataset(url, schema, [{"label": "ab"[i % 2], "x": i} for i in range(20)],
                  row_group_size_rows=5, partition_by=["label"])
    a_files = [f for f in open_dataset(url).files if "label=a" in f]
    info = open_dataset(a_files)
    assert all(dict(rg.partition_values).get("label") == "a" for rg in info.row_groups)
    assert info.stored_schema == schema  # _common_metadata found at true root


def test_explicit_filesystem_reaches_workers(dataset):
    import pyarrow.fs as pafs

    url, rows = dataset
    with make_reader(url, filesystem=pafs.LocalFileSystem(),
                     schema_fields=["id"], shuffle_row_groups=False) as reader:
        ids = sorted(r.id for r in reader)
    assert ids == sorted(r["id"] for r in rows)


def test_resume_from_state_dict(dataset):
    url, rows = dataset
    with make_reader(url, shuffle_seed=11, reader_pool_type="serial",
                     num_epochs=2, workers_count=1) as reader:
        full = [r.id for r in reader]
        state_end = reader.state_dict()
    assert state_end["position"] == 12  # 6 rowgroups x 2 epochs

    # consume exactly one epoch, snapshot, resume: second half must match
    with make_reader(url, shuffle_seed=11, reader_pool_type="serial",
                     num_epochs=2, workers_count=1) as reader:
        first_half = [r.id for r in [next(reader) for _ in range(60)]]
        state = reader.state_dict()
    assert state["position"] == 6
    with make_reader(url, shuffle_seed=11, reader_pool_type="serial",
                     num_epochs=2, workers_count=1, resume_from=state) as reader:
        second_half = [r.id for r in reader]
    assert first_half + second_half == full


def test_serial_pool_infinite_epochs_bounded(dataset):
    # ventilator must not run unboundedly ahead on the serial pool
    url, _ = dataset
    import time
    with make_reader(url, reader_pool_type="serial", num_epochs=None) as reader:
        for _ in range(10):
            next(reader)
        time.sleep(0.3)
        assert reader.diagnostics["ventilated"] < 100


def test_diagnostics_shape(dataset):
    url, _ = dataset
    with make_reader(url, shuffle_row_groups=False) as reader:
        next(reader)
        d = reader.diagnostics
    assert "items_per_epoch" in d and d["items_per_epoch"] == 6


def test_memory_cache_process_pool_rejected(dataset):
    url, _ = dataset
    with pytest.raises(PetastormTpuError, match="process-local"):
        make_reader(url, cache_type="memory", reader_pool_type="process")


def test_memory_cache_isolated_from_inplace_mutation():
    from petastorm_tpu.batch import ColumnBatch
    from petastorm_tpu.cache import InMemoryCache

    cache = InMemoryCache()
    fixed = np.arange(6, dtype=np.float64)
    ragged = np.empty(2, dtype=object)
    ragged[0], ragged[1] = np.ones(3), np.ones(5)
    v1 = cache.get("k", lambda: ColumnBatch({"a": fixed[:2], "r": ragged}, 2))
    v1.columns["a"] /= 2.0          # consumer mutates in place
    v1.columns["r"][0] *= 100.0
    v2 = cache.get("k", lambda: (_ for _ in ()).throw(AssertionError("miss")))
    np.testing.assert_array_equal(v2.columns["a"], [0.0, 1.0])
    np.testing.assert_array_equal(v2.columns["r"][0], np.ones(3))


def test_memory_cache_object_column_sizing():
    from petastorm_tpu.batch import ColumnBatch
    from petastorm_tpu.cache import InMemoryCache

    big = np.empty(2, dtype=object)
    big[0] = np.zeros(300_000, np.uint8)  # 300KB payload behind 8-byte pointer
    big[1] = np.zeros(300_000, np.uint8)
    batch = ColumnBatch({"r": big}, 2)
    assert InMemoryCache._estimate_size(batch) > 500_000
    # cap smaller than the true payload: entry must be served uncached
    cache = InMemoryCache(size_limit_bytes=100_000)
    calls = {"n": 0}

    def fill():
        calls["n"] += 1
        return batch
    cache.get("k", fill)
    cache.get("k", fill)
    assert calls["n"] == 2


def test_batch_reader_over_multiple_urls(tmp_path):
    """make_batch_reader accepts a homogeneous URL list (reference:
    dataset_url_or_urls, reader.py:179)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from petastorm_tpu.reader import make_batch_reader

    for name, lo in (("p1", 0), ("p2", 100)):
        d = tmp_path / name
        d.mkdir()
        pq.write_table(pa.table({"a": list(range(lo, lo + 10))}),
                       str(d / "x.parquet"))
    urls = [str(tmp_path / "p1"), str(tmp_path / "p2")]
    with make_batch_reader(urls, shuffle_row_groups=False, num_epochs=1) as r:
        got = sorted(int(v) for b in r for v in b.a)
    assert got == list(range(10)) + list(range(100, 110))


def test_workers_count_auto(tmp_path):
    """'auto' with the default autotune arming seeds the pool from the
    static PLANNER's verdict (petastorm_tpu.planner - parquet metadata or a
    recorded flight profile); ``autotune=False`` restores the old static
    core heuristic (usable cores - 1, capped at 10)."""
    import os

    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema

    url = str(tmp_path / "ds")
    write_dataset(url, Schema("A", [Field("id", np.int64)]),
                  [{"id": i} for i in range(16)], row_group_size_rows=8)
    with make_batch_reader(url, workers_count="auto", num_epochs=1) as r:
        got = sorted(int(v) for b in r.iter_batches() for v in b.columns["id"])
        workers = r.diagnostics["workers_count"]
        verdict = r.planner
    assert got == list(range(16))
    assert verdict is not None
    assert workers == verdict.knobs["workers"].value
    assert verdict.knobs["workers"].source in ("metadata", "default",
                                               "profile")
    with make_batch_reader(url, workers_count="auto", num_epochs=1,
                           autotune=False) as r:
        list(r.iter_batches())
        static_workers = r.diagnostics["workers_count"]
        assert r.planner is None
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    assert static_workers == max(1, min(10, cores - 1))
