"""Test configuration.

JAX runs on a virtual 8-device CPU mesh in tests (multi-chip sharding is validated
without TPU hardware, mirroring how the reference simulates multi-node sharding
in-process - petastorm/tests/test_end_to_end.py:454).  The env vars must be set
before the first ``import jax`` anywhere in the test process.
"""

import os
import sys

# The image's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon (the real-TPU tunnel), so env vars alone are too late.
# The backend itself is lazy, so overriding config BEFORE the first
# jax.devices() call still lands us on the virtual 8-device CPU platform.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)
