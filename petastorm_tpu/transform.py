"""User transforms applied on reader workers.

Reference parity: petastorm/transform.py - TransformSpec(func, edit_fields,
removed_fields, selected_fields) (transform.py:27-57) and ``transform_schema``
deriving the post-transform schema (transform.py:60-89).

Difference: the transform here is **columnar** - ``func`` receives a dict of numpy
column arrays (one entry per field, batch-major) and returns the same, matching the
batch path the reference applies via pandas (arrow_reader_worker.py:190-222).  A
``row_transform`` convenience wraps a per-row function for row-path readers.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from petastorm_tpu.errors import PetastormTpuError, SchemaError
from petastorm_tpu.schema import Field, Schema

logger = logging.getLogger(__name__)

#: edit_fields entries: (name, numpy_dtype, shape, nullable)
EditFieldT = Tuple[str, "np.dtype", Tuple[Optional[int], ...], bool]


class TransformSpec:
    """Worker-side columnar transform: ``func(columns) -> columns`` plus the
    schema edits it implies (``edit_fields`` added/retyped, ``removed_fields``
    dropped, ``selected_fields`` kept) - the reader's output schema reflects
    the edits before any data flows (reference transform_spec semantics).

    ``deterministic`` declares whether ``func`` is a pure function of its
    input columns (same batch in -> bit-identical columns out, across calls
    and processes), which is what lets the shared warm tier cache the
    transform's OUTPUT so warm epochs skip decode AND transform
    (docs/operations.md "Transform caching & the pipeline planner"):

    * ``'auto'`` (default) - a conservative pure-bytecode heuristic decides:
      output caching arms only when the compiled function references no
      known-stochastic names (``random``/``shuffle``/``time``/...) and every
      closure cell folds into the cache signature as a stable constant.
    * ``True`` - the user asserts purity; still refused (with a one-time
      warning, never a wrong cache hit) when closure/instance state cannot
      be folded into the signature.
    * ``False`` - the transform re-runs every epoch; its output is never
      cached (augmentation, anything sampling an RNG).
    """
    def __init__(self,
                 func: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]] = None,
                 edit_fields: Optional[Sequence[EditFieldT]] = None,
                 removed_fields: Optional[Sequence[str]] = None,
                 selected_fields: Optional[Sequence[str]] = None,
                 deterministic: Union[bool, str] = "auto"):
        self.func = func
        self.edit_fields = list(edit_fields or [])
        self.removed_fields = list(removed_fields or [])
        self.selected_fields = list(selected_fields) if selected_fields is not None else None
        if deterministic not in (True, False, "auto"):
            raise PetastormTpuError(
                "TransformSpec deterministic must be True, False or 'auto';"
                f" got {deterministic!r}")
        self.deterministic = deterministic

    def __call__(self, columns: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = self.func(columns) if self.func is not None else dict(columns)
        for name in self.removed_fields:
            out.pop(name, None)
        if self.selected_fields is not None:
            out = {k: out[k] for k in self.selected_fields}
        return out


def _hash_code_object(code, update) -> None:
    """Feed a code object's CONTENT (bytecode, names, stable const tokens,
    nested code objects recursively) into ``update``.  repr() of a code
    object embeds its memory address and repr() of a set is
    hash-randomization-ordered - both would make the digest differ between
    interpreters, silently defeating cross-process cache sharing."""
    import types

    update(code.co_code)
    update(repr(code.co_names).encode())
    update(repr(code.co_varnames).encode())
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            _hash_code_object(const, update)
        elif isinstance(const, frozenset):
            update(("frozenset:"
                    + ",".join(sorted(map(repr, const)))).encode())
        else:
            update(repr(const).encode())


#: closure-cell value types that fold into the signature verbatim (immutable
#: scalars whose repr is stable across interpreters and PYTHONHASHSEEDs)
_SAFE_SCALARS = (type(None), bool, int, float, complex, str, bytes)

#: names whose presence in a transform's bytecode makes the 'auto'
#: determinism heuristic refuse output caching (stochastic / clock sources;
#: false positives only cost a cache, never correctness)
_STOCHASTIC_NAMES = frozenset({
    "random", "default_rng", "RandomState", "Generator", "rand", "randn",
    "randint", "random_sample", "permutation", "shuffle", "choice",
    "normal", "uniform", "standard_normal", "integers", "poisson",
    "binomial", "exponential", "sample", "getrandbits", "urandom",
    "token_bytes", "uuid1", "uuid4", "time", "time_ns", "perf_counter",
    "perf_counter_ns", "monotonic", "monotonic_ns"})


def _constant_token(value, depth: int = 0) -> Optional[str]:
    """Interpreter/PYTHONHASHSEED-stable token for a closure-cell constant,
    or None when the value is not a foldable constant.  Sets/dicts/lists
    (mutable) and arbitrary objects (repr may embed addresses; hashable-by-
    identity objects can mutate without changing their hash) are NOT
    foldable - refusing them is what keeps a folded signature from ever
    serving a wrong cache hit."""
    if depth > 4:
        return None
    if isinstance(value, _SAFE_SCALARS):
        return f"{type(value).__name__}:{value!r}"
    if isinstance(value, tuple):
        parts = [_constant_token(v, depth + 1) for v in value]
        if any(p is None for p in parts):
            return None
        return "tuple:(" + ",".join(parts) + ")"
    if isinstance(value, frozenset):
        parts = [_constant_token(v, depth + 1) for v in value]
        if any(p is None for p in parts):
            return None
        # sorted tokens, never iteration order: frozenset iteration is
        # hash-randomization-ordered across interpreters
        return "frozenset:{" + ",".join(sorted(parts)) + "}"
    if isinstance(value, np.dtype):
        return f"dtype:{value!s}"
    if isinstance(value, np.ndarray) and value.dtype != object:
        # value-hashed at signature time: two jobs closing over different
        # constant arrays (normalization mean/std) get different keys.
        # Mutating a captured array mid-job is out of contract for a
        # deterministic-declared transform (documented in operations.md).
        import hashlib

        h = hashlib.md5(np.ascontiguousarray(value).tobytes()).hexdigest()
        return f"ndarray:{value.dtype}:{value.shape}:{h[:16]}"
    if isinstance(value, np.generic):
        return f"npscalar:{value.dtype}:{value!r}"
    return None


def _instance_state(obj) -> List[tuple]:
    """Sorted (name, value) pairs of an object's instance state:
    ``__dict__`` PLUS every ``__slots__`` entry in its MRO (a slotted
    callable's config must fold - or refuse - exactly like a dict-backed
    one) PLUS plain data attributes declared on its classes (class-level
    config like ``factor = 2`` is read through ``self.`` just the same)."""
    items = dict(getattr(obj, "__dict__", None) or {})
    for klass in type(obj).__mro__:
        if klass is object:
            continue
        for slot in getattr(klass, "__slots__", ()) or ():
            if isinstance(slot, str) and slot not in ("__dict__",
                                                      "__weakref__"):
                try:
                    items.setdefault(slot, getattr(obj, slot))
                except AttributeError:
                    pass  # never assigned: no state to fold
        for name, value in vars(klass).items():
            if (name.startswith("__") or callable(value)
                    or hasattr(value, "__get__")):
                continue  # methods/descriptors are code, not data
            items.setdefault(name, value)
    return sorted(items.items())


def _fold_state(name: str, value, update, seen: set, names: set,
                depth: int = 0) -> List[str]:
    """Fold one closure cell / instance attribute / referenced global into
    the digest; returns the (possibly nested) names whose values could not
    be folded.  Every reached code object also feeds ``names`` (the
    stochastic-name check must see helpers, not just the top function)."""
    import types

    if depth > 3:
        # a pathological reference graph: refusing keeps the guard honest
        update(f"cell:{name}:<opaque:depth>".encode())
        return [name]
    if isinstance(value, types.ModuleType):
        # module references (np, cv2, ...) fold by name - calls INTO them
        # are covered by the stochastic-name check, like attribute calls
        update(f"cell:{name}:module:{value.__name__}".encode())
        return []
    if callable(value) and getattr(value, "__code__", None) is not None:
        # a captured/referenced python function (row_transform's wrapped fn,
        # module-level helpers): fold its CODE recursively, so editing the
        # inner function's body changes the signature - the PR 7 closure
        # caveat this closes.  Its own closure AND globals fold too.
        update(f"cell:{name}:func".encode())
        if id(value) in seen:
            return []
        seen.add(id(value))
        _hash_code_object(value.__code__, update)
        _collect_names(value.__code__, names)
        opaque = [f"{name}.{n}" for n in
                  _fold_closure(value, update, seen, names, depth + 1)]
        opaque += [f"{name}.{n}" for n in
                   _fold_globals(value, update, seen, names, depth + 1)]
        return opaque
    if isinstance(value, type):
        # a referenced class: folds by qualified name, and its PYTHON
        # method bodies fold too (editing a method changes the cache key)
        # AND feed the stochastic-name scan - a transform routing its RNG
        # call through Jitter().apply() must refuse exactly like an inline
        # np.random call would.  C-implemented classes (np.ndarray, ...)
        # have no inspectable method code and stay name-only.
        update(f"cell:{name}:class:{getattr(value, '__module__', '')}"
               f".{value.__qualname__}".encode())
        if id(value) in seen:
            return []
        seen.add(id(value))
        for klass in value.__mro__:
            if klass is object:
                continue
            for attr in sorted(vars(klass)):
                member = vars(klass)[attr]
                # unwrap static/class methods and properties to their code
                fn = getattr(member, "__func__", None) \
                    or getattr(member, "fget", None) or member
                code = getattr(fn, "__code__", None)
                if code is not None:
                    update(f"cell:{name}.{attr}:method".encode())
                    _hash_code_object(code, update)
                    _collect_names(code, names)
        return []
    if callable(value):
        call_code = getattr(getattr(value, "__call__", None), "__code__",
                            None)
        if call_code is None:
            # C-level callable (np ufunc, builtin): no inspectable state -
            # fold by qualified name
            qual = (f"{getattr(value, '__module__', '')}."
                    f"{getattr(value, '__qualname__', type(value).__qualname__)}")
            update(f"cell:{name}:cfunc:{qual}".encode())
            return []
        # python callable OBJECT: fold its __call__ code + instance state
        # (the same treatment _analyze gives a callable-object spec.func)
        update(f"cell:{name}:callable".encode())
        if id(value) in seen:
            return []
        seen.add(id(value))
        _hash_code_object(call_code, update)
        _collect_names(call_code, names)
        return [f"{name}.{n}" for n in
                _fold_closure(value, update, seen, names, depth + 1)]
    token = _constant_token(value)
    if token is None:
        update(f"cell:{name}:<opaque:{type(value).__name__}>".encode())
        return [name]
    update(f"cell:{name}:{token}".encode())
    return []


def _global_refs(code) -> Tuple[set, set]:
    """(names LOAD_GLOBALed, names STORE/DELETE_GLOBALed) by ``code`` and
    its nested code objects - the precise read/write sets (``co_names``
    alone conflates globals with attribute names)."""
    import dis
    import types

    loads: set = set()
    writes: set = set()
    for ins in dis.get_instructions(code):
        if ins.opname == "LOAD_GLOBAL":
            loads.add(str(ins.argval).removeprefix("NULL + "))
        elif ins.opname in ("STORE_GLOBAL", "DELETE_GLOBAL"):
            writes.add(str(ins.argval))
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            sub_loads, sub_writes = _global_refs(const)
            loads |= sub_loads
            writes |= sub_writes
    return loads, writes


def _fold_globals(func, update, seen: set, names: set,
                  depth: int = 0) -> List[str]:
    """Fold the module globals ``func`` actually reads into the digest (the
    global analog of the closure fold: a transform scaling by a module-level
    ``FACTOR`` must key the cache by its VALUE); returns opaque names.
    Writing any global marks the function opaque outright - a transform
    mutating module state is stateful by construction."""
    code = getattr(func, "__code__", None)
    if code is None:
        return []
    g = getattr(func, "__globals__", None) or {}
    loads, writes = _global_refs(code)
    opaque = [f"<writes global {n}>" for n in sorted(writes)]
    for name in sorted(loads):
        if name not in g:
            # a builtin (len, dict, range, ...): stable by name
            update(f"g:{name}:<builtin>".encode())
            continue
        opaque.extend(_fold_state(f"g:{name}", g[name], update, seen,
                                  names, depth))
    return opaque


def _fold_closure(func, update, seen: set, names: set,
                  depth: int = 0) -> List[str]:
    """Fold ``func``'s closure cells (and, for callable objects, instance
    state incl. ``__slots__`` and class-level data attributes) into the
    digest; returns the names of opaque state."""
    opaque: List[str] = []
    code = getattr(func, "__code__", None)
    cells = getattr(func, "__closure__", None) or ()
    freevars = code.co_freevars if code is not None else ()
    for name, cell in zip(freevars, cells):
        try:
            value = cell.cell_contents
        except ValueError:  # still-empty cell (recursive def mid-build)
            update(f"cell:{name}:<empty>".encode())
            continue
        opaque.extend(_fold_state(name, value, update, seen, names, depth))
    if code is None and callable(func):
        # callable object: its configuring instance state is the closure
        # analog - fold what folds, report the rest as opaque
        call = getattr(func, "__call__", None)
        if call is not None and getattr(call, "__closure__", None):
            opaque.extend(_fold_closure(call, update, seen, names, depth))
        for name, value in _instance_state(func):
            opaque.extend(_fold_state(f"self.{name}", value, update, seen,
                                      names, depth))
    return opaque


def _collect_names(code, out: set) -> None:
    """All names referenced by ``code`` and its nested code objects."""
    import types

    out.update(code.co_names)
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            _collect_names(const, out)


def _analyze(spec: "TransformSpec") -> Tuple[str, List[str], List[str]]:
    """(signature, opaque state names, stochastic names referenced) - the
    one walk both :func:`transform_signature` and
    :func:`transform_output_cacheable` share."""
    import hashlib

    digest = hashlib.md5()
    opaque: List[str] = []
    referenced: set = set()
    func = getattr(spec, "func", None)
    if func is not None:
        # plain function, or a callable object's __call__ (its configuring
        # instance state folds below like closure cells)
        code = getattr(func, "__code__", None) or getattr(
            getattr(func, "__call__", None), "__code__", None)
        if code is not None:
            _hash_code_object(code, digest.update)
            _collect_names(code, referenced)
        seen: set = {id(func)}
        opaque = _fold_closure(func, digest.update, seen, referenced)
        # the GLOBAL analog of the closure fold: module-level constants the
        # function reads key the cache by value, referenced module-level
        # helpers fold their code (AND feed the stochastic-name check - a
        # helper sampling an RNG must refuse like an inline call would),
        # and mutable/written globals mark the spec opaque (a transform
        # reading a module-level list/dict is exactly as stateful as one
        # closing over it)
        target = func if getattr(func, "__code__", None) is not None \
            else getattr(func, "__call__", None)
        if target is not None:
            opaque = opaque + _fold_globals(target, digest.update, seen,
                                            referenced)
        digest.update((f"{getattr(func, '__module__', '')}."
                       f"{getattr(func, '__qualname__', '')}."
                       f"{type(func).__qualname__}").encode())
    digest.update(repr(getattr(spec, "edit_fields", None)).encode())
    digest.update(repr(getattr(spec, "removed_fields", None)).encode())
    digest.update(repr(getattr(spec, "selected_fields", None)).encode())
    stochastic = sorted(referenced & _STOCHASTIC_NAMES)
    return digest.hexdigest()[:12], opaque, stochastic


def transform_signature(spec: Optional["TransformSpec"]) -> str:
    """Short content signature of a transform, for shared-cache keys.

    Two readers sharing the host-wide warm tier must never trade entries
    across DIFFERENT transforms (docs/operations.md "Warm cache"), so the
    cache key carries this digest.  The function half hashes the compiled
    bytecode + constants (recursively through nested code objects, so the
    digest is stable ACROSS interpreters - editing the function body changes
    the key, restarting the process does not) and degrades to the qualified
    name; CLOSURE CELLS and READ MODULE GLOBALS fold in as stable constant
    tokens (a captured or referenced function folds its own code
    recursively, so ``row_transform(f1)`` and ``row_transform(f2)`` sign
    differently and editing a module-level helper changes the key), and
    state that cannot be folded (mutable objects, written globals) is
    marked opaque - such a spec never has its OUTPUT cached
    (:func:`transform_output_cacheable`); the schema-edit half hashes the
    declared field edits.
    """
    if spec is None:
        return "-"
    return _analyze(spec)[0]


def transform_cache_info(spec: Optional["TransformSpec"]) -> Tuple[str, bool, str]:
    """(signature, output_cacheable, reason) from ONE analysis walk - the
    worker's entry point (the walk md5s bytecode and any captured arrays,
    so it must not run twice per reader); :func:`transform_signature` and
    :func:`transform_output_cacheable` are thin views of the same triple."""
    if spec is None:
        return "-", False, "no transform"
    declared = getattr(spec, "deterministic", "auto")
    func = getattr(spec, "func", None)
    sig, opaque, stochastic = _analyze(spec)
    if declared is False:
        return sig, False, "declared deterministic=False"
    if func is None:
        return sig, True, "pure field selection (no func)"
    if opaque:
        # even an explicit deterministic=True cannot overrule this: state
        # the signature cannot capture means two jobs with different state
        # would share one key - the wrong-hit the guard exists to prevent
        return sig, False, ("closure/global/instance state not foldable into"
                            f" the cache signature: {sorted(opaque)}")
    if declared is True:
        return sig, True, "declared deterministic=True"
    code = getattr(func, "__code__", None) or getattr(
        getattr(func, "__call__", None), "__code__", None)
    if code is None:
        return sig, False, "auto: no inspectable bytecode (C callable)"
    if stochastic:
        return sig, False, (f"auto: bytecode references {stochastic}"
                            " (possibly stochastic); declare"
                            " deterministic=True to assert purity")
    return sig, True, "auto: pure-bytecode heuristic"


def transform_output_cacheable(spec: Optional["TransformSpec"]) -> Tuple[bool, str]:
    """May this transform's OUTPUT be served from the warm cache?

    ``(True, why)`` only when a cached post-transform batch is provably
    interchangeable with re-running the transform: the spec declares (or the
    'auto' bytecode heuristic concludes) determinism - the name scan covers
    every captured/referenced helper function, not just the top-level body -
    AND every piece of closure/global/instance state folded into the
    signature.  Anything uncertain refuses - a wrong cache hit is silent
    data corruption, a refused one just re-runs the transform
    (docs/operations.md "Transform caching & the pipeline planner").
    """
    _sig, cacheable, reason = transform_cache_info(spec)
    return cacheable, reason


#: one-time-per-process ledger for output-caching refusal warnings
_CACHE_DISABLED_LOGGED: set = set()


def log_output_cache_disabled(spec: "TransformSpec", reason: str,
                              signature: str) -> None:
    """One-time (per spec signature, per process) notice that post-transform
    output caching is disabled for ``spec``.  Opaque-state refusals WARN
    (the user likely expected the warm win and must restructure the closure
    or accept per-epoch transforms); heuristic refusals log info (the
    conservative default doing its job)."""
    key = (signature, reason)
    if key in _CACHE_DISABLED_LOGGED:
        return
    _CACHE_DISABLED_LOGGED.add(key)
    declared = getattr(spec, "deterministic", "auto")
    if "not foldable" in reason:
        logger.warning(
            "transform output caching DISABLED for %s (deterministic=%r):"
            " %s. The transform re-runs every epoch; warm epochs still skip"
            " decode. Capture only constants (scalars, tuples, arrays) or"
            " pass state through module-level config to re-enable.",
            getattr(spec.func, "__qualname__", spec.func), declared, reason)
    else:
        logger.info(
            "transform output caching not armed for %s (deterministic=%r):"
            " %s", getattr(spec.func, "__qualname__", spec.func), declared,
            reason)


def transform_schema(schema: Schema, spec: TransformSpec) -> Schema:
    """Derive the post-transform schema (reference: transform.py:60-89)."""
    fields = list(schema)
    by_name = {f.name: i for i, f in enumerate(fields)}
    for name, dtype, shape, nullable in spec.edit_fields:
        new = Field(name, np.dtype(dtype), tuple(shape), nullable=nullable)
        if name in by_name:
            fields[by_name[name]] = new
        else:
            by_name[name] = len(fields)
            fields.append(new)
    fields = [f for f in fields if f.name not in set(spec.removed_fields)]
    if spec.selected_fields is not None:
        missing = set(spec.selected_fields) - {f.name for f in fields}
        if missing:
            raise SchemaError(f"selected_fields {sorted(missing)} not in post-transform schema")
        order = {n: i for i, n in enumerate(spec.selected_fields)}
        fields = sorted((f for f in fields if f.name in order), key=lambda f: order[f.name])
    return Schema(schema.name, fields)


def row_transform(fn: Callable[[Dict[str, object]], Dict[str, object]]):
    """Adapt a per-row dict->dict function to the columnar transform contract."""
    def columnar(columns: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        names = list(columns)
        n = len(columns[names[0]]) if names else 0
        rows = [fn({k: columns[k][i] for k in names}) for i in range(n)]
        if not rows:
            return columns
        out: Dict[str, np.ndarray] = {}
        for k in rows[0]:
            vals = [r[k] for r in rows]
            first = np.asarray(vals[0])
            if first.ndim > 0 and all(np.asarray(v).shape == first.shape for v in vals):
                out[k] = np.stack([np.asarray(v) for v in vals])
            else:
                col = np.empty(len(vals), dtype=object)
                for i, v in enumerate(vals):
                    col[i] = v
                out[k] = col if first.ndim > 0 else np.asarray(vals)
        return out
    return columnar
