"""On-device batched image augmentation: random crop + horizontal flip.

The standard ImageNet training transforms, run ON-CHIP after delivery (or
after the hybrid jpeg decode) instead of on host workers: uint8 in, uint8
out, fully batched under ``jit`` with per-image randomness derived from one
key.  Host workers stay decode-only, the augmentation costs no host CPU and
no extra host->device bytes, and XLA fuses the gather/flip into whatever
follows (normalize, first conv).

Reference analog: none - the reference leaves augmentation to the consumer
framework (torchvision/tf.image on host).  Keeping it device-side is the
TPU-first translation of that stage.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("crop_hw",))
def random_crop(images: jax.Array, key: jax.Array,
                crop_hw: Tuple[int, int]) -> jax.Array:
    """Per-image random crop of an (N, H, W, C) batch to (N, ch, cw, C)."""
    n, h, w, _ = images.shape
    ch, cw = crop_hw
    if ch > h or cw > w:
        raise ValueError(f"crop {crop_hw} larger than image {(h, w)}")
    ky, kx = jax.random.split(key)
    ys = jax.random.randint(ky, (n,), 0, h - ch + 1)
    xs = jax.random.randint(kx, (n,), 0, w - cw + 1)

    def crop_one(img, y, x):
        return jax.lax.dynamic_slice(img, (y, x, 0),
                                     (ch, cw, img.shape[-1]))

    return jax.vmap(crop_one)(images, ys, xs)


@jax.jit
def random_flip(images: jax.Array, key: jax.Array) -> jax.Array:
    """Per-image horizontal flip with probability 0.5, (N, H, W, C)."""
    flip = jax.random.bernoulli(key, 0.5, (images.shape[0],))
    return jnp.where(flip[:, None, None, None], images[:, :, ::-1, :], images)


@functools.partial(jax.jit, static_argnames=("crop_hw",))
def random_crop_flip(images: jax.Array, key: jax.Array,
                     crop_hw: Optional[Tuple[int, int]] = None) -> jax.Array:
    """Crop (when ``crop_hw`` is set) then flip - the ImageNet train pair."""
    k1, k2 = jax.random.split(key)
    if crop_hw is not None:
        images = random_crop(images, k1, crop_hw)
    return random_flip(images, k2)


@functools.partial(jax.jit, static_argnames=("alpha",))
def mixup(images: jax.Array, labels: jax.Array, key: jax.Array,
          alpha: float = 0.2):
    """Batch mixup (Zhang et al. 2017), on-chip: blend each image with a
    permuted partner using one Beta(alpha, alpha) lambda per batch.

    Returns ``(mixed_images, labels, permuted_labels, lam)``; compute the
    loss as ``lam * ce(logits, labels) + (1 - lam) * ce(logits,
    permuted_labels)``.  uint8 images mix in float32 and come back uint8;
    float images keep their dtype.
    """
    k_lam, k_perm = jax.random.split(key)
    lam = jax.random.beta(k_lam, alpha, alpha)
    lam = jnp.maximum(lam, 1.0 - lam)  # keep the dominant image first
    perm = jax.random.permutation(k_perm, images.shape[0])
    x = images.astype(jnp.float32)
    mixed = lam * x + (1.0 - lam) * x[perm]
    return _restore_dtype(mixed, images.dtype), labels, labels[perm], lam


@functools.partial(jax.jit, static_argnames=("alpha",))
def cutmix(images: jax.Array, labels: jax.Array, key: jax.Array,
           alpha: float = 1.0):
    """Batch CutMix (Yun et al. 2019), on-chip: paste one random box from a
    permuted partner into every image; one box per batch (the paper's
    formulation), so the patch becomes a static-shape masked blend.

    Returns ``(mixed_images, labels, permuted_labels, lam)`` with ``lam``
    the kept-area fraction, recomputed from the actual box.  Dtype is
    preserved exactly (pure selection, no resampling).
    """
    n, h, w, _ = images.shape
    k_lam, k_perm, k_y, k_x = jax.random.split(key, 4)
    lam0 = jax.random.beta(k_lam, alpha, alpha)
    cut = jnp.sqrt(1.0 - lam0)
    bh = (cut * h).astype(jnp.int32)
    bw = (cut * w).astype(jnp.int32)
    cy = jax.random.randint(k_y, (), 0, h)
    cx = jax.random.randint(k_x, (), 0, w)
    y0 = jnp.clip(cy - bh // 2, 0, h)
    y1 = jnp.clip(cy + bh // 2, 0, h)
    x0 = jnp.clip(cx - bw // 2, 0, w)
    x1 = jnp.clip(cx + bw // 2, 0, w)
    rows = jnp.arange(h)[None, :, None, None]
    cols = jnp.arange(w)[None, None, :, None]
    in_box = ((rows >= y0) & (rows < y1) & (cols >= x0) & (cols < x1))
    perm = jax.random.permutation(k_perm, n)
    mixed = jnp.where(in_box, images[perm], images)
    lam = 1.0 - ((y1 - y0) * (x1 - x0)) / (h * w)
    return mixed, labels, labels[perm], lam


def _restore_dtype(out: jax.Array, src_dtype) -> jax.Array:
    """float32 resample result -> the source dtype (round+clip for ints)."""
    if jnp.issubdtype(src_dtype, jnp.integer):
        info = jnp.iinfo(src_dtype)
        return jnp.clip(jnp.round(out), info.min, info.max).astype(src_dtype)
    return out.astype(src_dtype)


@functools.partial(jax.jit, static_argnames=("out_hw", "method", "antialias"))
def resize_images(images: jax.Array, out_hw: Tuple[int, int],
                  method: str = "bilinear", antialias: bool = True) -> jax.Array:
    """Batched on-chip resize of (N, H, W, C) to (N, oh, ow, C).

    Antialiased by default (``jax.image.resize`` semantics) - the scale here
    is STATIC, so XLA specializes the filter support and the cost stays
    small; uint8 inputs round-trip through float32 and come back uint8,
    float inputs keep their dtype.
    """
    n, _, _, c = images.shape
    oh, ow = out_hw
    x = images.astype(jnp.float32)
    out = jax.image.resize(x, (n, oh, ow, c), method=method,
                           antialias=antialias)
    return _restore_dtype(out, images.dtype)


@functools.partial(jax.jit,
                   static_argnames=("out_hw", "scale", "ratio", "method",
                                    "antialias"))
def random_resized_crop(images: jax.Array, key: jax.Array,
                        out_hw: Tuple[int, int],
                        scale: Tuple[float, float] = (0.08, 1.0),
                        ratio: Tuple[float, float] = (3.0 / 4.0, 4.0 / 3.0),
                        method: str = "bilinear",
                        antialias: bool = False) -> jax.Array:
    """torchvision-style RandomResizedCrop, fully on-chip and batched.

    Per image: sample a crop area fraction in ``scale`` and an aspect ratio
    log-uniform in ``ratio``, place the crop uniformly, and resize it to
    ``out_hw``.  Crop geometry varies per image but every shape is STATIC:
    the variable box becomes per-image scale/translation scalars fed to
    ``jax.lax`` scale-and-translate under ``vmap``, so XLA compiles one
    kernel for the whole batch (no dynamic shapes, no host round-trip).
    uint8 in -> uint8 out.

    ``antialias`` defaults OFF - plain bilinear sampling is the classic
    ImageNet-training behavior (torchvision pre-v2).  For this op's
    per-image traced scales the antialiased form measures near-parity on a
    v5e chip (0.7 vs 0.4 ms per 256-image batch; see
    benchmark/ops_microbench.py), so turning it on for torchvision-v2
    quality parity is fine.  Beware hand-rolled variants whose crop scale
    constant-folds at trace time: one such configuration measured 149 ms for
    the same batch - keep the scale a traced value if you fork this.
    """
    n, h, w, c = images.shape
    oh, ow = out_hw
    k_area, k_ratio, k_y, k_x = jax.random.split(key, 4)
    area_frac = jax.random.uniform(k_area, (n,), minval=scale[0],
                                   maxval=scale[1])
    log_r = jax.random.uniform(k_ratio, (n,),
                               minval=jnp.log(ratio[0]),
                               maxval=jnp.log(ratio[1]))
    r = jnp.exp(log_r)
    area = area_frac * (h * w)
    crop_w = jnp.sqrt(area * r)
    crop_h = jnp.sqrt(area / r)
    # clamp to the image (torchvision retries then falls back to center;
    # clamping keeps everything branch-free and on-chip)
    crop_w = jnp.clip(crop_w, 1.0, float(w))
    crop_h = jnp.clip(crop_h, 1.0, float(h))
    y0 = jax.random.uniform(k_y, (n,)) * (h - crop_h)
    x0 = jax.random.uniform(k_x, (n,)) * (w - crop_w)

    src_dtype = images.dtype
    x = images.astype(jnp.float32)

    def one(img, ch, cw, yy, xx):
        # map the crop box onto the (oh, ow) output grid: out = scale*in + t,
        # with translation chosen so in-coordinate y0 lands at out 0
        sy = oh / ch
        sx = ow / cw
        return jax.image.scale_and_translate(
            img, (oh, ow, c), (0, 1),
            jnp.stack([sy, sx]),
            jnp.stack([-yy * sy, -xx * sx]),
            method=method, antialias=antialias)

    out = jax.vmap(one)(x, crop_h, crop_w, y0, x0)
    return _restore_dtype(out, src_dtype)
