"""ImageNet-style ResNet-50 training feed on TPU: the flagship benchmark path.

Reference parity: examples/imagenet/ (petastorm ImageNet dataset + pytorch
feed).  TPU re-design: JPEG-compressed images are stored via
CompressedImageCodec, decoded by host workers, shipped as uint8 (1 byte/pixel
over PCIe/DCN), normalized ON-CHIP (ops.normalize_images, fused by XLA into
the first conv), and the global batch is sharded over the mesh's 'data' axis
by the loader.  Run with --steps/--rows sized for your pod; the defaults are
smoke-test sized.

This is the BASELINE.md north-star shape: samples/sec/chip feeding ResNet-50.
"""

import argparse
import os
import queue
import tempfile
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.jax import JaxDataLoader
from petastorm_tpu.models import ResNet50
from petastorm_tpu.ops import (normalize_images, random_flip,
                               random_resized_crop)
from petastorm_tpu.reader import make_reader
from petastorm_tpu.schema import Field, Schema


def imagenet_schema(side: int) -> Schema:
    return Schema("ImagenetLike", [
        Field("label", np.int64, (), ScalarCodec()),
        Field("image", np.uint8, (side, side, 3),
              CompressedImageCodec("jpeg", quality=90)),
    ])


def generate_dataset(url: str, rows: int, side: int, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    schema = imagenet_schema(side)

    def row(i):
        label = int(rng.integers(0, 1000))
        base = rng.integers(0, 255, (side, side, 3)).astype(np.uint8)
        return {"label": label, "image": base}

    write_dataset(url, schema, (row(i) for i in range(rows)),
                  row_group_size_rows=max(rows // 8, 1), mode="overwrite")


def build_tfrecord(dataset_url: str, tfr_path: str) -> None:
    """Extract the STORED jpeg bytes from the parquet dataset into a TFRecord
    so the tf.data comparator reads its native format with zero parquet
    overhead (best effort for tf.data; same bytes, same decode work)."""
    import pyarrow.dataset as pads
    import tensorflow as tf

    table = pads.dataset(dataset_url, format="parquet").to_table(
        columns=["label", "image"])
    # write-then-rename: an interrupted build must not leave a truncated
    # .tfrecord that a later 'if exists' check happily reuses
    tmp_path = tfr_path + ".tmp"
    with tf.io.TFRecordWriter(tmp_path) as w:
        for b, lbl in zip(table.column("image").to_pylist(),
                          table.column("label").to_pylist()):
            ex = tf.train.Example(features=tf.train.Features(feature={
                "image": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[b])),
                "label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[int(lbl)]))}))
            w.write(ex.SerializeToString())
    os.replace(tmp_path, tfr_path)


class TfdataDeviceFeed:
    """tf.data comparator for the north-star A/B: TFRecord -> decode_jpeg ->
    batch -> prefetch(AUTOTUNE), plus a background device-transfer thread
    (depth = ``prefetch``) so both pipelines overlap host->device copies with
    compute - the A/B then measures the INPUT pipelines, not a strawman
    synchronous ``device_put`` on the tf.data consumer path.

    Mirrors JaxDataLoader's consumer contract: ``next()`` yields a dict of
    ready device arrays and ``consumer_wait_s`` accumulates the time the
    consumer spent blocked - the input-attributable device idle.
    """

    def __init__(self, tfr_path: str, global_batch: int, prefetch: int,
                 image_sharding, label_sharding):
        import tensorflow as tf

        feat = {"image": tf.io.FixedLenFeature([], tf.string),
                "label": tf.io.FixedLenFeature([], tf.int64)}

        def _parse(raw):
            ex = tf.io.parse_single_example(raw, feat)
            return tf.io.decode_jpeg(ex["image"], channels=3), ex["label"]

        ds = (tf.data.TFRecordDataset(tfr_path).repeat()
                .map(_parse, num_parallel_calls=tf.data.AUTOTUNE,
                     deterministic=False)
                .batch(global_batch, drop_remainder=True)
                .prefetch(tf.data.AUTOTUNE))
        self._it = ds.as_numpy_iterator()
        self._image_sharding = image_sharding
        self._label_sharding = label_sharding
        self._q: "queue.Queue" = queue.Queue(maxsize=max(prefetch, 1))
        self._stop = threading.Event()
        self.consumer_wait_s = 0.0
        self._thread = threading.Thread(target=self._produce, daemon=True,
                                        name="tfdata-device-feed")
        self._thread.start()

    def _produce(self):
        try:
            while not self._stop.is_set():
                img, lbl = next(self._it)
                batch = {"image": jax.device_put(img, self._image_sharding),
                         "label": jax.device_put(lbl, self._label_sharding)}
                jax.block_until_ready(batch)  # commit in the transfer thread
                while not self._stop.is_set():
                    try:
                        self._q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as exc:  # noqa: BLE001 - re-raised in __next__
            # a silently-dead producer would block the consumer forever on
            # q.get(); ship the error as a sentinel instead (without blocking
            # past shutdown if the consumer is already gone)
            while not self._stop.is_set():
                try:
                    self._q.put(("__error__", exc), timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        batch = self._q.get()
        self.consumer_wait_s += time.perf_counter() - t0
        if isinstance(batch, tuple) and batch and batch[0] == "__error__":
            raise RuntimeError("tf.data feed producer failed") from batch[1]
        return batch

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=10)


def train(dataset_url: str, steps: int, global_batch: int, side: int,
          num_classes: int = 1000, decode: str = "device",
          workers: int = 4, prefetch: int = 2, cache: str = "null",
          input_pipeline: str = "petastorm", scan_steps: int = 1) -> dict:
    """Run ``steps`` real ResNet-50 train steps fed by the loader; returns a
    metrics dict incl. samples/sec/chip and the input-attributable device-idle
    percentage (consumer wait vs wall time over the measured window)."""
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("data",))
    model = ResNet50(num_classes=num_classes)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, side, side, 3), jnp.bfloat16))
    # replicate params across the mesh; batch is sharded over 'data'
    params = jax.device_put(params, NamedSharding(mesh, P()))
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def _step_math(p, o, image_u8, label, key):
        def loss_fn(pp):
            k1, k2 = jax.random.split(key)
            # the full ImageNet train transform, ON-CHIP: per-image
            # RandomResizedCrop (scale/ratio sampling, one static-shape
            # kernel), flip, then uint8 -> bf16 normalize - host workers
            # stay decode-only
            imgs = random_resized_crop(image_u8, k1, (side, side))
            imgs = random_flip(imgs, k2)
            x = normalize_images(imgs)          # on-chip uint8 -> bf16 + scale
            logits = model.apply(pp, x)
            onehot = jax.nn.one_hot(label, num_classes)
            return -(jax.nn.log_softmax(logits) * onehot).sum(-1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    @jax.jit
    def train_step(params, opt_state, image_u8, label, key):
        return _step_math(params, opt_state, image_u8, label, key)

    @jax.jit
    def train_scan(params, opt_state, images_u8, labels, key):
        """scan_steps train steps in ONE dispatch (images_u8: (K, B, H, W, 3)).

        On a tunneled/remote device runtime each jit call pays a fixed
        dispatch RPC (~3-4 ms here); lax.scan amortizes it K-fold, which is
        exactly the warm-cache bottleneck once ingest is out of the way.
        Same math as train_step - scan carries (params, opt_state, key).
        """
        def body(carry, xs):
            p, o, k = carry
            img, lbl = xs
            k, sub = jax.random.split(k)
            p, o, loss = _step_math(p, o, img, lbl, sub)
            return (p, o, k), loss

        (params, opt_state, _), losses = jax.lax.scan(
            body, (params, opt_state, key), (images_u8, labels))
        return params, opt_state, losses[-1]

    if input_pipeline == "tfdata":
        # the north-star comparator: SAME stored jpegs (re-packed as TFRecord,
        # tf.data's native format), SAME train_step, symmetric background
        # device transfer - only the input pipeline differs
        tfr = dataset_url.rstrip("/") + ".tfrecord"
        if not os.path.exists(tfr):
            build_tfrecord(dataset_url, tfr)
        feed = TfdataDeviceFeed(tfr, global_batch, prefetch,
                                NamedSharding(mesh, P("data")),
                                NamedSharding(mesh, P("data")))
        decode = "tfdata-host"
    else:
        # decode='device': hybrid jpeg decode - host does only entropy decode,
        # dequant + IDCT + upsample + color run on-chip (ops/jpeg.py)
        if decode == "device":
            from petastorm_tpu.native import image as native_image

            if not native_image.available():
                print("native image library unavailable; falling back to host"
                      " decode")
                decode = "host"
        placement = {"image": "device"} if decode == "device" else None
        # cache='memory' keeps decoded (or entropy-decoded, for
        # decode='device') batches in a host LRU: epochs after the first skip
        # parquet+jpeg work entirely - the answer for datasets that fit RAM
        reader = make_reader(dataset_url, num_epochs=None,
                             workers_count=workers,
                             decode_placement=placement, cache_type=cache)
        # scan mode rides the loader's first-class stacked delivery: ONE
        # (K, B, ...) transfer per K steps (stack_batches=K), not K transfers
        # + a stack dispatch hand-rolled here (VERDICT r4 item 1)
        feed = JaxDataLoader(reader, batch_size=global_batch, mesh=mesh,
                             prefetch=prefetch,
                             stack_batches=max(scan_steps, 1),
                             shardings={"image": P("data"),
                                        "label": P("data")})

    def consumer_wait(f):
        # both feeds expose the same signal: seconds the consumer spent
        # blocked waiting for a ready device batch
        return (f.diagnostics["consumer_wait_s"] if hasattr(f, "diagnostics")
                else f.consumer_wait_s)

    step = 0
    with feed:
        it = iter(feed)
        aug_key = jax.random.PRNGKey(17)

        def pull_unit():
            if scan_steps <= 1 or input_pipeline != "tfdata":
                # petastorm scan mode: the loader already delivers whole
                # (K, B, ...) stacks (stack_batches=K) in one transfer
                return next(it)
            # tfdata comparator only: tf.data has no stacked delivery, so the
            # comparator pays K transfers + a stack dispatch per unit
            bs = [next(it) for _ in range(scan_steps)]
            return {"image": jnp.stack([b["image"] for b in bs]),
                    "label": jnp.stack([b["label"] for b in bs])}

        def measure_peak_flops():
            """Same-session matmul peak probe: the MFU DENOMINATOR is
            measured, not read off a spec sheet (a tunneled chip's
            device_kind label is not proof of its speed).  Chained bf16
            4096x4096 matmuls in ONE dispatch (lax.fori_loop), timed at TWO
            iteration counts and differenced - the marginal slope cancels
            the fixed dispatch+fetch round trip (~100 ms on this tunnel).
            Every timing here ends in a VALUE FETCH, not block_until_ready:
            on the tunneled runtime block_until_ready returns immediately
            (measured: 256 chained matmuls "completed" in 30 us), so only
            fetching a result actually waits for the device.  FLOPs counted
            as 2*n^3 per matmul - the same FMA=2 convention as XLA's
            cost_analysis numerator.  (Round-5 capture: 192 TFLOP/s - the
            nominal v5e 197 within 3%.)"""
            if jax.default_backend() == "cpu":
                return None  # minutes on CPU, and MFU is a chip metric
            n = 4096
            a = jax.random.normal(jax.random.PRNGKey(0), (n, n),
                                  jnp.bfloat16) * 0.01
            b = jax.random.normal(jax.random.PRNGKey(1), (n, n),
                                  jnp.bfloat16) * 0.01

            def make_burn(iters):
                @jax.jit
                def burn(a, b):
                    out = jax.lax.fori_loop(0, iters, lambda i, c: c @ b, a)
                    return (out.astype(jnp.float32) ** 2).sum()
                return burn

            lo, hi = 128, 512
            burns = {it: make_burn(it) for it in (lo, hi)}
            for it in (lo, hi):
                float(burns[it](a, b))  # compile + settle
            # INTERLEAVED passes, min per size: the probe's own matmuls feed
            # the in-process dispatch degradation, so lo-then-hi in sequence
            # would time the two sizes under different fixed overheads and
            # bias the slope; alternating and taking minima cancels it
            best = {lo: float("inf"), hi: float("inf")}
            for _ in range(3):
                for it in (lo, hi):
                    t0 = time.perf_counter()
                    float(burns[it](a, b))  # the fetch IS the sync
                    best[it] = min(best[it], time.perf_counter() - t0)
            slope = (best[hi] - best[lo]) / (hi - lo)
            if slope <= 0:
                return None  # drift swamped the probe; fall back to nominal
            return 2 * n ** 3 / slope

        # AOT-compile the step once: the SAME executable runs the loop AND
        # reports XLA's FLOP estimate for the whole dispatch - the MFU
        # numerator comes from the compiler, not a hand-derived constant
        unit0 = pull_unit()
        fn = train_step if scan_steps <= 1 else train_scan
        exe = fn.lower(params, opt_state, unit0["image"], unit0["label"],
                       aug_key).compile()
        try:
            flops_per_dispatch = float(exe.cost_analysis()["flops"])
        except (KeyError, TypeError, IndexError):
            flops_per_dispatch = None  # backend without a cost model

        def run_unit(p, o, unit, key):
            return exe(p, o, unit["image"], unit["label"], key)

        # warmup: fill queues, settle dispatch.  Every measured window below
        # ends in a VALUE FETCH (float(loss)), never block_until_ready: the
        # tunneled runtime's block_until_ready returns without waiting
        # (verified with the peak probe above), and the loss chains through
        # every step's params, so fetching it waits for ALL queued compute -
        # the wall times below include full device completion
        params, opt_state, loss = run_unit(params, opt_state, unit0, aug_key)
        float(loss)
        # consumer wait accumulates while the consumer blocks on the prefetch
        # queue: the delta over the measured window IS the device-idle time
        # attributable to input starvation during REAL train steps
        wait0 = consumer_wait(feed)
        n_disp = 0
        t0 = time.perf_counter()
        while step < steps:
            params, opt_state, loss = run_unit(params, opt_state, pull_unit(),
                                               jax.random.fold_in(aug_key, step))
            step += max(scan_steps, 1)
            n_disp += 1
        float(loss)
        dt = time.perf_counter() - t0
        input_wait_s = consumer_wait(feed) - wait0
        # compute floor: the SAME number of dispatches on one RESIDENT unit -
        # no input pipeline inside the loop, so (dt - compute_dt) is the
        # input-attributable stall.  Unlike consumer_wait, this is valid in
        # scan mode too (consumer wait there overlaps in-flight device work).
        unit_f = pull_unit()
        p2, o2 = params, opt_state
        t1 = time.perf_counter()
        for i in range(n_disp):
            p2, o2, loss2 = run_unit(p2, o2, unit_f,
                                     jax.random.fold_in(aug_key, 1 << 20 | i))
        float(loss2)
        compute_dt = time.perf_counter() - t1
        # the probe runs LAST: this box's tunneled dispatch path degrades
        # under sustained in-process load (RESULTS.md environment caveat),
        # so running ~1300 probe matmuls BEFORE the measured windows was
        # observed to poison them (dispatch cost 4 ms -> ~70 ms)
        measured_peak = measure_peak_flops()
        diag = feed.diagnostics if hasattr(feed, "diagnostics") else {}
    samples = step * global_batch
    # per-sample FLOPs only from the SINGLE-step lowering: XLA's cost model
    # counts a lax.scan body ONCE (verified: scan=8 reports exactly 1/8th),
    # so the scan executable's figure is not per-sample-meaningful - callers
    # wanting scan-mode MFU should take flops_per_sample from a scan=1 run
    # of the same shapes (bench.py does exactly that)
    flops_per_sample = (flops_per_dispatch / global_batch
                        if flops_per_dispatch and scan_steps <= 1 else None)
    return {
        "flops_per_dispatch": flops_per_dispatch,
        "samples_per_sec": samples / dt,
        "samples_per_sec_per_chip": samples / dt / len(devices),
        "device_idle_pct": 100.0 * input_wait_s / dt,
        "input_stall_pct": 100.0 * max(0.0, dt - compute_dt) / dt,
        "compute_floor_wall_s": compute_dt,
        "flops_per_sample": flops_per_sample,
        "measured_peak_flops": measured_peak,
        "device_kind": devices[0].device_kind,
        "steps": step,
        "scan_steps": scan_steps,
        "global_batch": global_batch,
        "wall_s": dt,
        "decode": decode,
        "input": input_pipeline,
        "n_devices": len(devices),
        "final_loss": float(loss),
        "diagnostics": diag,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-url", default=None)
    parser.add_argument("--rows", type=int, default=256)
    parser.add_argument("--side", type=int, default=224)
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--global-batch", type=int, default=32)
    parser.add_argument("--decode", choices=("host", "device"), default="device",
                        help="device = hybrid on-chip jpeg decode")
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--prefetch", type=int, default=2)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--cache", choices=("null", "memory", "local-disk"),
                        default="null",
                        help="memory = host LRU; warm epochs skip all decode")
    parser.add_argument("--input", choices=("petastorm", "tfdata"),
                        default="petastorm",
                        help="tfdata = north-star comparator: same jpegs via"
                             " TFRecord + tf.data feeding the SAME train step")
    parser.add_argument("--scan-steps", type=int, default=1,
                        help="K>1 = run K train steps per dispatch via"
                             " lax.scan (amortizes the fixed per-call dispatch"
                             " RPC on tunneled/remote runtimes)")
    parser.add_argument("--skip-generate", action="store_true",
                        help="dataset-url already holds the dataset")
    parser.add_argument("--json", action="store_true",
                        help="print the metrics dict as one JSON line")
    args = parser.parse_args()
    url = args.dataset_url or tempfile.mkdtemp(prefix="imagenet_tpu_") + "/imagenet"
    if not args.skip_generate:
        generate_dataset(url, args.rows, args.side)
    m = train(url, args.steps, args.global_batch, args.side,
              num_classes=args.num_classes, decode=args.decode,
              workers=args.workers, prefetch=args.prefetch, cache=args.cache,
              input_pipeline=args.input, scan_steps=args.scan_steps)
    if args.json:
        import json

        print(json.dumps(m))
    else:
        print(f"{m['steps'] * m['global_batch']} samples in {m['wall_s']:.2f}s"
              f" = {m['samples_per_sec']:.1f} samples/sec"
              f" ({m['samples_per_sec_per_chip']:.1f} samples/sec/chip on"
              f" {m['n_devices']} chip(s)), device idle"
              f" {m['device_idle_pct']:.1f}% (input-bound), final loss"
              f" {m['final_loss']:.4f}")
