"""ColumnBatch transport over the shared-memory arena.

The process-pool data plane: workers encode each result batch into the arena
(one copy, producer side); the consumer decodes by wrapping numpy arrays
directly over shared memory (zero copies) and the block is freed automatically
when the last array from the batch is garbage collected.

Reference parity: the pluggable serializer + zmq multipart scheme
(petastorm/workers_pool/process_pool.py:317-321,254-273 and
reader_impl/arrow_table_serializer.py) - here the 'payload part' is a shm
block and the 'control part' is a small picklable descriptor.

Fallbacks keep the executor correct without the fast path: object-dtype
columns (strings, variable-shape rows) and batches that cannot fit the arena
travel inside the descriptor via the queue's normal pickling.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.native import SharedArena

logger = logging.getLogger(__name__)

_ALIGN = 64
_ALLOC_RETRY_S = 0.01


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


@dataclasses.dataclass
class ShmBatchRef:
    """Queue-picklable descriptor of a batch whose raw columns live in shm.

    Two kinds of shm-resident columns:

    * ``("shm", ...)`` entries live packed inside ONE block at ``offset``
      (producer copied them in, ``encode_batch``);
    * ``("slot", dtype, shape, offset, nbytes)`` entries were decoded
      DIRECTLY into their own arena block by the worker (batch-slot decode,
      :class:`SlotAllocator`) - no producer-side copy ever happened.  Each
      slot block gets its own consumer-side lease and is freed independently.

    ``offset`` is None when every shm column is a slot (nothing was packed).
    """
    offset: Optional[int]
    total_bytes: int
    num_rows: int
    #: name -> ("shm", dtype_str, shape, rel_offset)
    #:       | ("slot", dtype_str, shape, abs_offset, nbytes)
    #:       | ("inline", ndarray/list)
    columns: Dict[str, Tuple]
    #: ventilation ordinal carried across the shm hop so the Reader's
    #: exact-contiguous-prefix resume cursor survives the process-pool
    #: transport (ColumnBatch.ordinal semantics, batch.py:22-26)
    ordinal: Optional[int] = None


class _Lease:
    """Owns one arena block; numpy arrays built over it keep it alive (PEP 688
    buffer protocol) and the block is freed when the last array dies."""

    def __init__(self, arena: SharedArena, offset: int, size: int):
        self._arena = arena
        self._offset = offset
        self._mv = arena.view(offset, size)

    def __buffer__(self, flags):
        return memoryview(self._mv)

    def __del__(self):
        try:
            self._mv.release()
            if not self._arena._closed:  # noqa: SLF001 - arena teardown races gc
                self._arena.free(self._offset)
        except Exception:  # noqa: BLE001 - never raise from gc
            pass


# -- batch-slot decode: codec output allocated straight in the arena ---------

_SLOT_TLS = threading.local()


def current_slot_allocator() -> Optional["SlotAllocator"]:
    """The :class:`SlotAllocator` active on this thread (set by the process
    pool's shm encoder around the worker function), or None.  Codecs that can
    decode into a caller-provided buffer use it to place their output
    DIRECTLY in a shared-memory batch slot, eliminating the decode->arena
    copy hop that ``encode_batch`` otherwise pays per batch."""
    return getattr(_SLOT_TLS, "allocator", None)


class SlotAllocator:
    """Arena-backed output allocator for decode-into-batch-slot.

    Lifecycle (all on the worker's single thread):

    1. the shm encoder installs one allocator per work item;
    2. a codec asks :meth:`alloc` for its batch-shaped output array - the
       array is a writable numpy view over a fresh arena block (None when the
       arena is full or the size is unreasonable: the codec then np.empty's
       and the normal copy path applies, so this is an optimization, never a
       correctness dependency);
    3. ``encode_batch`` CLAIMS columns whose array identity matches a live
       slot - they ship as ("slot", ...) refs with zero further copies;
    4. :meth:`finalize` frees every unclaimed slot (transform replaced the
       array, encode fell back to queue pickling) - after detaching any
       unclaimed slot array still referenced by an outgoing fallback batch,
       because a freed block can be reallocated by another worker while the
       queue is still pickling the stale view.
    """

    def __init__(self, arena: SharedArena):
        self._arena = arena
        #: offset -> (nbytes, array); strong refs keep identity valid
        self._slots: Dict[int, Tuple[int, np.ndarray]] = {}
        self._claimed: set = set()

    def alloc(self, shape: Tuple[int, ...], dtype) -> Optional[np.ndarray]:
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if nbytes <= 0 or nbytes > self._arena.size // 2:
            return None
        offset = self._arena.alloc(_align(nbytes))
        if offset is None:
            return None  # arena full right now: caller uses plain memory
        count = nbytes // dtype.itemsize
        arr = np.frombuffer(self._arena.view(offset, nbytes), dtype=dtype,
                            count=count).reshape(shape)
        self._slots[offset] = (nbytes, arr)
        return arr

    def claim(self, col: np.ndarray) -> Optional[Tuple[int, int]]:
        """(offset, nbytes) when ``col`` IS a live slot array (identity, not
        equality), marking it shipped - its block is then freed by the
        consumer's lease, not by :meth:`finalize`."""
        for offset, (nbytes, arr) in self._slots.items():
            if arr is col and offset not in self._claimed:
                self._claimed.add(offset)
                return offset, nbytes
        return None

    def rollback_claims(self) -> None:
        """Un-claim everything (an encode that claimed slots then fell back
        to queue pickling ships no block refs - finalize must reclaim)."""
        self._claimed.clear()

    def finalize(self, result: Any) -> Any:
        """Free unclaimed slots; detach anything in a fallback ``result``
        that still ALIASES one (identity or a view - ``np.shares_memory``)
        by replacing it with an in-process copy first, because a freed block
        can be reallocated by another worker while the queue is still
        pickling the stale view.  Returns the (possibly rewritten) result.
        Idempotent."""
        unclaimed = [(off, arr) for off, (_, arr) in self._slots.items()
                     if off not in self._claimed]
        if unclaimed and isinstance(result, ColumnBatch):
            hit = {}
            for name, col in result.columns.items():
                if (isinstance(col, np.ndarray) and col.dtype != object
                        and any(np.shares_memory(col, arr)
                                for _, arr in unclaimed)):
                    hit[name] = col.copy()
            if hit:
                result = dataclasses.replace(
                    result, columns={**result.columns, **hit})
        for offset, _arr in unclaimed:
            try:
                self._arena.free(offset)
            except Exception:  # noqa: BLE001 - teardown best-effort
                logger.debug("slot free failed", exc_info=True)
        self._slots = {}
        return result


class _slot_scope:
    """Context manager installing a :class:`SlotAllocator` on this thread."""

    def __init__(self, allocator: Optional[SlotAllocator]):
        self._allocator = allocator

    def __enter__(self):
        self._prev = getattr(_SLOT_TLS, "allocator", None)
        _SLOT_TLS.allocator = self._allocator
        return self._allocator

    def __exit__(self, *exc):
        _SLOT_TLS.allocator = self._prev


def encode_batch(arena: SharedArena, batch: Any,
                 stop_check=None, max_wait_s: float = 10.0,
                 slots: Optional[SlotAllocator] = None) -> Any:
    """Encode a batch for the queue; raw columns go through the arena.

    Columns the worker already decoded INTO arena slots (``slots``,
    :class:`SlotAllocator`) are claimed in place - zero copies; everything
    else raw is packed into one freshly-allocated block (one copy, as
    before).  Returns a ShmBatchRef, or the original value when it is not a
    ColumnBatch or nothing can use shm (the fallback keeps behavior
    identical, just slower).  Blocks while the arena is full, up to
    ``max_wait_s`` (then falls back to queue pickling so a stalled consumer
    can never deadlock workers); ``stop_check()`` (optional) aborts the wait
    early.  Fallback returns never reference live slots - the caller's
    ``slots.finalize`` detaches them.
    """
    if not isinstance(batch, ColumnBatch):
        return batch
    shm_cols = {}
    meta: Dict[str, Tuple] = {}
    total = 0
    n_slots = 0
    for name, col in batch.columns.items():
        if isinstance(col, np.ndarray) and col.dtype != object and col.nbytes > 0:
            if slots is not None:
                claimed = slots.claim(col)
                if claimed is not None:
                    # decoded straight into its own arena block by the worker
                    # (batch-slot decode): ship the block, copy nothing
                    meta[name] = ("slot", str(col.dtype), col.shape,
                                  claimed[0], claimed[1])
                    n_slots += 1
                    continue
            # np.copyto below handles strided sources directly - no
            # ascontiguousarray (that would be a second full copy)
            meta[name] = ("shm", str(col.dtype), col.shape, total)
            shm_cols[name] = col
            total += _align(col.nbytes)
        else:
            meta[name] = ("inline", col)
    def _fallback(value):
        # no block refs ship: any claims made in the scan above must be
        # released so finalize reclaims (and detaches) those slots
        if slots is not None:
            slots.rollback_claims()
        return value

    if not shm_cols and not n_slots:
        return batch
    if total > arena.size // 2:
        # a single batch this large would serialize the whole pipeline behind
        # one block; ship it the slow way instead of deadlocking the arena
        logger.warning("batch of %d bytes exceeds half the shm arena (%d);"
                       " falling back to queue pickling", total, arena.size)
        return _fallback(batch)

    offset = None
    if shm_cols:
        offset = arena.alloc(total)
        deadline = time.monotonic() + max_wait_s
        while offset is None:
            if stop_check is not None and stop_check():
                return _fallback(batch)
            if time.monotonic() > deadline:
                logger.warning("shm arena full for %.0fs; shipping batch via"
                               " queue pickling", max_wait_s)
                return _fallback(batch)
            time.sleep(_ALLOC_RETRY_S)
            offset = arena.alloc(total)

        view = arena.view(offset, total)
        for name, col in shm_cols.items():
            _, _, _, rel = meta[name]
            dst = np.frombuffer(view, dtype=col.dtype, count=col.size,
                                offset=rel).reshape(col.shape)
            np.copyto(dst, col)
        del dst, view  # drop buffer exports so a later arena.close() can unmap
    return ShmBatchRef(offset=offset, total_bytes=total, num_rows=batch.num_rows,
                       columns=meta, ordinal=batch.ordinal)


def decode_batch(arena: SharedArena, ref: Any) -> Any:
    """Rebuild a ColumnBatch; shm columns are zero-copy views into the arena.

    Packed columns share the main block's lease; slot columns (decoded in
    place by the worker) each own their block's lease - every block is freed
    when the last array over it dies.  Non-ShmBatchRef values (fallback
    batches, arbitrary worker results) pass through unchanged."""
    if not isinstance(ref, ShmBatchRef):
        return ref
    lease = (_Lease(arena, ref.offset, ref.total_bytes)
             if ref.offset is not None else None)
    cols: Dict[str, np.ndarray] = {}
    for name, entry in ref.columns.items():
        if entry[0] == "shm":
            _, dtype_str, shape, rel = entry
            dtype = np.dtype(dtype_str)
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            cols[name] = np.frombuffer(lease, dtype=dtype, count=count,
                                       offset=rel).reshape(shape)
        elif entry[0] == "slot":
            _, dtype_str, shape, abs_off, nbytes = entry
            dtype = np.dtype(dtype_str)
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            slot_lease = _Lease(arena, abs_off, nbytes)
            cols[name] = np.frombuffer(slot_lease, dtype=dtype,
                                       count=count).reshape(shape)
        else:
            cols[name] = entry[1]
    return ColumnBatch(cols, ref.num_rows, ordinal=ref.ordinal)


def slot_column_count(ref: Any) -> int:
    """Number of ("slot", ...) columns in an encoded batch ref (0 for
    anything else) - the parent-side observability hook for the zero-copy
    decode path (``decode.batch_slots`` counter)."""
    if not isinstance(ref, ShmBatchRef):
        return 0
    return sum(1 for entry in ref.columns.values() if entry[0] == "slot")


class _ShmEncodingFn:
    """The worker's process function; ``stop_event`` is bound by the worker
    main loop so a shutdown aborts any wait on a full arena immediately.

    Installs a fresh :class:`SlotAllocator` per item so codecs under the
    worker function can decode straight into arena batch slots
    (``current_slot_allocator``); ``encode_batch`` then claims those columns
    copy-free and ``finalize`` reclaims whatever went unused.
    """

    def __init__(self, fn, arena: SharedArena):
        self._fn = fn
        self._arena = arena
        self.stop_event = None  # bound by _process_worker_main when available

    def _stopped(self) -> bool:
        return self.stop_event is not None and self.stop_event.is_set()

    def __call__(self, item):
        allocator = SlotAllocator(self._arena)
        try:
            with _slot_scope(allocator):
                result = self._fn(item)
            out = encode_batch(self._arena, result, stop_check=self._stopped,
                               slots=allocator)
            return allocator.finalize(out)
        except BaseException:
            # the work function failed after possibly allocating slots: free
            # them, or every failed item leaks arena space until close
            allocator.finalize(None)
            raise


class ShmResultEncoder:
    """Worker-side wrapper: ``fn(item)`` results are arena-encoded.

    Picklable (spawn): holds only the arena name and the inner factory; the
    arena attach and library load happen lazily in the worker process.
    """

    def __init__(self, worker_factory, arena_name: str):
        self._worker_factory = worker_factory
        self._arena_name = arena_name

    def __call__(self):
        return _ShmEncodingFn(self._worker_factory(),
                              SharedArena.attach(self._arena_name))
