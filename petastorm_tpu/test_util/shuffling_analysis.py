"""Statistical shuffle-quality analysis.

Reference parity: petastorm/test_util/shuffling_analysis.py (85 LoC) - generate an
ordered dataset, read it back with given shuffle options, and quantify how far the
read order is from the written order via rank correlation
(shuffling_analysis.py:30-52).  |rho| ~ 1 means barely shuffled; ~0 means well
decorrelated.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def rank_correlation(read_ids: np.ndarray) -> float:
    """Spearman rank correlation between read order and sequential id order."""
    read_ids = np.asarray(read_ids, dtype=np.float64)
    n = len(read_ids)
    if n < 2:
        return 1.0
    positions = np.arange(n, dtype=np.float64)
    rx = np.argsort(np.argsort(read_ids)).astype(np.float64)
    ry = np.argsort(np.argsort(positions)).astype(np.float64)
    rx -= rx.mean()
    ry -= ry.mean()
    denom = np.sqrt((rx ** 2).sum() * (ry ** 2).sum())
    return float((rx * ry).sum() / denom) if denom else 1.0


def analyze_shuffle_quality(dataset_url: str, id_field: str = "id",
                            shuffle_row_groups: bool = True,
                            shuffle_row_drop_partitions: int = 1,
                            shuffling_queue_capacity: int = 0,
                            seed: Optional[int] = 0) -> float:
    """Read the dataset and return the rank correlation of the observed order."""
    from petastorm_tpu.jax.loader import JaxDataLoader
    from petastorm_tpu.reader import make_reader

    reader = make_reader(dataset_url, schema_fields=[id_field],
                         shuffle_row_groups=shuffle_row_groups,
                         shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                         shuffle_seed=seed, reader_pool_type="serial")
    ids = []
    if shuffling_queue_capacity:
        with JaxDataLoader(reader, batch_size=16, drop_last=False,
                           shuffling_queue_capacity=shuffling_queue_capacity,
                           buffer_seed=seed) as loader:
            for b in loader:
                ids.extend(np.asarray(b[id_field]).tolist())
    else:
        with reader:
            ids = [getattr(r, id_field) for r in reader]
    return rank_correlation(np.asarray(ids))
