"""On-device batched image augmentation: random crop + horizontal flip.

The standard ImageNet training transforms, run ON-CHIP after delivery (or
after the hybrid jpeg decode) instead of on host workers: uint8 in, uint8
out, fully batched under ``jit`` with per-image randomness derived from one
key.  Host workers stay decode-only, the augmentation costs no host CPU and
no extra host->device bytes, and XLA fuses the gather/flip into whatever
follows (normalize, first conv).

Reference analog: none - the reference leaves augmentation to the consumer
framework (torchvision/tf.image on host).  Keeping it device-side is the
TPU-first translation of that stage.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("crop_hw",))
def random_crop(images: jax.Array, key: jax.Array,
                crop_hw: Tuple[int, int]) -> jax.Array:
    """Per-image random crop of an (N, H, W, C) batch to (N, ch, cw, C)."""
    n, h, w, _ = images.shape
    ch, cw = crop_hw
    if ch > h or cw > w:
        raise ValueError(f"crop {crop_hw} larger than image {(h, w)}")
    ky, kx = jax.random.split(key)
    ys = jax.random.randint(ky, (n,), 0, h - ch + 1)
    xs = jax.random.randint(kx, (n,), 0, w - cw + 1)

    def crop_one(img, y, x):
        return jax.lax.dynamic_slice(img, (y, x, 0),
                                     (ch, cw, img.shape[-1]))

    return jax.vmap(crop_one)(images, ys, xs)


@jax.jit
def random_flip(images: jax.Array, key: jax.Array) -> jax.Array:
    """Per-image horizontal flip with probability 0.5, (N, H, W, C)."""
    flip = jax.random.bernoulli(key, 0.5, (images.shape[0],))
    return jnp.where(flip[:, None, None, None], images[:, :, ::-1, :], images)


@functools.partial(jax.jit, static_argnames=("crop_hw",))
def random_crop_flip(images: jax.Array, key: jax.Array,
                     crop_hw: Optional[Tuple[int, int]] = None) -> jax.Array:
    """Crop (when ``crop_hw`` is set) then flip - the ImageNet train pair."""
    k1, k2 = jax.random.split(key)
    if crop_hw is not None:
        images = random_crop(images, k1, crop_hw)
    return random_flip(images, k2)
