"""Device-mesh utilities: shard assignment and batch shardings from the JAX runtime.

The reference's entire distributed-parallelism surface is rank arithmetic
(petastorm/reader.py:508) with rank discovered from Horovod/MPI env vars
(petastorm/spark_dataset_converter.py:124-163).  The TPU-native equivalents:

* data-shard identity  <- ``jax.process_index()/process_count()`` (the JAX
  distributed runtime already agrees on these across a pod; no env sniffing)
* delivery sharding    <- ``jax.sharding.NamedSharding`` over an explicit Mesh;
  the loader assembles global arrays with
  ``jax.make_array_from_process_local_data``, which rides ICI/DCN via XLA rather
  than any bespoke collective backend.

Consumers running tensor/sequence/expert parallelism pass their own mesh +
PartitionSpec; these helpers only cover the common data-parallel case and the
"what do I load locally" arithmetic for sequence-sharded (context-parallel)
delivery.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from petastorm_tpu.errors import PetastormTpuError


def shard_options_from_jax() -> Tuple[int, int]:
    """(cur_shard, shard_count) for make_reader, from the JAX process topology."""
    return jax.process_index(), jax.process_count()


def data_parallel_mesh(axis_name: str = "data",
                       devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or given) devices for pure data parallelism."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def sharding_for_batch(mesh: Mesh, batch_axes: Sequence[str] = ("data",),
                       spec: Optional[PartitionSpec] = None) -> NamedSharding:
    """NamedSharding for a batch array: dim 0 sharded over ``batch_axes`` (the
    data axes), other dims replicated unless an explicit spec is given."""
    if spec is None:
        spec = PartitionSpec(tuple(batch_axes) if len(batch_axes) > 1 else batch_axes[0])
    return NamedSharding(mesh, spec)


def local_data_slice(sharding: NamedSharding, global_shape: Tuple[int, ...]
                     ) -> Tuple[slice, ...]:
    """The index-slice of the *global* logical array this process must produce.

    Used by the loader to know which rows (batch axis) and which sequence range
    (context-parallel axis) to materialize host-side before
    ``jax.make_array_from_process_local_data`` assembles the global array.
    All addressable devices of one process must cover a contiguous block per
    sharded dimension (true for standard TPU meshes).
    """
    addressable = [d for d in sharding.mesh.devices.flat
                   if d.process_index == jax.process_index()]
    indices = sharding.addressable_devices_indices_map(global_shape)
    if not indices:
        raise PetastormTpuError(
            "Mesh contains no devices addressable by this process"
            f" (process_index {jax.process_index()}); build the loader's mesh"
            " from devices this host owns")
    starts = [s.start or 0 for s in next(iter(indices.values()))]
    stops = [s.stop if s.stop is not None else dim
             for s, dim in zip(next(iter(indices.values())), global_shape)]
    lo = list(starts)
    hi = list(stops)
    for dev in addressable:
        idx = indices.get(dev)
        if idx is None:
            continue
        for d, s in enumerate(idx):
            start = s.start or 0
            stop = s.stop if s.stop is not None else global_shape[d]
            lo[d] = min(lo[d], start)
            hi[d] = max(hi[d], stop)
    return tuple(slice(a, b) for a, b in zip(lo, hi))
