"""Native (C++) runtime components.

``SharedArena`` is a process-shared memory allocator used as the data plane of
the process executor - the TPU-host replacement for the reference's ZeroMQ
transport (petastorm/workers_pool/process_pool.py:52-74).  Workers copy column
payloads into the arena once; the consumer wraps them as numpy arrays with zero
additional copies and frees the block when the arrays are garbage collected.
"""

from __future__ import annotations

import ctypes
import logging
import threading
from multiprocessing import resource_tracker, shared_memory
from typing import Optional

logger = logging.getLogger(__name__)

def _configure_arena(lib: ctypes.CDLL) -> None:
    lib.psa_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.psa_init.restype = ctypes.c_int
    lib.psa_check.argtypes = [ctypes.c_void_p]
    lib.psa_check.restype = ctypes.c_int
    lib.psa_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.psa_alloc.restype = ctypes.c_int64
    lib.psa_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.psa_free.restype = ctypes.c_int
    lib.psa_free_bytes.argtypes = [ctypes.c_void_p]
    lib.psa_free_bytes.restype = ctypes.c_uint64
    lib.psa_largest_free.argtypes = [ctypes.c_void_p]
    lib.psa_largest_free.restype = ctypes.c_uint64


def _load_lib() -> Optional[ctypes.CDLL]:
    from petastorm_tpu.native.build import load_library

    return load_library("shm_arena", _configure_arena)


def is_available() -> bool:
    """True if the zero-copy transport plane works here: the native library
    builds AND the interpreter supports PEP 688 buffer-protocol leases."""
    return transport_availability()["available"]


def transport_availability() -> dict:
    """``{"available": bool, "reason": str}`` for the zero-copy transport
    plane - the *why* behind :func:`is_available`, surfaced in
    ``Reader.diagnostics['native']['shm_transport']`` and the service
    client's hello log so a silently dark fast path (e.g. python < 3.12)
    is observable instead of just slow."""
    import sys

    if sys.version_info < (3, 12):
        # zero-copy leases rely on the PEP 688 buffer protocol (__buffer__),
        # which np.frombuffer only honors from 3.12
        return {"available": False,
                "reason": f"python {sys.version_info.major}."
                          f"{sys.version_info.minor} < 3.12 (zero-copy"
                          " leases need the PEP 688 buffer protocol)"}
    if not allocator_available():
        return {"available": False,
                "reason": "native shm_arena library unavailable (no"
                          " C++ toolchain? see petastorm_tpu.native.build)"}
    return {"available": True, "reason": "ok"}


def allocator_available() -> bool:
    """True if the C arena allocator itself is usable (no interpreter-version
    gate: copy-based users like the shared warm-cache tier work on any
    python - only the transport's zero-copy leases need 3.12)."""
    return _load_lib() is not None


#: serializes the resource-tracker monkeypatch below: two concurrent
#: attaches (e.g. a warm-cache lazy attach in a worker racing the transport
#: arena attach) could otherwise interleave save/restore and leave the
#: suppressed register installed process-wide permanently
_ATTACH_LOCK = threading.Lock()


def attach_shared_memory(name: str) -> shared_memory.SharedMemory:
    """Attach an existing named segment WITHOUT registering it with the
    resource tracker.

    python<3.13 registers even *attached* segments with the resource tracker,
    which would unlink the creator's segment when this process exits (and
    sending unregister instead races other attachers into KeyErrors inside
    the shared tracker).  Suppress the registration during the constructor
    call - the creator's own registration is the only one that should exist.
    """
    with _ATTACH_LOCK:
        orig_register = resource_tracker.register

        def _no_shm_register(rname, rtype):
            if rtype != "shared_memory":
                orig_register(rname, rtype)

        resource_tracker.register = _no_shm_register
        try:
            return shared_memory.SharedMemory(name=name, create=False)
        finally:
            resource_tracker.register = orig_register


class SharedArena:
    """One shared-memory segment + the C allocator over it.

    The creator (consumer process) calls ``SharedArena.create``; workers attach
    by name with ``SharedArena.attach``.  Python's SharedMemory handles segment
    lifetime; the C library handles allocation inside it.
    """

    def __init__(self, shm: shared_memory.SharedMemory, owner: bool):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native shm_arena library unavailable")
        self._lib = lib
        self._shm = shm
        self._owner = owner
        self._closed = False    # allocation disabled (close requested)
        self._unmapped = False  # segment actually unmapped
        self._base = ctypes.addressof(ctypes.c_char.from_buffer(shm.buf))

    # -- lifecycle ------------------------------------------------------------

    @classmethod
    def create(cls, size_bytes: int, name: Optional[str] = None) -> "SharedArena":
        shm = shared_memory.SharedMemory(name=name, create=True, size=size_bytes)
        arena = cls(shm, owner=True)
        rc = arena._lib.psa_init(arena._base, shm.size)
        if rc != 0:
            shm.close()
            shm.unlink()
            raise RuntimeError(f"psa_init failed: {rc}")
        return arena

    @classmethod
    def attach(cls, name: str) -> "SharedArena":
        shm = attach_shared_memory(name)
        arena = cls(shm, owner=False)
        if not arena._lib.psa_check(arena._base):
            raise RuntimeError(f"shared arena {name!r} is not initialized")
        return arena

    @property
    def name(self) -> str:
        return self._shm.name

    @property
    def size(self) -> int:
        return self._shm.size

    @property
    def closed(self) -> bool:
        return self._closed

    def disown(self) -> None:
        """Give up unlink responsibility: ``close()`` will detach this
        process's mapping but leave the named segment alive for other
        attached processes (the warm-cache tier's lifecycle - the segment
        outlives any single reader; the creator process's resource-tracker
        registration still reclaims it at process exit)."""
        self._owner = False

    def close(self) -> None:
        """Unmap (and unlink, if owner) the segment.  If zero-copy batch views
        are still alive the close is deferred: allocation is disabled
        immediately, and a later close()/__del__ retries the unmap."""
        if self._unmapped:
            return
        # ctypes.from_buffer holds an export on shm.buf; drop it before close
        self._base = None
        self._closed = True  # no new allocs/frees; leases skip free from now on
        import gc

        gc.collect()
        try:
            self._shm.close()
        except BufferError:
            # numpy views into the segment are still alive somewhere; keep the
            # mapping open and retry on the next close()/__del__
            logger.debug("arena %s still has live views; deferring close",
                         self._shm.name)
            return
        self._unmapped = True
        if self._owner:
            self._owner = False  # unlink exactly once
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass

    def __del__(self):  # best-effort; explicit close() is the supported path
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    # -- allocation -----------------------------------------------------------

    def alloc(self, size: int) -> Optional[int]:
        """Payload offset, or None when the arena is currently full."""
        if self._closed:
            raise RuntimeError("arena is closed")
        off = self._lib.psa_alloc(self._base, size)
        if off == -2:
            raise RuntimeError("shared arena corrupted")
        return None if off < 0 else int(off)

    def free(self, offset: int) -> None:
        if self._closed:  # teardown already reclaimed everything
            return
        rc = self._lib.psa_free(self._base, offset)
        if rc != 0:
            raise RuntimeError(f"psa_free({offset}) failed: {rc}")

    def free_bytes(self) -> int:
        if self._closed:
            return 0
        return int(self._lib.psa_free_bytes(self._base))

    def largest_free(self) -> int:
        if self._closed:
            return 0
        return int(self._lib.psa_largest_free(self._base))

    def view(self, offset: int, size: int) -> memoryview:
        """Writable view of a payload region (no ownership transfer)."""
        if self._closed:
            raise RuntimeError("arena is closed")
        return self._shm.buf[offset:offset + size]
