"""Hybrid JPEG decode: host entropy half + device IDCT/color half.

Reference analog: the all-host CompressedImageCodec decode
(petastorm/codecs.py:92-118, tests/test_codec_compressed_image.py); the hybrid
split is this framework's on-device-decode design (SURVEY.md section 7 step 8).
"""

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from petastorm_tpu.errors import CodecError  # noqa: E402
from petastorm_tpu.native import image as native_image  # noqa: E402

if not native_image.available():
    pytest.skip("native image library unavailable", allow_module_level=True)


def _smooth_rgb(h, w, seed=0):
    x, y = np.meshgrid(np.arange(w), np.arange(h))
    img = np.stack([
        (np.sin(x / (9.0 + seed)) + np.cos(y / 7.0)) * 60 + 120,
        (np.sin(x / 5.0) + seed * 0.1) * 50 + 128,
        np.cos(x / 11.0) * np.sin(y / 13.0) * 55 + 120,
    ], -1)
    return img.clip(0, 255).astype(np.uint8)


def _encode(img, quality=90, sampling=None):
    params = [int(cv2.IMWRITE_JPEG_QUALITY), quality]
    if sampling is not None:
        params += [int(cv2.IMWRITE_JPEG_SAMPLING_FACTOR), sampling]
    src = img if img.ndim == 2 else cv2.cvtColor(img, cv2.COLOR_RGB2BGR)
    ok, enc = cv2.imencode(".jpeg", src, params)
    assert ok
    return enc.tobytes()


def _cv2_decode(buf, gray=False):
    flag = cv2.IMREAD_GRAYSCALE if gray else cv2.IMREAD_COLOR
    out = cv2.imdecode(np.frombuffer(buf, np.uint8), flag)
    return out if gray else cv2.cvtColor(out, cv2.COLOR_BGR2RGB)


def test_coef_layout_and_read():
    buf = _encode(_smooth_rgb(64, 96))
    layout = native_image.jpeg_coef_layout(buf)
    assert (layout.width, layout.height) == (96, 64)
    assert len(layout.components) == 3
    h0, v0, bw0, bh0 = layout.components[0]  # luma, 4:2:0 by default
    assert (bw0, bh0) == (96 // 8, 64 // 8)
    planes, qtabs, _ = native_image.read_jpeg_coefficients(buf)
    assert planes[0].shape == (bh0, bw0, 64) and planes[0].dtype == np.int16
    assert qtabs.shape == (3, 64) and qtabs.min() >= 1
    # DC of the first luma block, dequantized, reconstructs the block mean
    dc = float(planes[0][0, 0, 0]) * float(qtabs[0, 0]) / 8.0 + 128.0
    ref_mean = _cv2_decode(buf)[..., :].astype(float)
    y = (0.299 * ref_mean[..., 0] + 0.587 * ref_mean[..., 1]
         + 0.114 * ref_mean[..., 2])
    assert abs(dc - y[:8, :8].mean()) < 3.0


@pytest.mark.parametrize("sampling,name", [
    (None, "420-default"),
    (getattr(cv2, "IMWRITE_JPEG_SAMPLING_FACTOR_444", None), "444"),
    (getattr(cv2, "IMWRITE_JPEG_SAMPLING_FACTOR_422", None), "422"),
])
def test_hybrid_matches_cv2_color(sampling, name):
    if name != "420-default" and sampling is None:
        pytest.skip("cv2 build lacks sampling-factor control")
    from petastorm_tpu.ops.jpeg import decode_jpeg_column

    bufs = [_encode(_smooth_rgb(64, 96, seed=i), sampling=sampling)
            for i in range(3)]
    ours = np.asarray(decode_jpeg_column(bufs))
    refs = np.stack([_cv2_decode(b) for b in bufs])
    assert ours.shape == refs.shape == (3, 64, 96, 3)
    diff = np.abs(ours.astype(int) - refs.astype(int))
    assert diff.max() <= 6, (name, diff.max())
    assert diff.mean() < 1.0, (name, diff.mean())


def test_hybrid_grayscale():
    from petastorm_tpu.ops.jpeg import decode_jpeg_column

    imgs = [_smooth_rgb(40, 56, seed=i)[..., 0] for i in range(2)]
    bufs = [_encode(im) for im in imgs]
    ours = np.asarray(decode_jpeg_column(bufs))
    refs = np.stack([_cv2_decode(b, gray=True) for b in bufs])
    assert ours.shape == refs.shape == (2, 40, 56)
    assert np.abs(ours.astype(int) - refs.astype(int)).max() <= 4


def test_hybrid_non_multiple_of_8_and_float_output():
    import jax.numpy as jnp

    from petastorm_tpu.ops.jpeg import decode_jpeg_column

    img = _smooth_rgb(37, 53)  # forces block padding + crop
    buf = _encode(img)
    ours = np.asarray(decode_jpeg_column([buf]))
    ref = _cv2_decode(buf)
    assert ours.shape == (1, 37, 53, 3)
    assert np.abs(ours[0].astype(int) - ref.astype(int)).max() <= 6
    f = np.asarray(decode_jpeg_column([buf], out_dtype=jnp.float32))
    assert f.dtype == np.float32
    # float path skips the round/clip: same values within rounding
    assert np.abs(f[0] - ref.astype(np.float32)).max() <= 6.5


def test_column_geometry_mismatch_raises():
    bufs = [_encode(_smooth_rgb(64, 96)), _encode(_smooth_rgb(32, 96))]
    with pytest.raises(CodecError, match="geometry"):
        native_image.read_jpeg_coefficients_column(bufs)


def test_non_jpeg_raises():
    with pytest.raises(CodecError):
        native_image.jpeg_coef_layout(b"\x89PNG\r\n\x1a\nnot a jpeg")


def test_decode_coefficients_is_jittable_batch():
    """The device half traces once per geometry (static shapes) - the property
    the JAX ingest loop needs."""
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.ops.jpeg import decode_coefficients

    bufs = [_encode(_smooth_rgb(64, 96, seed=i)) for i in range(2)]
    planes, qtabs, layout = native_image.read_jpeg_coefficients_column(bufs)
    sampling = tuple((h, v) for (h, v, _, _) in layout.components)
    args = (tuple(jnp.asarray(p) for p in planes), jnp.asarray(qtabs))
    kw = dict(image_size=(layout.height, layout.width), sampling=sampling)
    out1 = decode_coefficients(*args, **kw)
    n_before = decode_coefficients._cache_size()
    out2 = decode_coefficients(*args, **kw)
    assert decode_coefficients._cache_size() == n_before  # no retrace
    assert isinstance(out1, jax.Array)
    assert np.array_equal(np.asarray(out1), np.asarray(out2))


# -- end-to-end: decode_placement='device' through reader + jax loader --------


@pytest.fixture(scope="module")
def jpeg_ds(tmp_path_factory):
    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("JpegDs", [
        Field("idx", np.int64),
        Field("image", np.uint8, (64, 96, 3), CompressedImageCodec("jpeg", quality=90)),
    ])
    rows = [{"idx": i, "image": _smooth_rgb(64, 96, seed=i)} for i in range(32)]
    url = str(tmp_path_factory.mktemp("jpeg_ds") / "ds")
    write_dataset(url, schema, rows, row_group_size_rows=8)
    return url


def test_device_decode_end_to_end(jpeg_ds):
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    with make_batch_reader(jpeg_ds, shuffle_row_groups=False, num_epochs=1) as r:
        with JaxDataLoader(r, batch_size=8, fields=["idx", "image"]) as loader:
            host_batches = [{k: np.asarray(v) for k, v in b.items()}
                            for b in loader]
    with make_batch_reader(jpeg_ds, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        assert r.device_decode_fields == ["image"]
        with JaxDataLoader(r, batch_size=8, fields=["idx", "image"]) as loader:
            dev_batches = [{k: np.asarray(v) for k, v in b.items()}
                           for b in loader]
    assert len(host_batches) == len(dev_batches) == 4
    # thread-pool results arrive in completion order: compare by idx
    host_by_idx = {int(i): hb["image"][k]
                   for hb in host_batches for k, i in enumerate(hb["idx"])}
    dev_by_idx = {int(i): db["image"][k]
                  for db in dev_batches for k, i in enumerate(db["idx"])}
    assert sorted(host_by_idx) == sorted(dev_by_idx) == list(range(32))
    for db in dev_batches:
        assert db["image"].shape == (8, 64, 96, 3) and db["image"].dtype == np.uint8
    for i in range(32):
        diff = np.abs(host_by_idx[i].astype(int) - dev_by_idx[i].astype(int))
        assert diff.max() <= 6 and diff.mean() < 1.0


def test_device_decode_on_mesh(jpeg_ds):
    import jax
    from jax.sharding import Mesh, PartitionSpec

    assert len(jax.devices()) == 8, "conftest forces the 8-device CPU platform"

    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    with make_batch_reader(jpeg_ds, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=16, mesh=mesh,
                           shardings={"idx": PartitionSpec("data"),
                                      "image": PartitionSpec("data")},
                           fields=["idx", "image"]) as loader:
            batches = list(loader)
    assert len(batches) == 2
    img = batches[0]["image"]
    assert img.shape == (16, 64, 96, 3)
    assert img.sharding.spec == PartitionSpec("data")
    # values survive the sharded decode
    host = np.asarray(img)
    assert host.std() > 10  # real image content, not zeros


def test_device_decode_rejects_png(tmp_path):
    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.errors import PetastormTpuError
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("P", [Field("image", np.uint8, (16, 16, 3),
                                CompressedImageCodec("png"))])
    url = str(tmp_path / "ds")
    write_dataset(url, schema, [{"image": _smooth_rgb(16, 16)}])
    with pytest.raises(PetastormTpuError, match="jpeg"):
        make_batch_reader(url, decode_placement={"image": "device"})
    with pytest.raises(PetastormTpuError,
                       match="'host', 'device', 'device-mixed' or 'auto'"):
        make_batch_reader(url, decode_placement={"image": "chip"})


def test_device_decode_rejects_non_jax_consumption(jpeg_ds):
    """Row iteration and the torch loaders would yield object-dtype jpeg bytes
    where the schema promises pixels; both must refuse loudly."""
    from petastorm_tpu.errors import PetastormTpuError
    from petastorm_tpu.pytorch import DataLoader
    from petastorm_tpu.reader import make_batch_reader, make_reader

    with make_reader(jpeg_ds, num_epochs=1,
                     decode_placement={"image": "device"}) as r:
        with pytest.raises(PetastormTpuError, match="JaxDataLoader"):
            next(r)
    with make_batch_reader(jpeg_ds, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with pytest.raises(PetastormTpuError, match="decode_placement='host'"):
            DataLoader(r, batch_size=4)


def test_grayscale_hw1_field_keeps_rank(tmp_path):
    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("G", [Field("image", np.uint8, (32, 48, 1),
                                CompressedImageCodec("jpeg"))])
    rows = [{"image": _smooth_rgb(32, 48, seed=i)[..., :1]} for i in range(8)]
    url = str(tmp_path / "ds")
    write_dataset(url, schema, rows)
    with make_batch_reader(url, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=8, fields=["image"]) as loader:
            b = next(iter(loader))
    assert b["image"].shape == (8, 32, 48, 1)  # schema rank honored


def test_wrong_size_jpeg_raises_clear_error(jpeg_ds, tmp_path):
    """Stored jpegs that contradict the schema shape fail loudly in the
    worker's entropy half, not with a silent wrong-shape batch."""
    import shutil

    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.errors import CodecError
    from petastorm_tpu.etl.writer import stamp_dataset_metadata
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema

    url = str(tmp_path / "ds")
    shutil.copytree(jpeg_ds, url)
    lying = Schema("JpegDs", [
        Field("idx", np.int64),
        Field("image", np.uint8, (32, 96, 3), CompressedImageCodec("jpeg"))])
    stamp_dataset_metadata(url, lying)  # stored jpegs are really 64x96
    from petastorm_tpu.errors import PetastormTpuError

    with make_batch_reader(url, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=4, fields=["image"]) as loader:
            # worker failures surface as WorkerError(PetastormTpuError)
            # carrying the remote CodecError traceback in the message
            with pytest.raises(PetastormTpuError, match="schema says"):
                list(loader)


def test_mixed_geometry_rejected_with_guidance(jpeg_ds, monkeypatch):
    """Non-uniform jpeg geometry cannot take the device path (the on-chip
    decode compiles per geometry); the worker refuses with migration
    guidance instead of silently degrading."""
    from petastorm_tpu.errors import CodecError
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    def boom(cells, **kw):
        raise CodecError("geometry differs (simulated)")

    monkeypatch.setattr("petastorm_tpu.native.image.read_jpeg_coefficients_column",
                        boom)
    from petastorm_tpu.errors import PetastormTpuError

    with make_batch_reader(jpeg_ds, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=8, fields=["idx", "image"]) as loader:
            with pytest.raises(PetastormTpuError, match="decode_placement='host'"):
                list(loader)


def _write_raw_jpeg_ds(tmp_path, bufs, rows_per_group):
    """Dataset with hand-encoded jpeg bytes (writer would re-encode), so
    tests can control per-cell subsampling."""
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.etl.writer import stamp_dataset_metadata
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("Mixed", [
        Field("idx", np.int64),
        Field("image", np.uint8, (64, 96, 3), CompressedImageCodec("jpeg"))])
    url = str(tmp_path / "mixed_ds")
    os.makedirs(url)
    table = pa.Table.from_pylist(
        [{"idx": i, "image": b} for i, b in enumerate(bufs)],
        schema=schema.as_arrow_schema())
    pq.write_table(table, os.path.join(url, "part-00000.parquet"),
                   row_group_size=rows_per_group)
    stamp_dataset_metadata(url, schema)
    return url


def test_mixed_geometry_within_rowgroup_diagnosed(tmp_path):
    """A rowgroup mixing 4:2:0 and 4:4:4 jpegs fails in the worker with the
    offending cell named and host-decode guidance - not an opaque rc."""
    s444 = getattr(cv2, "IMWRITE_JPEG_SAMPLING_FACTOR_444", None)
    if s444 is None:
        pytest.skip("cv2 build lacks sampling-factor control")
    from petastorm_tpu.errors import PetastormTpuError
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    bufs = [_encode(_smooth_rgb(64, 96, seed=i)) for i in range(6)]
    bufs[3] = _encode(_smooth_rgb(64, 96, seed=3), sampling=s444)
    url = _write_raw_jpeg_ds(tmp_path, bufs, rows_per_group=6)
    with make_batch_reader(url, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=6, fields=["image"]) as loader:
            with pytest.raises(PetastormTpuError,
                               match=r"cell 3 has geometry.*"
                                     r"decode_placement='host'"):
                list(loader)


def test_mixed_geometry_across_rowgroups_guided(tmp_path):
    """Uniform rowgroups with different subsampling: batch assembly spanning
    the boundary must raise the guided error, not a numpy shape mismatch."""
    s444 = getattr(cv2, "IMWRITE_JPEG_SAMPLING_FACTOR_444", None)
    if s444 is None:
        pytest.skip("cv2 build lacks sampling-factor control")
    from petastorm_tpu.errors import CodecError
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    bufs = ([_encode(_smooth_rgb(64, 96, seed=i)) for i in range(4)]
            + [_encode(_smooth_rgb(64, 96, seed=i), sampling=s444)
               for i in range(4, 8)])
    url = _write_raw_jpeg_ds(tmp_path, bufs, rows_per_group=4)
    with make_batch_reader(url, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=8, fields=["image"]) as loader:
            with pytest.raises(CodecError, match="decode_placement='host'"):
                list(loader)


def test_corrupt_jpeg_cell_diagnosed(tmp_path):
    """A truncated jpeg cell is reported as corruption (host decode would
    fail too), NOT as a geometry-uniformity problem."""
    from petastorm_tpu.errors import PetastormTpuError
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    bufs = [_encode(_smooth_rgb(64, 96, seed=i)) for i in range(4)]
    bufs[2] = bufs[2][:40]  # truncate mid-header
    url = _write_raw_jpeg_ds(tmp_path, bufs, rows_per_group=4)
    with make_batch_reader(url, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=4, fields=["image"]) as loader:
            with pytest.raises(PetastormTpuError,
                               match="corrupt or truncated"):
                list(loader)


def test_decode_placement_validation_errors(jpeg_ds):
    from petastorm_tpu.errors import PetastormTpuError
    from petastorm_tpu.reader import make_batch_reader

    with pytest.raises(PetastormTpuError, match="not in"):
        make_batch_reader(jpeg_ds, decode_placement={"imge": "host"})  # typo
    with pytest.raises(PetastormTpuError, match="not being read"):
        make_batch_reader(jpeg_ds, schema_fields=["idx"],
                          decode_placement={"image": "device"})
    from petastorm_tpu.predicates import in_lambda
    with pytest.raises(PetastormTpuError, match="coefficient planes"):
        make_batch_reader(jpeg_ds, decode_placement={"image": "device"},
                          predicate=in_lambda(["image"], lambda image: True))


def test_progressive_jpeg_hybrid_decode():
    """jpeg_read_coefficients runs the full entropy decode, so progressive
    streams (multi-scan) work identically to baseline."""
    from petastorm_tpu.ops.jpeg import decode_jpeg_column

    img = _smooth_rgb(64, 96)
    prog = int(getattr(cv2, "IMWRITE_JPEG_PROGRESSIVE", -1))
    if prog < 0:
        pytest.skip("cv2 build lacks progressive encoding control")
    ok, enc = cv2.imencode(".jpeg", cv2.cvtColor(img, cv2.COLOR_RGB2BGR),
                           [int(cv2.IMWRITE_JPEG_QUALITY), 90, prog, 1])
    assert ok
    buf = enc.tobytes()
    ours = np.asarray(decode_jpeg_column([buf]))[0]
    ref = _cv2_decode(buf)
    assert np.abs(ours.astype(int) - ref.astype(int)).max() <= 6


def test_device_decode_with_process_pool(jpeg_ds):
    """Coefficient-plane columns ride the process pool's shm transport
    zero-copy (fixed-shape int16/uint16/int32 arrays)."""
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    with make_batch_reader(jpeg_ds, shuffle_row_groups=False, num_epochs=1,
                           reader_pool_type="process", workers_count=2,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=8, fields=["idx", "image"]) as loader:
            batches = list(loader)
    assert len(batches) == 4
    assert all(b["image"].shape == (8, 64, 96, 3) for b in batches)
    seen = sorted(int(i) for b in batches for i in np.asarray(b["idx"]))
    assert seen == list(range(32))


def test_weighted_sampling_propagates_device_decode(jpeg_ds):
    """A weighted mix of device-decode readers feeds the jax loader (the
    coefficient-plane columns need the loader's on-chip finish), and the
    row path refuses, like a plain Reader."""
    from petastorm_tpu.errors import PetastormTpuError
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.weighted_sampling import WeightedSamplingReader

    r1 = make_batch_reader(jpeg_ds, num_epochs=1, shuffle_row_groups=False,
                           decode_placement={"image": "device"})
    r2 = make_batch_reader(jpeg_ds, num_epochs=1, shuffle_row_groups=False,
                           decode_placement={"image": "device"})
    mixed = WeightedSamplingReader([r1, r2], [0.5, 0.5], seed=0)
    assert mixed.device_decode_fields == ["image"]
    with pytest.raises(PetastormTpuError, match="JaxDataLoader"):
        next(mixed)
    with mixed:
        with JaxDataLoader(mixed, batch_size=8,
                           fields=["idx", "image"]) as loader:
            b = next(iter(loader))
    assert np.asarray(b["image"]).shape == (8, 64, 96, 3)

    # mismatched placement across sub-readers is refused up front
    r3 = make_batch_reader(jpeg_ds, num_epochs=1,
                           decode_placement={"image": "device"})
    r4 = make_batch_reader(jpeg_ds, num_epochs=1)
    try:
        with pytest.raises(PetastormTpuError, match="decode_placement"):
            WeightedSamplingReader([r3, r4], [0.5, 0.5])
    finally:
        for r in (r3, r4):
            r.stop(); r.join()


def test_producer_error_winds_down_pipeline(jpeg_ds):
    """A terminal producer error must stop the reader/executor/assembly
    threads even WITHOUT the context manager - no spinning threads left."""
    import threading
    import time

    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    before = threading.active_count()
    r = make_batch_reader(jpeg_ds, num_epochs=None, shuffle_row_groups=False)
    loader = JaxDataLoader(r, batch_size=4, fields=["idx"],
                           transform_fn=lambda cols: 1 / 0)
    with pytest.raises(ZeroDivisionError):
        next(iter(loader))
    deadline = time.monotonic() + 20
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.1)
    assert threading.active_count() <= before, "producer threads kept running"


def test_copy_dataset_migrates_mixed_geometry_for_device_decode(tmp_path):
    """The guided migration actually works: a mixed-subsampling dataset that
    the device path refuses reads fine after petastorm-tpu-copy-dataset
    re-encodes it (uniform geometry), matching the original pixels."""
    s444 = getattr(cv2, "IMWRITE_JPEG_SAMPLING_FACTOR_444", None)
    if s444 is None:
        pytest.skip("cv2 build lacks sampling-factor control")
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.tools.copy_dataset import copy_dataset

    bufs = ([_encode(_smooth_rgb(64, 96, seed=i)) for i in range(4)]
            + [_encode(_smooth_rgb(64, 96, seed=i), sampling=s444)
               for i in range(4, 8)])
    src = _write_raw_jpeg_ds(tmp_path, bufs, rows_per_group=4)
    dst = str(tmp_path / "uniform_ds")
    assert copy_dataset(src, dst, jpeg_quality=95) == 8

    with make_batch_reader(dst, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=8, fields=["idx", "image"]) as loader:
            b = next(iter(loader))
    imgs, idxs = np.asarray(b["image"]), np.asarray(b["idx"])
    assert imgs.shape == (8, 64, 96, 3)
    by_idx = {int(i): imgs[k] for k, i in enumerate(idxs)}
    for i in range(8):
        want = _smooth_rgb(64, 96, seed=i)
        # two lossy hops (original jpeg + re-encode at q95): still close
        assert np.abs(by_idx[i].astype(int) - want.astype(int)).mean() < 3.0
