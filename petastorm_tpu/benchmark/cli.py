"""``petastorm-tpu-throughput`` CLI.

Reference parity: petastorm/benchmark/cli.py:30-112 (flags for dataset url,
field regexes, warmup/measure cycles, pool type/size) plus the fresh-process
isolation mode the reference buries in throughput.py:69-91; extended with
``--method jax`` for the device feed path.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-throughput",
        description="Measure reader / device-loader throughput on a dataset")
    parser.add_argument("dataset_url", help="file:// or fsspec URL of the dataset")
    parser.add_argument("-f", "--field-regex", nargs="+", default=None,
                        help="only read fields matching these regexes")
    parser.add_argument("-n", "--warmup-cycles", type=int, default=200)
    parser.add_argument("-m", "--measure-cycles", type=int, default=1000)
    parser.add_argument("-p", "--pool-type", default="thread",
                        choices=("thread", "process", "serial"))
    parser.add_argument("-w", "--workers-count", type=int, default=3)
    parser.add_argument("--method", default="row", choices=("row", "batch", "jax"),
                        help="row=make_reader, batch=make_batch_reader, "
                             "jax=device feed via JaxDataLoader")
    parser.add_argument("--batch-size", type=int, default=32,
                        help="device batch size (--method jax only)")
    parser.add_argument("--simulated-step-ms", type=float, default=0.0,
                        help="emulate an N-ms training step between batches;"
                             " the report's input_stall_percent then reads as"
                             " device-idle%% (--method jax only)")
    parser.add_argument("--decode-device", nargs="+", default=(),
                        metavar="FIELD",
                        help="decode these jpeg fields on-chip"
                             " (decode_placement='device'; --method jax only)")
    parser.add_argument("--prefetch", type=int, default=None,
                        help="loader queue depth per producer stage"
                             " (--method jax only; default: the pipeline"
                             " planner's verdict under --autotune, else 2)")
    parser.add_argument("--no-shuffle", action="store_true",
                        help="disable rowgroup shuffling")
    parser.add_argument("--telemetry", action="store_true",
                        help="record pipeline telemetry over the reader's"
                             " whole life (warmup INCLUDED - stage counters"
                             " will exceed the measured-cycle sample count):"
                             " metrics ride the JSON output as 'metrics' and"
                             " the human output appends the pipeline"
                             " bottleneck report")
    parser.add_argument("--trace-out", metavar="PATH", default=None,
                        help="write a Chrome trace_event JSON of the run to"
                             " PATH (open in Perfetto); implies --telemetry")
    parser.add_argument("--json", action="store_true",
                        help="print one JSON line instead of human-readable text")
    parser.add_argument("--isolated", action="store_true",
                        help="re-run in a fresh interpreter for clean RSS")
    parser.add_argument("--chaos", metavar="SPEC", default=None,
                        help="measure throughput UNDER injected faults:"
                             " comma-separated ChaosSpec fields, e.g."
                             " 'decode_fail_rate=0.01,kill_ordinals=3;7,"
                             "fail_first_reads=5,seed=1' (ordinal lists use"
                             " ';'). Pair with --on-error skip so the run"
                             " survives the injected data errors"
                             " (petastorm_tpu.test_util.chaos)")
    parser.add_argument("--on-error", default="raise",
                        choices=("raise", "skip"),
                        help="reader failure policy: 'skip' quarantines"
                             " failing rowgroups and keeps reading (counts"
                             " ride telemetry as errors.*)")
    parser.add_argument("--item-deadline", type=float, default=None,
                        metavar="S",
                        help="liveness: kill+respawn (process pool) or"
                             " abandon (thread pool) a worker hung on one"
                             " work item for S seconds and requeue the item;"
                             " pair with --chaos 'hang_ordinals=...' to"
                             " measure throughput under hang recovery"
                             " (counts ride telemetry as liveness.*)")
    from petastorm_tpu.pool import parse_hedge_after

    parser.add_argument("--hedge-after", default=None, metavar="S|auto",
                        type=parse_hedge_after,
                        help="liveness: speculatively re-issue a work item"
                             " running longer than S seconds to an idle"
                             " worker, first result wins ('auto' = 4x the"
                             " telemetry decode p99; needs --telemetry)")
    parser.add_argument("--metrics-port", type=int, default=None, metavar="N",
                        help="serve live metrics in Prometheus text format"
                             " on localhost:N for the benchmark's lifetime"
                             " (0 = ephemeral); auto-enables telemetry")
    parser.add_argument("--flight-record", metavar="PATH", default=None,
                        help="on a terminal reader failure, dump the flight"
                             " record (sampled series + trace tail) to PATH"
                             " as JSONL; auto-enables telemetry")
    parser.add_argument("--autotune", action="store_true",
                        help="run the closed-loop knob tuner during the"
                             " measurement: workers / results-queue bound /"
                             " prefetch adapt to the live metrics sampler"
                             " (petastorm_tpu.autotune; decisions ride"
                             " telemetry as autotune.*)")
    parser.add_argument("--cache-type", default="null",
                        choices=("null", "memory", "local-disk", "shared"),
                        help="decoded-rowgroup cache"
                             " (docs/operations.md 'Warm cache'): 'shared' ="
                             " the host-wide warm tier - repeat this command"
                             " (or run it concurrently) to measure warm-vs-"
                             "cold; cache.* telemetry shows the hit rate")
    parser.add_argument("--cache-location", default=None, metavar="PATH",
                        help="names the cache tier (same location = same"
                             " shared tier host-wide; also the disk"
                             " directory)")
    parser.add_argument("--cache-size-mb", type=int, default=None,
                        metavar="MB",
                        help="cache size cap (shared: the L1 shm arena;"
                             " memory/local-disk: the tier's byte cap)")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    telemetry = None
    if args.telemetry or args.trace_out:
        from petastorm_tpu.telemetry import Telemetry
        telemetry = Telemetry()

    chaos = None
    if args.chaos:
        from petastorm_tpu.test_util.chaos import ChaosSpec
        chaos = ChaosSpec.parse(args.chaos)

    cache_kwargs = dict(
        cache_type=args.cache_type, cache_location=args.cache_location,
        cache_size_limit=(args.cache_size_mb * 2 ** 20
                          if args.cache_size_mb else None))

    if args.isolated:
        from petastorm_tpu.benchmark.throughput import run_isolated
        forwarded = [a for a in (argv if argv is not None else sys.argv[1:])
                     if a not in ("--isolated", "--json")]
        result = run_isolated(forwarded)
    elif args.method == "jax":
        from petastorm_tpu.benchmark.throughput import jax_loader_throughput
        result = jax_loader_throughput(
            args.dataset_url, batch_size=args.batch_size,
            warmup_batches=max(args.warmup_cycles // 25, 2),
            measure_batches=max(args.measure_cycles // 25, 8),
            pool_type=args.pool_type, workers_count=args.workers_count,
            field_regex=args.field_regex,
            shuffle_row_groups=not args.no_shuffle,
            simulated_step_s=args.simulated_step_ms / 1000.0,
            device_decode_fields=args.decode_device,
            prefetch=args.prefetch, telemetry=telemetry,
            chaos=chaos, on_error=args.on_error,
            item_deadline_s=args.item_deadline, hedge_after_s=args.hedge_after,
            metrics_port=args.metrics_port,
            flight_record_path=args.flight_record,
            autotune=args.autotune, **cache_kwargs)
    else:
        from petastorm_tpu.benchmark.throughput import reader_throughput
        result = reader_throughput(
            args.dataset_url, field_regex=args.field_regex,
            warmup_cycles=args.warmup_cycles, measure_cycles=args.measure_cycles,
            pool_type=args.pool_type, workers_count=args.workers_count,
            read_method=args.method, shuffle_row_groups=not args.no_shuffle,
            telemetry=telemetry, chaos=chaos, on_error=args.on_error,
            item_deadline_s=args.item_deadline, hedge_after_s=args.hedge_after,
            metrics_port=args.metrics_port,
            flight_record_path=args.flight_record,
            autotune=args.autotune, **cache_kwargs)

    if telemetry is not None and args.trace_out and not args.isolated:
        telemetry.export_chrome_trace(args.trace_out)

    if args.json:
        print(result.to_json())
    else:
        line = (f"{result.samples_per_sec:.2f} samples/sec "
                f"({result.samples} samples in {result.wall_s:.2f}s), "
                f"RSS {result.rss_mb:.1f} MB, CPU {result.cpu_percent:.1f}%")
        if result.input_stall_percent is not None:
            line += (f", input stall {result.input_stall_percent:.1f}%"
                     f" (prefetch depth {result.prefetch_depth_avg:.1f})")
        print(line)
        if result.planner:
            # the static planner's seed verdict (per-knob provenance), so an
            # --autotune run shows where its starting knobs came from
            from petastorm_tpu.tools.diagnose import render_planner_verdict
            print(render_planner_verdict(result.planner))
        if result.metrics:
            # metrics may come from THIS process' recorder or from the
            # isolated child's JSON snapshot; the report renders either
            from petastorm_tpu.telemetry import render_pipeline_report
            print(render_pipeline_report(result.metrics))
        if args.trace_out:
            print(f"chrome trace written to {args.trace_out}"
                  " (load in Perfetto / chrome://tracing)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
