"""Synthetic dataset generation for tests and benchmarks.

Reference parity: petastorm/tests/test_common.py:40-102 - a single TestSchema
covering every codec/dtype/nullable/variable-shape case, materialized into tmpdirs by
session fixtures (tests/conftest.py:92-126) instead of golden files; and
petastorm/generator.py (random datapoint for a schema).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from petastorm_tpu.codecs import (CompressedImageCodec, CompressedNdarrayCodec,
                                  NdarrayCodec, ScalarCodec)
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.schema import Field, Schema

TEST_SCHEMA = Schema("TestSchema", [
    Field("id", np.int64),
    Field("id2", np.int32),
    Field("partition_key", np.dtype("object")),
    Field("python_primitive_uint8", np.uint8),
    Field("image_png", np.uint8, (16, 12, 3), CompressedImageCodec("png")),
    Field("matrix", np.float32, (4, 5), NdarrayCodec()),
    Field("matrix_compressed", np.float32, (4, 5), CompressedNdarrayCodec()),
    Field("matrix_var", np.float64, (None, 2), NdarrayCodec()),
    Field("sensor_name", np.dtype("object")),
    Field("timestamp_s", np.int64),
    Field("nullable_float", np.float64, nullable=True),
])


def synthetic_rgb_image(i: int, height: int, width: int,
                        noise: float = 6.0,
                        rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Deterministic-ish smooth RGB test image (compresses like a photo, not
    like random noise) - the one generator shared by the scaling/ops
    benchmarks and stress tests instead of per-file copies."""
    x, y = np.meshgrid(np.arange(width), np.arange(height))
    base = (np.stack([np.sin(x / (7.0 + i % 5)), np.cos(y / 6.0),
                      np.sin((x + y) / 11.0)], -1) + 1) * 110
    if noise:
        base = base + (rng or np.random.default_rng(i)).normal(0, noise,
                                                               base.shape)
    return base.clip(0, 255).astype(np.uint8)


def synthetic_jpeg_bytes(n: int, height: int, width: int,
                         quality: int = 90) -> List[bytes]:
    """``n`` same-geometry jpeg streams of synthetic_rgb_image frames."""
    import cv2

    out = []
    for i in range(n):
        ok, enc = cv2.imencode(
            ".jpeg", cv2.cvtColor(synthetic_rgb_image(i, height, width),
                                  cv2.COLOR_RGB2BGR),
            [int(cv2.IMWRITE_JPEG_QUALITY), quality])
        if not ok:
            raise RuntimeError("cv2.imencode failed")
        out.append(enc.tobytes())
    return out


def random_row(schema: Schema, rng: np.random.Generator, row_index: int) -> Dict:
    """One schema-conformant random row (reference: generator.py:21-47)."""
    row: Dict = {}
    for f in schema:
        if f.name == "id":
            row[f.name] = row_index
            continue
        if f.name == "timestamp_s":
            row[f.name] = 1_000_000 + row_index
            continue
        if f.nullable and rng.random() < 0.3:
            row[f.name] = None
            continue
        if f.shape == ():
            if f.dtype.kind == "O":
                row[f.name] = f"{f.name}_{rng.integers(0, 5)}"
            elif f.dtype.kind in "ui":
                row[f.name] = int(rng.integers(0, np.iinfo(f.dtype).max // 2, dtype=f.dtype))
            elif f.dtype.kind == "f":
                row[f.name] = float(rng.random())
            elif f.dtype.kind == "b":
                row[f.name] = bool(rng.integers(0, 2))
            else:
                raise ValueError(f"no generator for {f}")
        else:
            shape = tuple(d if d is not None else int(rng.integers(1, 6)) for d in f.shape)
            if f.dtype.kind in "ui":
                row[f.name] = rng.integers(0, 255, shape).astype(f.dtype)
            else:
                row[f.name] = rng.standard_normal(shape).astype(f.dtype)
    return row


def create_test_dataset(url: str,
                        num_rows: int = 100,
                        row_group_size_rows: int = 10,
                        schema: Optional[Schema] = None,
                        seed: int = 1234,
                        **write_kwargs) -> List[Dict]:
    """Write a synthetic dataset; returns the (decoded-form) rows for assertions.

    Reference: create_test_dataset (tests/test_common.py:102+).
    """
    schema = schema or TEST_SCHEMA
    rng = np.random.default_rng(seed)
    rows = [random_row(schema, rng, i) for i in range(num_rows)]
    write_dataset(url, schema, rows, row_group_size_rows=row_group_size_rows,
                  **write_kwargs)
    return rows


def write_token_corpus(url: str, n_docs: int = 400,
                       rows_per_rg: int = 32, vocab: int = 32000,
                       mean_len: float = 48.0, min_len: int = 1,
                       max_len: int = 512, seed: int = 0,
                       label_field: Optional[str] = "lang",
                       tokens_dtype=None, **write_kwargs) -> int:
    """A north-star-shaped token corpus: ``doc_id`` + ``n_tokens`` scalars,
    a ``tokens`` variable-length int32 column (lognormal document lengths -
    the long-tail shape real corpora have), and an optional small-cardinality
    ``label_field`` for predicate tests.  Shared by the chaos-matrix token
    cells, the ci.sh sequence smoke and ``bench.py bench_sequence_packing``
    so all three measure the same corpus shape.  Returns total tokens."""
    import numpy as np

    from petastorm_tpu.sequence.dataset import token_field

    tokens_dtype = np.dtype(tokens_dtype or np.int32)
    fields = [Field("doc_id", np.int64), Field("n_tokens", np.int32),
              token_field("tokens", dtype=tokens_dtype)]
    if label_field:
        fields.append(Field(label_field, np.dtype("object")))
    schema = Schema("TokenCorpus", fields)
    rng = np.random.default_rng(seed)
    sigma = 0.75
    lengths = np.clip(rng.lognormal(np.log(mean_len) - sigma ** 2 / 2,
                                    sigma, n_docs),
                      min_len, max_len).astype(np.int64)
    rows = []
    total = 0
    for i in range(n_docs):
        n = int(lengths[i])
        total += n
        row = {"doc_id": i, "n_tokens": n,
               "tokens": rng.integers(0, vocab, n, dtype=tokens_dtype)}
        if label_field:
            row[label_field] = f"l{int(rng.integers(0, 4))}"
        rows.append(row)
    write_dataset(url, schema, rows, row_group_size_rows=rows_per_rg,
                  **write_kwargs)
    return total


def write_wide_dataset(url: str, n_cols: int = 8, n_rowgroups: int = 8,
                       rows_per_rg: int = 32, vec_len: int = 16,
                       seed: int = 0) -> None:
    """A many-column 'wide' parquet dataset: an ``id`` int64 column plus
    ``n_cols - 1`` float32 vector columns - the shape where per-column-chunk
    remote reads would hurt most.  Shared by the remote-latency tests and
    ``bench.py``'s latent-vs-local config so both measure the same dataset."""
    schema = Schema("Wide", [Field("id", np.int64)] + [
        Field(f"c{i}", np.float32, (vec_len,)) for i in range(n_cols - 1)])
    rng = np.random.default_rng(seed)
    rows = [dict({"id": i},
                 **{f"c{c}": rng.standard_normal(vec_len).astype(np.float32)
                    for c in range(n_cols - 1)})
            for i in range(n_rowgroups * rows_per_rg)]
    write_dataset(url, schema, rows, row_group_size_rows=rows_per_rg)
