"""Ingest-service dispatcher: worker registry + per-client work assignment.

The dispatcher owns each client's deterministic plan stream (the client's
Ventilator feeds it :class:`~petastorm_tpu.pool.VentilatedItem`\\ s over the
wire, in exactly the order the seeded :class:`~petastorm_tpu.plan.ReadPlan`
produced them) and assigns items to registered workers, with the same
fault-tolerance semantics the in-process pools implement:

* a worker that disconnects or misses heartbeats has its in-flight items
  **requeued** onto surviving workers through the per-item attempt budget
  (``VentilatedItem.attempt`` rides the wire, so chaos injection and
  quarantine classification behave identically to the local pools);
* an item whose budget is spent surfaces to its client as a classified
  infrastructure failure (the client raises the same ``WorkerError`` the
  pools would);
* in-worker *data* failures (corrupt rowgroup, codec error) are forwarded
  to the client unchanged - ``on_error`` skip policies quarantine them
  client-side exactly as with a local pool.

Data-plane role: the dispatcher is a **buffer relay**.  Result frames are
parsed only to their control header (ordinal, rows, payload kind); the
column payload - the ~MBs of pixel data - is forwarded to the owning
client as opaque bytes, never decoded, never unpickled
(:mod:`petastorm_tpu.service.protocol`).  Work items likewise cross the
dispatcher as :class:`~petastorm_tpu.service.protocol.WireItem`\\ s:
structural scheduling metadata (ordinal, attempt, rowgroup-affinity key)
plus an opaque blob only the assigned worker opens.  The wire-encoding mix
is metered per relayed result (``service.frames_binary`` /
``frames_pickle_fallback`` / ``frames_shm``) so a hot pickle fallback is
visible, not silent.

Delivery is exactly-once per client: results are buffered until the client
**acks** them, so a dropped client connection replays unacked results on
reconnect and the client-side per-ordinal ledger dedups any overlap.

Rowgroup affinity: items are routed by a stable hash of their rowgroup so
repeated reads of one rowgroup (two clients on one dataset) prefer the same
worker - and co-located workers sharing a ``cache_type='shared'`` warm tier
decode each rowgroup once fleet-wide regardless.

Fleet sizing: clients piggyback their consumer starved-seconds (the
``queue.results_empty_wait_s`` signal petastorm_tpu.autotune drives worker
counts with) and :meth:`Dispatcher.scaling_signal` turns the aggregate into
a grow/ok/shrink recommendation plus a ``service.scale_pressure`` gauge.
:class:`~petastorm_tpu.service.autoscale.AutoscaleSupervisor` (CLI
``petastorm-tpu-service autoscale``) closes the loop: it polls the signal
and spawns/retires local worker processes (or invokes an ``--exec-hook``
for k8s-style orchestrators).  Retirement is **graceful**: a worker sends a
``retiring`` frame, the dispatcher marks it draining (no new assignments),
the worker finishes its in-flight items, flushes its outbox, and says
``bye`` - so ``deterministic='seed'`` streams stay bit-identical through
scale events (docs/operations.md "Fleet autoscaling & QoS").

Multi-tenant QoS: client hellos carry a ``weight`` (long-run share within a
priority tier) and a ``priority`` (strict tiers: a lower tier is served
only while no higher tier has pending work).  Assignment is weighted
deficit-round-robin, so a greedy trainer cannot starve its peers - and
admission control (``max_clients``, ``max_client_inflight``) bounds what
any one client (or an unbounded client herd) can occupy.  Per-client
weights/priorities/assigned shares are exact and unbounded in
``stats()['qos']``; refusals and cap deferrals ride ``service.qos.*``
counters.

Crash recovery (docs/operations.md "Fault domains"): the dispatcher's
state is **reconstructible from its peers**, so its own death is a
recoverable event, not an epoch abort.  A fresh dispatcher starts empty;
then

* clients re-hello with their job blob and resync their per-ordinal
  in-flight ledgers (unresolved items are re-sent; the ledger plus the
  reader's reorder stage keep delivery exactly-once and
  ``deterministic='seed'`` streams bit-identical through the outage) -
  counted as ``service.sessions_reconstructed``;
* workers rejoin (``--reconnect-attempts``) *without dropping their
  in-flight work*: the rejoin hello reports the assignments they are
  still executing, which the dispatcher records as **claims** so a
  client's resync re-attaches those ordinals to the executing worker
  instead of double-assigning them (``service.worker_rejoins`` /
  ``service.recovered_assignments``);
* a result finishing before its client has reconnected is buffered as an
  **orphan** (``service.orphan_results_buffered``) and replayed the moment
  the client's hello lands.

``journal_path`` arms the optional warm restart
(:mod:`petastorm_tpu.service.journal`): sessions replay from disk before
the listener opens, and reconnecting clients are told which ordinals are
already held (``hello_ok``'s ``known`` list) so their resync skips
re-sends.

High availability (docs/operations.md "Dispatcher HA"): a second
dispatcher started with ``standby_of='host:port'`` (CLI ``--standby-of``)
is a **hot standby** - it tails the primary's session journal over the
wire (``standby_hello`` -> ``journal_sync`` frames, fed from
:meth:`ServiceJournal.attach_tail`; the journal mirror is live even
without a ``--journal`` file) and keeps every client session warm.  While
standing by it refuses client/worker hellos (serving only ``stats?``,
which reports its sync lag); when the primary dies - connection lost AND
re-sync probes refused, after at least one successful sync - it
**promotes**: adopts the mirrored sessions, bumps the fencing *epoch*
past the primary's, and serves.  Clients and workers reach it through a
failover address list (``service_address='primary:p,standby:p'``), so a
failover costs one re-hello against already-warm state instead of a full
peer reconstruction (``service.failovers`` counts promotions;
``service.standby_lag_items`` meters how far a standby trails).

Split-brain fencing: every ``hello_ok`` and heartbeat reply (``hb_ok``)
carries the dispatcher's monotonic **epoch**.  A plain restart keeps its
journal-stored epoch (peers accept an equal epoch); a promotion bumps to
``primary_epoch + 1``; peers remember the highest epoch they have seen
and refuse anything lower (``service.stale_epoch_refusals``) - so a
deposed primary that comes back after its standby took over is refused
by its own fleet, no matter how often it restarts from its own journal.

Redelivery-buffer bound: unacked result *bodies* are capped at
``replay_buffer_bytes`` (gauge ``service.replay_buffer_bytes``).  On
overflow the oldest already-sent (or disconnected-client) bodies degrade
to header-only tombstones (``service.replay_bodies_dropped``): they are
dropped from the replay set and from ``known`` ordinals, which forces the
client's resync to re-enqueue exactly those items - re-fetch instead of
replay, bounded memory instead of an unbounded body buffer.
"""

from __future__ import annotations

import collections
import logging
import os
import queue
import socket
import threading
import time
import uuid
import zlib
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

from petastorm_tpu.errors import DEFAULT_REQUEUE_ATTEMPTS, PetastormTpuError
from petastorm_tpu.pool import VentilatedItem
from petastorm_tpu.service.protocol import (PROTOCOL_VERSION,
                                            FrameClosedError, FrameSocket,
                                            LegacyPickleFrameError, WireItem,
                                            connect_frames, parse_address_list,
                                            resolve_auth_token, token_matches)
from petastorm_tpu.service.wire import (SUPPORTED_CODECS, WireFormatError,
                                        negotiate_codec)
from petastorm_tpu.telemetry import resolve as _resolve_telemetry

logger = logging.getLogger(__name__)

#: telemetry counter prefixes a worker heartbeat may fold into the
#: dispatcher's registry as ``service.fleet.<name>`` (fleet-wide decode /
#: cache accounting - the observable proof of decode-once sharing; the
#: ``service.`` entry folds the workers' own wire-encoding mix and stage
#: counters so encode-side behavior is visible at the control plane)
FLEET_COUNTER_PREFIXES = ("decode.", "worker.", "cache.", "io.", "service.",
                          "stage.service.")


def compute_recommendation(pressure: float, threshold: float, pending: int,
                           capacity: int, busy_fraction: float,
                           clients: int) -> str:
    """The grow/ok/shrink rule, shared by :meth:`Dispatcher.scaling_signal`
    and the autoscale supervisor's remote ``stats`` probes (so a supervisor
    overriding ``starved_threshold`` re-judges the same raw fields the
    dispatcher published, with no second copy of the rule).

    * ``grow``: connected clients are starved past ``threshold`` (or there
      is no capacity at all) **and work is actually queued** - growing a
      fleet with an empty queue adds idle workers no matter how starved
      the consumers are (their bottleneck is elsewhere, e.g. their own
      in-flight window or the wire).
    * ``shrink``: capacity exists but is essentially idle (busy < 10%,
      nothing pending, pressure well under threshold) - including a fleet
      whose clients all left.
    * else ``ok``.
    """
    if clients and pending > 0 and (pressure > threshold or not capacity):
        return "grow"
    if capacity and busy_fraction < 0.1 and pending == 0 \
            and pressure < threshold / 4:
        return "shrink"
    return "ok"


class _WorkerState:
    __slots__ = ("name", "conn", "capacity", "hostname", "inflight",
                 "last_heartbeat", "busy", "jobs_sent", "gone", "codecs",
                 "draining", "counters", "hists")

    def __init__(self, name: str, conn: FrameSocket, capacity: int,
                 hostname: str, codecs=()):
        self.name = name
        self.conn = conn
        self.capacity = max(1, int(capacity))
        self.hostname = hostname
        #: wire codecs this worker can compress BATCH bodies with
        self.codecs = tuple(codecs or ())
        #: (client_id, ordinal) assignments awaiting a result
        self.inflight: Set[Tuple[str, int]] = set()
        self.last_heartbeat = time.monotonic()
        self.busy = 0
        self.jobs_sent: Set[str] = set()
        self.gone = False
        #: graceful retirement: a draining worker finishes its in-flight
        #: items but is never assigned new ones (the ``retiring`` frame)
        self.draining = False
        #: fleet aggregation: cumulative per-worker counter totals (folded
        #: from heartbeat deltas) and the latest cumulative histogram
        #: snapshots the worker shipped - the raw material for the
        #: ``fleet?`` frame and the per-worker-labeled Prometheus families
        self.counters: Dict[str, float] = {}
        self.hists: Dict[str, Dict] = {}


class _Assignment:
    __slots__ = ("item", "worker", "assigned_at")

    def __init__(self, item: VentilatedItem, worker: str):
        self.item = item
        self.worker = worker
        self.assigned_at = time.monotonic()


class _ClientState:
    __slots__ = ("client_id", "conn", "factory", "hostname", "shm_ok",
                 "max_requeue", "pending", "inflight", "unacked", "rows",
                 "results", "requeued", "connected", "disconnected_at",
                 "codecs", "weight", "priority", "deficit", "assigned")

    def __init__(self, client_id: str, conn: Optional[FrameSocket],
                 factory: bytes, hostname: str, shm_ok: bool,
                 max_requeue: int, codecs=(), weight: float = 1.0,
                 priority: int = 0):
        self.client_id = client_id
        #: None for a journal-restored session awaiting its reconnect
        self.conn = conn
        self.factory = factory
        self.hostname = hostname
        self.shm_ok = shm_ok
        self.max_requeue = max_requeue
        #: wire codecs this client can decompress BATCH bodies of
        self.codecs = tuple(codecs or ())
        #: items awaiting assignment (requeues go to the FRONT so a
        #: recovered item does not wait behind a whole epoch)
        self.pending: Deque[WireItem] = collections.deque()
        #: ordinal -> _Assignment at a worker
        self.inflight: Dict[int, _Assignment] = {}
        #: ordinal -> outcome frame delivered but not yet acked (replayed
        #: verbatim on reconnect; bounded by the client's in-flight window)
        self.unacked: Dict[int, Dict] = {}
        self.rows = 0
        self.results = 0
        self.requeued = 0
        self.connected = True
        self.disconnected_at: Optional[float] = None
        #: QoS: long-run share within this client's priority tier (weighted
        #: deficit-round-robin) and its strict-priority tier (higher first)
        self.weight = max(1e-6, float(weight))
        self.priority = int(priority)
        #: the WDRR deficit counter: refilled by ``weight`` per scheduler
        #: round, spent one unit per assigned item, reset when the client's
        #: pending queue empties (classic DRR - no idle-time credit burst)
        self.deficit = 0.0
        #: total items ever assigned (exact + unbounded - the per-client
        #: telemetry counter names are capped, this is not)
        self.assigned = 0

    def known_ordinals(self) -> Set[int]:
        """Ordinals a resync must NOT re-enqueue.  Body-dropped unacked
        tombstones (``_stale``) are excluded on purpose: their outcome can
        no longer be replayed, so the resync re-enqueueing them IS the
        documented re-fetch path of the bounded redelivery buffer."""
        known = set(self.inflight)
        known.update(o for o, out in self.unacked.items()
                     if not out.get("_stale"))
        known.update(i.ordinal for i in self.pending)
        return known


class Dispatcher:
    """The ingest-service control plane (one process serves many clients).

    ``heartbeat_timeout_s``: a worker silent this long is declared dead and
    its in-flight items requeue (socket EOF - the common death - is
    detected immediately; the timeout covers a worker whose heartbeat
    thread died with the process).  A worker wedged INSIDE user decode/IO
    code keeps heartbeating - that failure mode needs
    ``assignment_deadline_s``: when set, an assignment with no outcome for
    that long declares its worker hung and drops it (connection closed ->
    the worker process exits; its items requeue through the budget) - the
    service-plane analog of the process pool's SIGKILL-and-respawn.  Off
    by default, like ``item_deadline_s`` locally; size it WELL above the
    slowest legitimate rowgroup decode.
    ``client_grace_s``: a disconnected client's state (pending + in-flight
    + unacked results) is kept this long for a reconnect before purging.
    ``max_requeue_attempts``: default per-item budget; each client's hello
    may carry its own (the reader's ``on_error`` policy budget travels with
    the job, keeping service and in-process semantics identical).
    ``auth_token``: shared handshake secret; defaults to
    ``$PETASTORM_TPU_SERVICE_TOKEN``.  When set, every hello (worker,
    client, stats) must present it or the connection is refused.  The v2
    wire is pickle-free binary frames (the token gates who may ship jobs
    to the fleet, not frame parsing) - see the protocol module's
    trust-boundary notes.
    ``wire_codec``: BATCH-body compression policy, negotiated per
    (worker, client) pair at job time - ``'auto'`` (default; compress
    cross-host hops only), ``'off'``, or a codec name to force it
    everywhere both ends support it.  Defaults to
    ``$PETASTORM_TPU_SERVICE_COMPRESSION`` when unset.
    ``journal_path``: arm the warm-restart session journal (CLI
    ``--journal``; see :mod:`petastorm_tpu.service.journal`) - cold
    recovery from peers works without it.
    ``journal_fsync``: fsync the journal file per appended record (CLI
    ``--journal-fsync``; metered as ``service.journal_fsyncs``).  Default
    off: the flush-per-record journal already survives a process death,
    and the fsync only buys back the OS-buffered tail a host power-loss
    would eat - at a device round-trip per control-plane record.  Turn it
    on when a standby will warm-restart from this file and the host (not
    just the process) is in the fault model.
    ``standby_of``: run as a HOT STANDBY of the primary at this
    ``'host:port'`` (or failover list): tail its journal over the wire,
    refuse client/worker hellos until the primary dies, then promote with
    a bumped fencing epoch (module docstring; CLI ``--standby-of``).
    ``replay_buffer_bytes``: cap on retained unacked result *bodies*
    across all clients; overflow degrades the oldest to header-only
    tombstones whose clients re-fetch on reconnect (module docstring).
    ``starved_threshold``: the pressure level (starved-seconds per second)
    above which :meth:`scaling_signal` recommends ``grow`` (CLI
    ``--starved-threshold``); defaults to the in-process autotune loop's
    ``AutotunePolicy.starved_threshold`` so the fleet and a local pool
    judge "the worker plane is the bottleneck" identically.
    ``max_clients``: admission control - a NEW client hello past this many
    CONNECTED sessions is refused (``service.qos.admission_refused``;
    reconnects of admitted sessions always pass, and a crashed trainer
    riding out its reconnect grace does not hold a seat against its
    replacement).  Note a dispatcher restart re-admits sessions
    first-come-first-served, so a herd larger than the cap can lose
    members across a restart.  Default None = unbounded.
    ``max_client_inflight``: per-client cap on items in flight at workers;
    a client at the cap is skipped by the assignment loop until results
    return (``service.qos.capped_deferrals``), so one greedy trainer with
    a huge window degrades itself, not the fleet.  Default None = bounded
    only by the client's own window.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 telemetry=None,
                 heartbeat_timeout_s: float = 10.0,
                 client_grace_s: float = 30.0,
                 max_requeue_attempts: int = DEFAULT_REQUEUE_ATTEMPTS,
                 assignment_deadline_s: Optional[float] = None,
                 metrics_port: Optional[int] = None,
                 auth_token: Optional[str] = None,
                 wire_codec: Optional[str] = None,
                 journal_path: Optional[str] = None,
                 journal_fsync: bool = False,
                 standby_of: Optional[str] = None,
                 replay_buffer_bytes: int = 256 << 20,
                 starved_threshold: Optional[float] = None,
                 max_clients: Optional[int] = None,
                 max_client_inflight: Optional[int] = None):
        if assignment_deadline_s is not None and assignment_deadline_s <= 0:
            raise PetastormTpuError(
                "assignment_deadline_s must be > 0 or None")
        if starved_threshold is not None and starved_threshold < 0:
            raise PetastormTpuError("starved_threshold must be >= 0 or None")
        if max_clients is not None and max_clients < 1:
            raise PetastormTpuError("max_clients must be >= 1 or None")
        if max_client_inflight is not None and max_client_inflight < 1:
            raise PetastormTpuError(
                "max_client_inflight must be >= 1 or None")
        if wire_codec is None:
            wire_codec = os.environ.get(
                "PETASTORM_TPU_SERVICE_COMPRESSION", "auto")
        if wire_codec not in ("auto", "off") + SUPPORTED_CODECS:
            raise PetastormTpuError(
                f"wire_codec must be 'auto', 'off' or one of"
                f" {SUPPORTED_CODECS}; got {wire_codec!r}")
        self._wire_codec = wire_codec
        self._host = host
        self._requested_port = port
        self._heartbeat_timeout_s = float(heartbeat_timeout_s)
        self._client_grace_s = float(client_grace_s)
        self._assignment_deadline_s = assignment_deadline_s
        self._starved_threshold = starved_threshold
        self._max_clients = max_clients
        self._max_client_inflight = max_client_inflight
        self._max_requeue = int(max_requeue_attempts)
        self._auth_token = resolve_auth_token(auth_token)
        self.telemetry = _resolve_telemetry(telemetry)
        self._lock = threading.RLock()
        self._workers: Dict[str, _WorkerState] = {}
        self._clients: Dict[str, _ClientState] = {}
        self._client_order: List[str] = []  # round-robin fairness cursor
        self._rr = 0
        self._stop_event = threading.Event()
        self._threads: List[threading.Thread] = []
        self._listener: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self._started_at = time.monotonic()
        #: (monotonic, starved_s delta) reports from clients - the fleet
        #: pressure window (scaling_signal)
        self._starved_reports: Deque[Tuple[float, float]] = collections.deque(
            maxlen=512)
        self._worker_seq = 0
        self._client_counter_ids: Set[str] = set()
        self._counter_cap_warned = False
        self._metrics_port = metrics_port
        self.metrics_server = None
        #: identifies THIS dispatcher process across restarts: rides every
        #: client hello_ok so peers can count service.dispatcher_restarts
        self.boot_id = uuid.uuid4().hex[:12]
        #: (client_id, ordinal) -> (worker name, claimed-at) for rejoining
        #: workers' still-executing assignments whose client has not
        #: reconnected yet (honored at resync; swept after client_grace_s)
        self._claims: Dict[Tuple[str, int], Tuple[str, float]] = {}
        #: (client_id, ordinal) -> (outcome frame, buffered-at) for results
        #: that finished before their client reconnected
        self._orphan_results: Dict[Tuple[str, int], Tuple[Dict, float]] = {}
        #: retained result-body accounting (the bounded redelivery buffer):
        #: insertion-ordered (cid, outcome-dict) refs + live byte total
        self._replay_order: Deque[Tuple[str, Dict]] = collections.deque()
        self._replay_bytes = 0
        self._replay_cap = int(replay_buffer_bytes)
        self._journal = None
        self._journal_path = journal_path
        self._journal_fsync = bool(journal_fsync)
        # -- hot-standby HA state (module docstring "High availability") --
        self._standby_of = standby_of
        if standby_of is not None:
            parse_address_list(standby_of)  # fail fast on a bad address
        #: True while this dispatcher is a warm follower (refusing client/
        #: worker hellos); flips False exactly once, at promotion
        self._standby = standby_of is not None
        #: split-brain fencing epoch: rides every hello_ok / hb_ok; a
        #: restart keeps its journal-stored value, a promotion bumps past
        #: the primary's, and peers refuse anything below their max seen
        self.epoch = 1
        #: set when a standby promotes itself to primary (tests/operators)
        self.standby_promoted = threading.Event()
        self._primary_epoch = 0
        self._primary_boot: Optional[str] = None
        self._standby_synced = 0
        self._standby_lag = 0
        self._sync_warned = False
        #: primary-side standby health: peer address -> last journal seq
        #: fed to it (stats()['ha'] derives standby_lag_items from the gap
        #: to the live journal seq, so an operator sees standby sync state
        #: from the PRIMARY's one-shot stats probe)
        self._standby_feeds: Dict[str, int] = {}
        #: bounded fleet event log (tentpole d): structured control-plane
        #: events (promotions, fencing refusals, requeues, drains, worker
        #: lifecycle, autoscale decisions) - served by the ``events?``
        #: frame so a failing client can capture the fleet's last ~60s
        self._events: Deque[Dict] = collections.deque(maxlen=512)
        # -- service.* telemetry (rides the registry -> Prometheus/--watch) --
        tele = self.telemetry
        self._g_workers = tele.gauge("service.registered_workers")
        self._g_clients = tele.gauge("service.connected_clients")
        self._g_pending = tele.gauge("service.pending_items")
        self._g_inflight = tele.gauge("service.inflight_items")
        self._g_pressure = tele.gauge("service.scale_pressure")
        self._m_assigned = tele.counter("service.assigned_items")
        self._m_completed = tele.counter("service.completed_items")
        self._m_requeued = tele.counter("service.requeued_items")
        self._m_failures = tele.counter("service.forwarded_failures")
        self._m_dup = tele.counter("service.duplicate_results")
        self._m_bytes_in = tele.counter("service.frame_bytes_received")
        self._m_bytes_out = tele.counter("service.frame_bytes_sent")
        self._m_rows = tele.counter("service.client_rows")
        # wire-encoding mix of relayed results: the pickle fallback being
        # hot must be VISIBLE (ci.sh asserts frames_pickle_fallback == 0
        # on the result path of its smoke topology)
        self._m_frames_bin = tele.counter("service.frames_binary")
        self._m_frames_pkl = tele.counter("service.frames_pickle_fallback")
        self._m_frames_shm = tele.counter("service.frames_shm")
        # -- crash-recovery observability (module docstring) --
        self._m_sessions_rec = tele.counter("service.sessions_reconstructed")
        self._m_worker_rejoins = tele.counter("service.worker_rejoins")
        self._m_recovered = tele.counter("service.recovered_assignments")
        self._m_resync_restored = tele.counter(
            "service.resync_items_restored")
        self._m_orphans = tele.counter("service.orphan_results_buffered")
        self._m_replay_dropped = tele.counter("service.replay_bodies_dropped")
        self._m_refetches = tele.counter("service.replay_refetches_forced")
        self._m_journal_items = tele.counter("service.journal_items_restored")
        self._g_replay_bytes = tele.gauge("service.replay_buffer_bytes")
        # -- multi-tenant QoS observability (module docstring) --
        self._m_admission_refused = tele.counter(
            "service.qos.admission_refused")
        self._m_capped_deferrals = tele.counter("service.qos.capped_deferrals")
        self._m_drains = tele.counter("service.qos.workers_draining")
        self._g_priority_tiers = tele.gauge("service.qos.priority_tiers")
        # -- hot-standby HA observability (module docstring) --
        self._m_failovers = tele.counter("service.failovers")
        self._m_journal_fsyncs = tele.counter("service.journal_fsyncs")
        self._m_standby_refused = tele.counter("service.standby_hello_refused")
        self._g_standby_lag = tele.gauge("service.standby_lag_items")
        self._g_epoch = tele.gauge("service.epoch")

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "Dispatcher":
        """Bind the listener (``self.port`` is then live) and start the
        accept + monitor threads; returns self for chaining.  With a
        ``journal_path``, sessions replay from disk BEFORE the listener
        opens - a reconnecting client never races its own restoration."""
        if self._standby:
            # a standby's state arrives over journal_sync, never from its
            # own file: the journal stays an unloaded in-memory mirror
            # until promotion opens (and compacts warm state into) the file
            from petastorm_tpu.service.journal import ServiceJournal

            self._journal = ServiceJournal(
                self._journal_path, fsync=self._journal_fsync,
                fsync_counter=self._m_journal_fsyncs)
        else:
            self._restore_journal()
        self._g_epoch.set(self.epoch)
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self._host, self._requested_port))
        listener.listen(64)
        listener.settimeout(0.2)
        self._listener = listener
        self.port = listener.getsockname()[1]
        for target, name in ((self._accept_loop, "accept"),
                             (self._monitor_loop, "monitor")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"petastorm-tpu-dispatcher-{name}")
            t.start()
            self._threads.append(t)
        if self._metrics_port is not None and self.telemetry.enabled:
            from petastorm_tpu.telemetry.export import MetricsExportServer

            self.metrics_server = MetricsExportServer(
                self.telemetry, port=self._metrics_port,
                extra=self._fleet_prometheus)
            self.metrics_server.start()
        logger.info("Dispatcher listening on %s:%d", self._host, self.port)
        if self._standby:
            t = threading.Thread(target=self._standby_loop, daemon=True,
                                 name="petastorm-tpu-dispatcher-standby")
            t.start()
            self._threads.append(t)
            logger.info("Dispatcher is a hot STANDBY of %s (refusing client/"
                        "worker hellos until promotion)", self._standby_of)
        if self._auth_token is None and self._host not in (
                "127.0.0.1", "localhost", "::1"):
            logger.warning(
                "Dispatcher is listening on %s with NO auth token: anyone"
                " who can reach this port can register as a client and ship"
                " a worker factory the fleet will execute (the v2 binary"
                " wire removed unpickle-on-parse, not the execute-client-"
                "jobs feature).  Restrict to a trusted network and set"
                " $PETASTORM_TPU_SERVICE_TOKEN (docs/operations.md"
                " 'Disaggregated ingest service').", self._host)
        return self

    def _restore_journal(self) -> None:
        """Warm restart: rebuild client sessions from the journal file (see
        :mod:`petastorm_tpu.service.journal`).  Restored clients start
        disconnected with the grace timer running - one that never
        reconnects purges like any dropped client.  With ``journal_path``
        None the journal is still created as a pure in-memory mirror - the
        live record stream a hot standby tails needs no file."""
        from petastorm_tpu.service.journal import ServiceJournal

        self._journal = ServiceJournal(
            self._journal_path, fsync=self._journal_fsync,
            fsync_counter=self._m_journal_fsyncs)
        sessions = self._journal.load()
        with self._lock:
            restored_items = self._adopt_sessions_locked(sessions)
        # a plain restart KEEPS its stored epoch (peers accept an equal
        # epoch): only promotions bump, so a deposed primary can restart
        # from its own journal forever and still sit below its successor
        self.epoch = self._journal.epoch or 1
        self._journal.set_epoch(self.epoch)
        self._journal.open()
        if sessions:
            logger.info("journal restored %d session(s) with %d unresolved"
                        " item(s); clients have %.0fs to reconnect",
                        len(sessions), restored_items, self._client_grace_s)

    def _adopt_sessions_locked(self, sessions) -> int:
        """Turn journal-mirror sessions into disconnected client states
        awaiting their re-hello (warm restart AND standby promotion; caller
        holds the lock).  Sessions already registered - a client whose
        hello raced a promotion - are left alone."""
        now = time.monotonic()
        restored_items = 0
        for cid, session in sessions.items():
            if cid in self._clients:
                continue
            hello = session.hello
            client = _ClientState(
                cid, None, hello.get("factory"),
                hello.get("hostname", ""), bool(hello.get("shm_ok")),
                int(hello.get("max_requeue", self._max_requeue)),
                codecs=hello.get("codecs") or (),
                weight=hello.get("weight", 1.0),
                priority=hello.get("priority", 0))
            client.connected = False
            client.disconnected_at = now
            for item in session.items.values():
                try:
                    client.pending.append(WireItem.from_wire(item))
                except WireFormatError:
                    continue  # fuzzed/foreign record: skip, don't crash
                restored_items += 1
            self._clients[cid] = client
            self._client_order.append(cid)
        if restored_items:
            self._m_journal_items.add(restored_items)
        return restored_items

    # -- fleet event log (tentpole d) ------------------------------------------

    def _event(self, kind: str, src: str = "dispatcher", **fields) -> None:
        """Append one structured event to the bounded fleet log.  Wall-clock
        stamped (events are read by humans correlating across machines);
        the deque's maxlen drops the oldest on overflow - the log is a
        flight-data tail, not an audit trail."""
        ev = {"ts": round(time.time(), 3), "src": src, "kind": kind}
        ev.update(fields)
        self._events.append(ev)

    def events_tail(self, n: int = 256) -> List[Dict]:
        """The last ``n`` fleet events, oldest first (the ``events?``
        frame's payload; also folded into client flight records on a
        terminal failure)."""
        with self._lock:
            evs = list(self._events)
        return evs[-max(0, int(n)):]

    def _on_peer_event(self, msg: Dict, src: Optional[str] = None) -> None:
        """Fold one event reported by a peer (autoscale supervisor ``event``
        frames, worker heartbeat piggybacks) into the fleet log.  Only
        plain scalar fields are kept and the field count is capped - a
        peer cannot bloat the bounded log's entries."""
        if not isinstance(msg, dict):
            return
        kind = msg.get("kind")
        if not isinstance(kind, str) or not kind:
            return
        fields = {}
        for k, v in msg.items():
            if k in ("t", "kind", "token", "ts", "src"):
                continue
            if isinstance(v, (str, int, float, bool)) and len(fields) < 8:
                fields[str(k)[:32]] = v[:200] if isinstance(v, str) else v
        self._event(kind[:64], src=src or str(msg.get("src", "peer"))[:64],
                    **fields)

    # -- hot-standby HA (module docstring "High availability") -----------------

    #: live-tail records a slow standby may queue before the primary drops
    #: it (the standby then reconnects and re-snapshots - bounded memory
    #: beats an unbounded backlog for a follower that cannot keep up)
    _STANDBY_QUEUE_MAX = 10000
    #: snapshot records per journal_sync frame (a frame stays control-sized)
    _SYNC_CHUNK = 256
    #: consecutive failed re-sync attempts (connect + standby_ok) before a
    #: once-synced standby declares the primary dead and promotes
    _PROMOTE_AFTER_FAILS = 3

    def _standby_feed_loop(self, conn: FrameSocket, hello: Dict) -> None:
        """Primary side: stream the journal (snapshot, then the live tail)
        to one subscribed standby as ``journal_sync`` frames.  Runs on the
        standby's connection thread until either end dies or the standby
        falls irrecoverably behind (queue overflow -> disconnect; it
        reconnects and re-snapshots)."""
        if hello.get("protocol") != PROTOCOL_VERSION:
            conn.send({"t": "error", "error": "protocol version mismatch"})
            conn.close()
            return
        peer = hello.get("standby") or "?"
        q: "queue.Queue" = queue.Queue(maxsize=self._STANDBY_QUEUE_MAX)
        overflow = threading.Event()

        def tail(seq: int, rec: Dict) -> None:
            try:
                q.put_nowait((seq, rec))
            except queue.Full:
                overflow.set()

        snapshot, seq = self._journal.attach_tail(tail)
        with self._lock:
            self._standby_feeds[peer] = 0
        self._event("standby_subscribed", standby=peer,
                    snapshot_records=len(snapshot))
        logger.info("Standby %s subscribed to the journal tail (%d snapshot"
                    " record(s), seq %d)", peer, len(snapshot), seq)
        try:
            conn.send({"t": "standby_ok", "epoch": self.epoch,
                       "boot": self.boot_id})
            for i in range(0, len(snapshot), self._SYNC_CHUNK):
                chunk = snapshot[i:i + self._SYNC_CHUNK]
                try:
                    conn.send({"t": "journal_sync", "k": "snap",
                               "recs": chunk, "seq": seq})
                except WireFormatError:
                    # a record outside the wire domain poisons its whole
                    # chunk: retry singly so one bad hello costs one
                    # session's warmth, not the sync
                    for rec in chunk:
                        try:
                            conn.send({"t": "journal_sync", "k": "snap",
                                       "recs": [rec], "seq": seq})
                        except WireFormatError:
                            logger.warning("journal_sync: unencodable"
                                           " snapshot record skipped (%r)",
                                           rec.get("r"))
            conn.send({"t": "journal_sync", "k": "snap_end", "seq": seq})
            with self._lock:
                self._standby_feeds[peer] = seq
            while not self._stop_event.is_set():
                if overflow.is_set():
                    logger.warning(
                        "Standby %s fell > %d record(s) behind the journal"
                        " tail; disconnecting it to force a re-snapshot",
                        peer, self._STANDBY_QUEUE_MAX)
                    break
                try:
                    rec_seq, rec = q.get(timeout=0.5)
                except queue.Empty:
                    # idle keepalive: carries the LIVE journal seq, so the
                    # standby can meter any backlog as lag.  An empty feed
                    # queue means the standby has everything we appended -
                    # record it as fully fed
                    live_seq = self._journal.seq
                    conn.send({"t": "journal_sync", "k": "ping",
                               "seq": live_seq})
                    with self._lock:
                        self._standby_feeds[peer] = live_seq
                    continue
                try:
                    conn.send({"t": "journal_sync", "k": "rec", "rec": rec,
                               "seq": rec_seq})
                    with self._lock:
                        self._standby_feeds[peer] = rec_seq
                except WireFormatError:
                    logger.warning("journal_sync: unencodable tail record"
                                   " skipped (%r)", rec.get("r"))
        except (OSError, FrameClosedError):
            pass  # standby went away; it reconnects (or promoted)
        finally:
            self._journal.detach_tail(tail)
            with self._lock:
                self._standby_feeds.pop(peer, None)
            self._event("standby_unsubscribed", standby=peer)
            conn.close()

    def _standby_loop(self) -> None:
        """Standby side: keep a sync session against the primary; when the
        primary is gone (connection lost AND :data:`_PROMOTE_AFTER_FAILS`
        consecutive re-sync attempts fail) promote.  Never promotes before
        the FIRST successful sync: a standby that cannot reach a healthy
        primary at boot must wait, not seize an empty fleet."""
        targets = parse_address_list(self._standby_of)
        synced_ever = False
        fails = 0
        while not self._stop_event.is_set() and self._standby:
            contact = False
            for addr in targets:
                if self._standby_sync(addr):
                    synced_ever = True
                    fails = 0
                    contact = True
                    break
            if self._stop_event.is_set() or not self._standby:
                return
            if not contact:
                fails += 1
                if synced_ever and fails >= self._PROMOTE_AFTER_FAILS:
                    self._promote(f"primary {self._standby_of} unreachable"
                                  f" after {fails} re-sync attempt(s)")
                    return
            self._stop_event.wait(0.3)

    def _standby_sync(self, addr: Tuple[str, int]) -> bool:
        """One sync session: subscribe, ingest the snapshot, follow the
        tail until the stream dies.  Returns True when the primary
        answered ``standby_ok`` (contact - even if the stream later broke:
        only answer-less attempts count toward promotion)."""
        try:
            conn = connect_frames(addr, timeout=2.0)
        except OSError:
            return False
        contact = False
        try:
            conn.send({"t": "standby_hello", "protocol": PROTOCOL_VERSION,
                       "token": self._auth_token,
                       "standby": f"{self._host}:{self.port}"})
            ok = conn.recv(timeout=5.0)
            if not isinstance(ok, dict) or ok.get("t") != "standby_ok":
                if isinstance(ok, dict) and ok.get("t") == "error":
                    self._m_standby_refused.add(1)
                    logger.warning("Primary refused the standby"
                                   " subscription: %s", ok.get("error"))
                return False
            contact = True
            self._primary_epoch = max(self._primary_epoch,
                                      int(ok.get("epoch") or 1))
            self._primary_boot = ok.get("boot")
            # fresh snapshot incoming: drop whatever the last session left
            self._journal.reset()
            self._standby_synced = 0
            stream_pos = 0
            last_rx = time.monotonic()
            silence_limit = max(2.0, self._heartbeat_timeout_s)
            while not self._stop_event.is_set() and self._standby:
                msg = conn.recv(timeout=0.5)
                now = time.monotonic()
                if msg is None:
                    if now - last_rx > silence_limit:
                        logger.warning("journal_sync stream from %s:%d went"
                                       " silent for %.1fs; dropping it",
                                       addr[0], addr[1], now - last_rx)
                        return True
                    continue
                last_rx = now
                if not isinstance(msg, dict) or msg.get("t") != "journal_sync":
                    continue
                k, seq = msg.get("k"), msg.get("seq")
                if k == "snap":
                    for rec in msg.get("recs") or ():
                        self._journal.ingest(rec)
                        self._standby_synced += 1
                elif k == "rec":
                    self._journal.ingest(msg.get("rec"))
                    self._standby_synced += 1
                    if isinstance(seq, int):
                        stream_pos = seq
                elif k == "snap_end":
                    if isinstance(seq, int):
                        stream_pos = seq
                    self._standby_lag = 0
                    self._g_standby_lag.set(0)
                    logger.info("Standby warm: %d record(s) synced from"
                                " %s:%d (primary epoch %d)",
                                self._standby_synced, addr[0], addr[1],
                                self._primary_epoch)
                elif k == "ping" and isinstance(seq, int):
                    # ping carries the primary's LIVE seq; anything above
                    # our stream position is backlog we have not received
                    self._standby_lag = max(0, seq - stream_pos)
                    self._g_standby_lag.set(self._standby_lag)
        except (OSError, FrameClosedError):
            pass  # stream died: the outer loop probes, then promotes
        except (WireFormatError, PetastormTpuError) as exc:
            # mid-stream garbage (a cut frame, an undecodable record): the
            # warm mirror can no longer be trusted.  Degrade to a cold
            # re-snapshot - warned ONCE, never a crash or a desynced mirror
            if not self._sync_warned:
                self._sync_warned = True
                logger.warning(
                    "journal_sync stream from %s:%d was undecodable (%s);"
                    " dropping the warm mirror and re-snapshotting (a"
                    " promotion before the re-sync completes falls back to"
                    " cold peer reconstruction)", addr[0], addr[1], exc)
            self._journal.reset()
            self._standby_synced = 0
        finally:
            conn.close()
        return contact

    def _promote(self, reason: str) -> None:
        """Standby -> primary: adopt the mirrored sessions, fence the old
        primary out by bumping the epoch past anything it ever advertised,
        and start serving hellos."""
        with self._lock:
            if not self._standby:
                return
            self._standby = False
            self.epoch = max(self.epoch, self._primary_epoch + 1,
                             self._journal.epoch + 1)
            sessions = self._journal.sessions()
            restored = self._adopt_sessions_locked(sessions)
        self._journal.set_epoch(self.epoch)
        try:
            # persist the adopted state (and the new epoch) to this
            # dispatcher's OWN journal file, when it has one
            self._journal.open()
        except OSError:
            logger.warning("could not open the journal file after"
                           " promotion; serving without one", exc_info=True)
        self._m_failovers.add(1)
        self._g_epoch.set(self.epoch)
        self._g_standby_lag.set(0)
        self._event("promotion", reason=reason, epoch=self.epoch,
                    sessions=len(sessions), restored_items=restored)
        self.standby_promoted.set()
        logger.warning(
            "STANDBY PROMOTED to primary (%s): epoch %d, %d warm session(s)"
            " with %d pending item(s); serving at %s:%d", reason, self.epoch,
            len(sessions), restored, self._host, self.port)
        self._stamp_gauges()

    def stop(self) -> None:
        """Close the listener and every live connection; workers and
        clients see EOF immediately."""
        self._stop_event.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns = ([w.conn for w in self._workers.values()]
                     + [c.conn for c in self._clients.values()
                        if c.connected and c.conn is not None])
        for conn in conns:
            conn.close()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        if self._journal is not None:
            self._journal.close()

    def join(self, timeout: float = 5.0) -> None:
        """Bounded wait for the service threads after :meth:`stop`."""
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(max(0.0, deadline - time.monotonic()))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        self.join()

    # -- connection handling --------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop_event.is_set():
            try:
                sock, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed at stop
            t = threading.Thread(target=self._serve_conn,
                                 args=(FrameSocket(sock),), daemon=True,
                                 name="petastorm-tpu-dispatcher-conn")
            t.start()
            # prune finished connection threads as we go: a long-lived
            # dispatcher probed by `stats` every few seconds would otherwise
            # accumulate dead Thread objects for its whole lifetime
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_conn(self, conn: FrameSocket) -> None:
        try:
            hello = conn.recv(timeout=10.0)
        except LegacyPickleFrameError:
            # a v1 (pickled-wire) peer, detected WITHOUT unpickling it:
            # answer in the one format it can read so it fails loudly with
            # the version message instead of desyncing or hanging
            logger.warning("Refusing legacy v1 (pickled-frame) peer: this"
                           " dispatcher speaks the v2 binary wire")
            try:
                conn.send_legacy_error(
                    "protocol version mismatch: this dispatcher speaks the"
                    f" v2 binary wire (PROTOCOL_VERSION {PROTOCOL_VERSION});"
                    " upgrade the client/worker")
            except OSError:
                pass
            conn.close()
            return
        except Exception:  # noqa: BLE001 - drop bad conns (EOF, garbage)
            conn.close()
            return
        if hello is None or self._stop_event.is_set():
            # a connection that raced the accept loop against stop() must be
            # refused here: sending hello_ok and then never reading would
            # leave the peer waiting on a silent live socket
            conn.close()
            return
        kind = hello.get("t")
        if not token_matches(self._auth_token, hello.get("token")):
            # auth gate before ANY hello processing: an untokened peer gets
            # a refusal and a closed socket, never a registered state
            logger.warning("Refusing %r connection: bad/missing auth token",
                           kind)
            if self.telemetry.enabled:
                self.telemetry.counter("service.auth_rejected").add(1)
            try:
                conn.send({"t": "error", "error": "bad auth token"})
            except OSError:
                pass
            conn.close()
            return
        try:
            if self._standby and kind in ("worker_hello", "client_hello"):
                # a standby serves stats? and journal subscriptions only;
                # peers treat this refusal as a failed attempt and rotate
                # to the next address in their failover list
                self._event("fencing_refusal", peer=kind,
                            why="standing by", epoch=self.epoch)
                try:
                    conn.send({"t": "error", "error":
                               "dispatcher is a hot standby (of"
                               f" {self._standby_of}); not serving until"
                               " promoted"})
                except OSError:
                    pass
                conn.close()
            elif kind == "worker_hello":
                self._worker_loop(conn, hello)
            elif kind == "client_hello":
                self._client_loop(conn, hello)
            elif kind == "standby_hello":
                self._standby_feed_loop(conn, hello)
            elif kind == "stats?":
                conn.send({"t": "stats", "stats": self.stats()})
                conn.close()
            elif kind == "fleet?":
                conn.send({"t": "fleet", "fleet": self.fleet_stats()})
                conn.close()
            elif kind == "events?":
                n = hello.get("n")
                conn.send({"t": "events", "events": self.events_tail(
                    n if isinstance(n, int) else 256)})
                conn.close()
            elif kind == "event":
                # control-plane peers (the autoscale supervisor) report
                # decisions into the fleet event log over one-shot conns
                self._on_peer_event(hello)
                conn.send({"t": "event_ok"})
                conn.close()
            else:
                logger.warning("Dropping connection with bad hello %r", kind)
                conn.close()
        except FrameClosedError:
            pass
        except Exception:  # noqa: BLE001 - one bad conn must not kill serving
            if not self._stop_event.is_set():
                logger.warning("Dispatcher connection handler failed",
                               exc_info=True)

    # -- worker side ----------------------------------------------------------

    def _worker_loop(self, conn: FrameSocket, hello: Dict) -> None:
        if hello.get("protocol") != PROTOCOL_VERSION:
            conn.send({"t": "error", "error": "protocol version mismatch"})
            conn.close()
            return
        with self._lock:
            self._worker_seq += 1
            name = hello.get("worker") or f"worker-{self._worker_seq}"
            if name in self._workers:
                name = f"{name}-{self._worker_seq}"
            state = _WorkerState(name, conn, hello.get("capacity", 1),
                                 hello.get("hostname", ""),
                                 codecs=hello.get("codecs") or ())
            self._workers[name] = state
            self._g_workers.set(len(self._workers))
            recovered = self._absorb_worker_rejoin_locked(state, hello)
        # clock_ns: the dispatcher's monotonic clock at reply time - peers
        # estimate their offset to it from the handshake round-trip, the
        # skew anchor for merging cross-process trace stamps
        conn.send({"t": "hello_ok", "worker": name, "epoch": self.epoch,
                   "clock_ns": time.perf_counter_ns()})
        self._event("worker_join", worker=name,
                    rejoin=bool(hello.get("resume")),
                    capacity=state.capacity)
        if hello.get("resume"):
            self._m_worker_rejoins.add(1)
            logger.info("Worker %s REJOINED still executing %d item(s)"
                        " (%d re-attached, rest claimed for reconnecting"
                        " clients)", name,
                        len(hello.get("assignments") or ()), recovered)
        else:
            logger.info("Worker %s registered (capacity %d, host %s)", name,
                        state.capacity, state.hostname or "?")
        self._pump()
        bytes_folded = 0
        try:
            while not self._stop_event.is_set():
                msg = conn.recv(timeout=1.0)
                if conn.bytes_received > bytes_folded:
                    self._m_bytes_in.add(conn.bytes_received - bytes_folded)
                    bytes_folded = conn.bytes_received
                if msg is None:
                    continue
                kind = msg.get("t")
                if kind == "heartbeat":
                    self._on_heartbeat(state, msg)
                elif kind == "result":
                    self._on_result(state, msg)
                elif kind == "failure":
                    self._on_worker_failure(state, msg)
                elif kind == "retiring":
                    self._on_retiring(state)
                elif kind == "drained?":
                    self._on_drain_probe(state)
                elif kind == "bye":
                    break
        except FrameClosedError:
            pass
        finally:
            self._worker_gone(name)

    def _absorb_worker_rejoin_locked(self, state: _WorkerState,
                                     hello: Dict) -> int:
        """Re-attach a rejoining worker's still-executing assignments so
        nothing is double-assigned (caller holds the lock).

        Three cases per reported ``(client, ordinal, attempt)``:

        * the client is known and the ordinal is in-flight at a worker
          that no longer exists (the pre-restart assignment) - or pending
          (journal-restored) - the assignment moves to this worker;
        * the client is known and the ordinal is in-flight at a LIVE other
          worker: the dispatcher already requeued it past this worker (a
          worker-link blip, not a dispatcher restart) - the claim is
          stale, this worker's eventual result dedups;
        * the client is unknown (it has not reconnected yet): recorded in
          ``_claims`` and honored when its resync arrives.

        ``jobs`` marks which client factories the worker still holds, so
        the pump does not re-ship them.
        """
        state.jobs_sent.update(str(c) for c in hello.get("jobs") or ())
        now = time.monotonic()
        recovered = 0
        for entry in hello.get("assignments") or ():
            if not (isinstance(entry, (list, tuple)) and len(entry) >= 2):
                continue
            cid, ordinal = str(entry[0]), entry[1]
            if not isinstance(ordinal, int):
                continue
            client = self._clients.get(cid)
            if client is None:
                self._claims[(cid, ordinal)] = (state.name, now)
                continue
            assign = client.inflight.get(ordinal)
            if assign is not None:
                holder = self._workers.get(assign.worker)
                if holder is None or holder is state:
                    assign.worker = state.name
                    assign.assigned_at = now
                    state.inflight.add((cid, ordinal))
                    recovered += 1
                continue
            if ordinal in client.unacked:
                continue  # already completed: the worker's copy will dedup
            for i, item in enumerate(client.pending):
                if item.ordinal == ordinal:
                    del client.pending[i]
                    client.inflight[ordinal] = _Assignment(item, state.name)
                    state.inflight.add((cid, ordinal))
                    recovered += 1
                    break
            else:
                # client reconnected but its resync has not landed yet:
                # claim now, honor at resync
                self._claims[(cid, ordinal)] = (state.name, now)
        if recovered:
            self._m_recovered.add(recovered)
        return recovered

    def _on_retiring(self, state: _WorkerState) -> None:
        """Graceful retirement, phase 1: the worker asked to drain.  Mark
        it draining (the assignment loop never picks it again), then ack -
        the worker finishes its in-flight items, flushes, and says ``bye``.
        Because nothing is dropped or requeued on this path, a
        ``deterministic='seed'`` stream rides a graceful shrink untouched.
        """
        with self._lock:
            already = state.draining
            state.draining = True
            inflight = len(state.inflight)
        if not already:
            self._m_drains.add(1)
            self._event("worker_drain", worker=state.name, inflight=inflight)
            logger.info("Worker %s is retiring (draining %d in-flight"
                        " item(s); no new assignments)", state.name, inflight)
        try:
            state.conn.send({"t": "retire_ok"})
        except OSError:
            pass  # dying connection: _worker_gone's requeue path covers it

    def _on_drain_probe(self, state: _WorkerState) -> None:
        """Graceful retirement, phase 2: the worker's held/outbox sets are
        empty and it asks whether the DISPATCHER still has anything
        assigned to it.  The dispatcher's in-flight set is the source of
        truth (an assignment is recorded there before its ``work`` frame is
        even sent), so ``drain_ok`` structurally proves nothing is - or
        ever will be - outstanding: the worker may say ``bye`` with no
        timing window (the pre-PR 0.3s quiet-period heuristic raced
        results still crossing the wire)."""
        with self._lock:
            remaining = len(state.inflight)
        try:
            if remaining == 0:
                state.conn.send({"t": "drain_ok"})
            else:
                state.conn.send({"t": "drain_wait", "inflight": remaining})
        except OSError:
            pass  # dying connection: _worker_gone's requeue path covers it

    def _on_heartbeat(self, state: _WorkerState, msg: Dict) -> None:
        state.last_heartbeat = time.monotonic()
        state.busy = int(msg.get("busy", 0))
        deltas = msg.get("counters") or {}
        if self.telemetry.enabled:
            for cname, delta in deltas.items():
                if delta and cname.startswith(FLEET_COUNTER_PREFIXES):
                    self.telemetry.counter(f"service.fleet.{cname}").add(delta)
        # fleet aggregation: fold the deltas into this worker's cumulative
        # totals and keep its latest cumulative histogram snapshots - the
        # per-worker truth behind fleet_stats() and the labeled Prometheus
        # families (the delta fold above only keeps fleet-wide sums)
        for cname, delta in deltas.items():
            if isinstance(delta, (int, float)) and delta:
                state.counters[cname] = state.counters.get(cname, 0) + delta
        hists = msg.get("hists")
        if isinstance(hists, dict):
            state.hists = hists
        for ev in msg.get("events") or ():
            self._on_peer_event(ev, src=state.name)
        try:
            # the heartbeat reply carries the fencing epoch, so a fleet
            # learns about a failover even between reconnects
            state.conn.send({"t": "hb_ok", "epoch": self.epoch})
        except OSError:
            pass  # dying connection: the read loop handles it

    # -- bounded redelivery buffer (satellite: replay_buffer_bytes) ------------

    def _retain_body_locked(self, cid: str, out: Dict) -> None:
        """Account one buffered outcome's body toward the replay cap and
        enforce the cap (caller holds the lock).  Only ``_body``-carrying
        outcomes (results) count; failure frames are header-sized."""
        body = out.get("_body")
        if body is None:
            return
        self._replay_bytes += len(body)
        self._replay_order.append((cid, out))
        if self._replay_bytes <= self._replay_cap:
            return
        # ONE oldest-first pass, dropping as many eligible bodies as the
        # overflow needs (re-walking the ineligible prefix per drop would
        # be O(n) per retained result, under the dispatcher lock, on the
        # relay hot path)
        deferred = []
        while self._replay_order and self._replay_bytes > self._replay_cap:
            ocid, old = self._replay_order.popleft()
            if old.get("_body") is None:
                continue  # already acked/released: drop the tombstone
            client = self._clients.get(ocid)
            if old is out or (client is not None and client.connected
                              and not old.get("_sent")):
                # never degrade the newest entry or one still awaiting its
                # FIRST send to a live client (the client would simply
                # never see it); re-check next overflow
                deferred.append((ocid, old))
                continue
            self._replay_bytes -= len(old["_body"])
            del old["_body"]
            old["_stale"] = True
            self._m_replay_dropped.add(1)
        self._replay_order.extendleft(reversed(deferred))

    def _release_body_locked(self, out: Optional[Dict]) -> None:
        """Free one outcome's body accounting (ack, purge, replay drop).
        The deque entry stays behind as a tombstone; the overflow sweep
        skips released entries."""
        if out is None:
            return
        body = out.get("_body")
        if body is not None:
            self._replay_bytes -= len(body)
            del out["_body"]

    def _on_result(self, state: _WorkerState, msg: Dict) -> None:
        cid, ordinal = msg["client"], msg["ordinal"]
        state.last_heartbeat = time.monotonic()
        tc = msg.get("tc")
        if isinstance(tc, dict):
            # traced item: stamp the dispatcher's result-receive time into
            # the returning hop timeline (the client closes the
            # return-relay hop against its own receive stamp)
            tc.setdefault("hops", []).append(
                ["d", "relay", int(msg.get("attempt", 0)),
                 time.perf_counter_ns(), 0])
        duplicate = False
        orphaned = False
        # ONE critical section from duplicate check to outcome recording:
        # splitting them would let _purge_client (grace expiry, bye) pop
        # the client in between, silently losing the result into an
        # orphaned _ClientState
        with self._lock:
            state.inflight.discard((cid, ordinal))
            client = self._clients.get(cid)
            if client is None:
                conn = None
                claim = self._claims.pop((cid, ordinal), None)
                if claim is not None:
                    # a rejoined worker finished an item whose client has
                    # not reconnected yet: buffer the outcome and replay it
                    # the moment the client's hello lands (bounded by the
                    # replay cap + the grace sweep)
                    out = {k: v for k, v in msg.items() if k != "client"}
                    out["worker"] = state.name
                    self._orphan_results[(cid, ordinal)] = (
                        out, time.monotonic())
                    self._retain_body_locked(cid, out)
                    orphaned = True
                else:
                    duplicate = True
            elif client.inflight.pop(ordinal, None) is None:
                claim = self._claims.pop((cid, ordinal), None)
                if claim is not None:
                    # a claimed item's result landed after the client's
                    # hello but before its resync: record + deliver it now;
                    # popping the claim keeps the resync from re-attaching
                    # an ordinal the worker already finished (which would
                    # wedge the client waiting on a result that never
                    # comes again)
                    out = {k: v for k, v in msg.items() if k != "client"}
                    out["worker"] = state.name
                    client.unacked[ordinal] = out
                    client.results += 1
                    client.rows += int(msg.get("rows", 0))
                    self._retain_body_locked(cid, out)
                    conn = client.conn if client.connected else None
                else:
                    # late duplicate (the ordinal was requeued and its
                    # sibling delivered first, or the client was purged):
                    # drop - the client-side ledger would drop it anyway
                    duplicate = True
                    conn = None
            else:
                # buffer relay: forward the worker's result header verbatim
                # (minus its routing field) with the column payload as
                # opaque bytes - the dispatcher never decodes it
                out = {k: v for k, v in msg.items() if k != "client"}
                out["worker"] = state.name
                client.unacked[ordinal] = out
                client.results += 1
                client.rows += int(msg.get("rows", 0))
                self._retain_body_locked(cid, out)
                conn = client.conn if client.connected else None
        pk = msg.get("pk")
        if pk == "bin":
            self._m_frames_bin.add(1)
        elif pk == "shm":
            self._m_frames_shm.add(1)
        elif pk == "pickle":
            self._m_frames_pkl.add(1)
        if duplicate:
            # outside the lock: _pump's sends must never run while this
            # thread holds the dispatcher lock (a worker with a full TCP
            # buffer would stall every other connection's thread)
            self._m_dup.add(1)
            self._stamp_gauges()
            self._pump()
            return
        if orphaned:
            self._m_orphans.add(1)
            self._m_completed.add(1)
            self._m_rows.add(int(msg.get("rows", 0)))
            self._pump()
            return
        self._m_completed.add(1)
        self._m_rows.add(int(msg.get("rows", 0)))
        self._count_client_rows(cid, int(msg.get("rows", 0)))
        if conn is not None:
            self._send_to_client(cid, conn, out)
        # no _stamp_gauges here: the monitor loop stamps every 0.5s, and a
        # per-result lock+scan on the relay hot path costs real throughput
        # on a core shared with decode
        self._pump()

    def _count_client_rows(self, cid: str, rows: int) -> None:
        """Per-client delivered-row telemetry under a bounded name set: a
        dispatcher serving an unbounded client churn must not grow the
        registry forever.  The cap applies ONLY to registry counter names -
        ``stats()`` per-client counts and the ``qos`` share report come
        from ``_ClientState`` and stay exact and unbounded past it; the
        first capped client logs a warning so the silent gap in the
        ``service.client.*`` series is explained."""
        if not self.telemetry.enabled:
            return
        if cid in self._client_counter_ids \
                or len(self._client_counter_ids) < 100:
            self._client_counter_ids.add(cid)
            self.telemetry.counter(
                f"service.client.{cid[:12]}.rows").add(rows)
        elif not self._counter_cap_warned:
            self._counter_cap_warned = True
            logger.warning(
                "per-client counter cap reached (100 clients): client %s"
                " (and later arrivals) will NOT get a service.client.<id>"
                ".rows registry counter; per-client counts in stats() and"
                " the stats()['qos'] share report remain exact and"
                " unbounded", cid)

    def _on_worker_failure(self, state: _WorkerState, msg: Dict) -> None:
        cid, ordinal = msg["client"], msg["ordinal"]
        state.last_heartbeat = time.monotonic()
        with self._lock:
            state.inflight.discard((cid, ordinal))
            # drop any claim for this item: a claimed item failing is
            # resolved by the client's resync re-enqueueing it (the fresh
            # dispatcher never saw the blob, so re-execution IS its
            # requeue path) - a dangling claim would re-attach the ordinal
            # to a worker that no longer holds it and wedge the client
            claim = self._claims.pop((cid, ordinal), None)
            client = self._clients.get(cid)
            if client is None:
                return
            assign = client.inflight.pop(ordinal, None)
            if assign is None:
                if claim is None:
                    self._m_dup.add(1)
                return
        # failures are plain fields on the wire (formatted traceback, kind,
        # exc_type) - no object envelope; the client recovers the failed
        # item from its own in-flight ledger
        if msg.get("kind", "data") == "infra":
            # in-worker infra failure (e.g. MemoryError): the item is
            # healthy, the worker wasn't - same treatment as a death
            self._requeue_or_fail(
                cid, ordinal, assign,
                f"in-worker infra failure ({msg.get('exc_type')})")
        else:
            self._forward_failure(cid, ordinal,
                                  formatted=msg.get("formatted"),
                                  kind=msg.get("kind", "data"),
                                  exc_type=msg.get("exc_type"))
        self._pump()

    def _worker_gone(self, name: str) -> None:
        with self._lock:
            state = self._workers.pop(name, None)
            if state is None or state.gone:
                return
            state.gone = True
            lost = list(state.inflight)
            self._g_workers.set(len(self._workers))
        state.conn.close()
        self._event("worker_gone", worker=name, lost_inflight=len(lost))
        if lost:
            logger.warning("Worker %s lost with %d in-flight item(s);"
                           " requeueing", name, len(lost))
        for cid, ordinal in lost:
            with self._lock:
                client = self._clients.get(cid)
                assign = client.inflight.pop(ordinal, None) if client else None
            if assign is not None:
                self._requeue_or_fail(cid, ordinal, assign,
                                      f"worker {name} death")
        self._pump()

    def _requeue_or_fail(self, cid: str, ordinal: int, assign: _Assignment,
                         why: str) -> None:
        """Pool `_requeue_lost` semantics across the wire: re-ventilate
        through the attempt budget, else surface a classified infra failure."""
        with self._lock:
            client = self._clients.get(cid)
            if client is None:
                return
            attempt = getattr(assign.item, "attempt", 0)
            if attempt < client.max_requeue:
                # a traced item's context survives the requeue: the same
                # trace id accumulates the retry's hop stamps, so the
                # merged trace shows both attempts as sibling span trees
                tc = getattr(assign.item, "tc", None)
                if isinstance(tc, dict):
                    tc.setdefault("hops", []).append(
                        ["d", "requeue", attempt + 1,
                         time.perf_counter_ns(), 0])
                retry = WireItem(ordinal, attempt + 1, assign.item.blob,
                                 assign.item.rg, tc)
                client.pending.appendleft(retry)
                client.requeued += 1
                conn = client.conn if client.connected else None
                notice = {"t": "requeued", "ordinal": ordinal,
                          "attempt": attempt + 1, "why": why}
            else:
                conn = None
                notice = None
        if notice is not None:
            self._m_requeued.add(1)
            self._event("requeue", client=cid, ordinal=ordinal,
                        attempt=attempt + 1, why=why)
            logger.warning("Requeueing work item %s for client %s after %s"
                           " (attempt %d/%d)", ordinal, cid, why, attempt + 1,
                           client.max_requeue)
            if conn is not None:
                self._send_to_client(cid, conn, notice)
            return
        self._event("item_failed", client=cid, ordinal=ordinal, why=why,
                    attempts=attempt)
        self._forward_failure(
            cid, ordinal, message=(
                f"Work item {ordinal} lost to {why}; requeue budget exhausted"
                f" ({attempt} requeue(s) of max {client.max_requeue})"
                " - possible crash/OOM"),
            kind="infra")

    def _forward_failure(self, cid: str, ordinal: int,
                         formatted: Optional[str] = None,
                         message: Optional[str] = None, kind: str = "data",
                         exc_type: Optional[str] = None) -> None:
        with self._lock:
            client = self._clients.get(cid)
            if client is None:
                return
            out = {"t": "failure", "ordinal": ordinal, "kind": kind}
            if formatted is not None:
                out["formatted"] = formatted
            if message is not None:
                out["message"] = message
            if exc_type is not None:
                out["exc_type"] = exc_type
            client.unacked[ordinal] = out
            conn = client.conn if client.connected else None
        self._m_failures.add(1)
        if conn is not None:
            self._send_to_client(cid, conn, out)

    # -- client side ----------------------------------------------------------

    def _client_loop(self, conn: FrameSocket, hello: Dict) -> None:
        if hello.get("protocol") != PROTOCOL_VERSION:
            conn.send({"t": "error", "error": "protocol version mismatch"})
            conn.close()
            return
        cid = hello["client"]
        resumed = bool(hello.get("resume"))
        refetch = 0
        with self._lock:
            client = self._clients.get(cid)
            # admission control: a NEW session past the cap is refused
            # inside the registration critical section (two racing hellos
            # cannot both squeeze under the cap) and before any state
            # exists for it; reconnects of admitted sessions never hit
            # this - their state is live above.  Only CONNECTED sessions
            # count toward the cap: a crashed trainer riding out its
            # reconnect grace (or a journal-restored session that never
            # came back) must not block its replacement's seat.
            admitted = (client is not None or self._max_clients is None
                        or sum(1 for c in self._clients.values()
                               if c.connected) < self._max_clients)
            if not admitted:
                pass  # refusal send/close happens outside the lock, below
            elif client is None:
                client = _ClientState(
                    cid, conn, hello.get("factory"),
                    hello.get("hostname", ""), bool(hello.get("shm_ok")),
                    int(hello.get("max_requeue", self._max_requeue)),
                    codecs=hello.get("codecs") or (),
                    weight=hello.get("weight", 1.0),
                    priority=hello.get("priority", 0))
                self._clients[cid] = client
                self._client_order.append(cid)
                if resumed:
                    # a client that WAS mid-session re-helloing to a
                    # dispatcher that has never seen it: the restart
                    # recovery path (its resync reconstructs the session)
                    self._m_sessions_rec.add(1)
                    logger.info("Client %s session reconstructed after a"
                                " dispatcher restart", cid)
                else:
                    logger.info("Client %s registered", cid)
            else:
                # reconnect: swap the connection in, replay unacked outcomes
                old = client.conn
                client.conn = conn
                client.connected = True
                client.disconnected_at = None
                if old is not None and old is not conn:
                    old.close()
                logger.info("Client %s reconnected (%d unacked outcome(s)"
                            " to replay)", cid, len(client.unacked))
            replay: List[Dict] = []
            known: List[int] = []
            if admitted:
                # adopt any orphan results a rejoined worker finished while
                # this client was away (they replay below like unacked ones)
                for key in [k for k in self._orphan_results if k[0] == cid]:
                    out, _ts = self._orphan_results.pop(key)
                    if not out.get("_stale"):
                        client.unacked[key[1]] = out
                        client.results += 1
                        client.rows += int(out.get("rows", 0))
                for ordinal in list(client.unacked):
                    out = client.unacked[ordinal]
                    if out.get("_stale"):
                        # body degraded under the replay cap: cannot replay;
                        # dropping it here + excluding it from `known`
                        # forces the client's resync to re-enqueue it
                        # (re-fetch)
                        del client.unacked[ordinal]
                        refetch += 1
                    else:
                        replay.append(out)
                known = sorted(client.known_ordinals())
                self._g_clients.set(
                    sum(1 for c in self._clients.values() if c.connected))
        if not admitted:
            self._m_admission_refused.add(1)
            logger.warning("Refusing client %s: admission control"
                           " (max_clients=%d sessions live)", cid,
                           self._max_clients)
            try:
                conn.send({"t": "error", "error":
                           "admission refused: this dispatcher caps"
                           f" sessions at max_clients={self._max_clients}"})
            except OSError:
                pass
            conn.close()
            return
        if refetch:
            self._m_refetches.add(refetch)
        if self._journal is not None:
            self._journal.append_hello(cid, {
                "factory": hello.get("factory"),
                "hostname": hello.get("hostname", ""),
                "shm_ok": bool(hello.get("shm_ok")),
                "max_requeue": int(hello.get("max_requeue",
                                             self._max_requeue)),
                "codecs": list(hello.get("codecs") or ()),
                "weight": client.weight, "priority": client.priority})
        # `boot` lets the client count dispatcher restarts; `known` lets a
        # warm-restarted (journaled) session skip resync re-sends; `epoch`
        # is the fencing token (a deposed primary's lower value is refused)
        # `clock_ns` anchors the client's handshake clock-offset estimate
        # (distributed tracing maps dispatcher/worker stamps into the
        # client's monotonic domain through it)
        conn.send({"t": "hello_ok", "client": cid, "boot": self.boot_id,
                   "epoch": self.epoch, "known": known,
                   "clock_ns": time.perf_counter_ns()})
        for out in replay:
            self._send_to_client(cid, conn, out)
        self._pump()
        bytes_folded = 0
        try:
            while not self._stop_event.is_set():
                msg = conn.recv(timeout=1.0)
                if conn.bytes_received > bytes_folded:
                    self._m_bytes_in.add(conn.bytes_received - bytes_folded)
                    bytes_folded = conn.bytes_received
                if msg is None:
                    continue
                kind = msg.get("t")
                if kind == "enqueue":
                    item = WireItem.from_wire(msg["item"])
                    if item.tc is not None:
                        # traced item: stamp its arrival at the control
                        # plane (the dispatcher-queue hop opens here)
                        item.tc.setdefault("hops", []).append(
                            ["d", "recv", item.attempt,
                             time.perf_counter_ns(), 0])
                    with self._lock:
                        client.pending.append(item)
                    if self._journal is not None:
                        self._journal.append_enqueue(cid, item.to_wire())
                    self._pump()
                elif kind == "ack":
                    with self._lock:
                        for ordinal in msg["ordinals"]:
                            self._release_body_locked(
                                client.unacked.pop(ordinal, None))
                    if self._journal is not None:
                        self._journal.append_ack(cid, msg["ordinals"])
                elif kind == "resync":
                    self._on_resync(client, msg)
                elif kind == "client_stats":
                    starved = float(msg.get("starved_s", 0.0))
                    if starved > 0:
                        self._starved_reports.append(
                            (time.monotonic(), starved))
                elif kind == "stats?":
                    conn.send({"t": "stats", "stats": self.stats()})
                elif kind == "bye":
                    self._purge_client(cid, reason="clean goodbye")
                    return
        except FrameClosedError:
            pass
        finally:
            with self._lock:
                current = self._clients.get(cid)
                if current is not None and current.conn is conn:
                    current.connected = False
                    current.disconnected_at = time.monotonic()
                    self._g_clients.set(sum(1 for c in self._clients.values()
                                            if c.connected))
            if self._stop_event.is_set():
                # stop-path exit (not a client-side drop): close the socket
                # so the peer sees EOF instead of an idle live connection
                conn.close()

    def _on_resync(self, client: _ClientState, msg: Dict) -> None:
        """Reconnect recovery: re-enqueue any ledger item the dispatcher
        has no record of (an ``enqueue`` frame lost in the dying
        connection, or a whole session lost with a dead dispatcher).  An
        item a rejoined worker CLAIMED re-attaches to that worker instead
        of pending - the executing copy is the assignment; nothing is
        double-assigned."""
        cid = client.client_id
        journal_items = []
        with self._lock:
            known = client.known_ordinals()
            restored = reattached = 0
            for entry in msg.get("items", ()):
                item = WireItem.from_wire(entry)
                if item.ordinal in known:
                    continue
                claim = self._claims.pop((cid, item.ordinal), None)
                worker = (self._workers.get(claim[0])
                          if claim is not None else None)
                if worker is not None and not worker.gone:
                    client.inflight[item.ordinal] = _Assignment(
                        item, worker.name)
                    worker.inflight.add((cid, item.ordinal))
                    reattached += 1
                else:
                    client.pending.append(item)
                    restored += 1
                journal_items.append(item.to_wire())
        if self._journal is not None:
            for fields in journal_items:
                self._journal.append_enqueue(cid, fields)
        if reattached:
            self._m_recovered.add(reattached)
        if restored:
            self._m_resync_restored.add(restored)
        if restored or reattached:
            logger.info("Client %s resync restored %d lost work item(s)"
                        " (+%d re-attached to executing workers)",
                        cid, restored, reattached)
        self._pump()

    def _send_to_client(self, cid: str, conn: FrameSocket, out: Dict) -> None:
        try:
            body = out.get("_body")
            if body is not None:
                # result relay: re-frame the header, forward the payload
                # bytes untouched (vectored write - no staging copy).
                # Underscore keys are dispatcher-local bookkeeping
                # (_body/_sent/_stale) and never ride the wire.
                header = {k: v for k, v in out.items()
                          if not k.startswith("_")}
                self._m_bytes_out.add(conn.send_batch(header, [body]))
            else:
                self._m_bytes_out.add(conn.send(
                    {k: v for k, v in out.items()
                     if not k.startswith("_")}))
            # a sent body is eligible for the replay-cap degrade: losing
            # it costs a re-fetch only if the delivery ALSO got lost
            out["_sent"] = True
        except OSError:
            # connection died mid-send: the outcome stays in unacked and
            # replays on reconnect; the client read loop marks disconnect
            logger.debug("send to client %s failed (kept for replay)", cid)

    def _purge_client(self, cid: str, reason: str) -> None:
        notify = []
        with self._lock:
            client = self._clients.pop(cid, None)
            if client is None:
                return
            if cid in self._client_order:
                self._client_order.remove(cid)
            dropped = len(client.pending) + len(client.inflight)
            for out in client.unacked.values():
                self._release_body_locked(out)
            for key in [k for k in self._claims if k[0] == cid]:
                del self._claims[key]
            for key in [k for k in self._orphan_results if k[0] == cid]:
                out, _ts = self._orphan_results.pop(key)
                self._release_body_locked(out)
            for worker in self._workers.values():
                worker.inflight = {(c, o) for c, o in worker.inflight
                                   if c != cid}
                if cid in worker.jobs_sent:
                    notify.append(worker.conn)
            self._g_clients.set(sum(1 for c in self._clients.values()
                                    if c.connected))
        if self._journal is not None:
            self._journal.append_purge(cid)
        for conn in notify:  # sends stay outside the dispatcher lock
            try:
                conn.send({"t": "job_done", "client": cid})
            except OSError:
                pass
        if client.conn is not None:
            client.conn.close()
        self._event("client_purged", client=cid, reason=reason,
                    dropped_items=dropped)
        logger.info("Client %s purged (%s; %d undelivered item(s) dropped)",
                    cid, reason, dropped)
        self._stamp_gauges()

    # -- assignment -----------------------------------------------------------

    def _pick_worker(self, item: VentilatedItem, free: List[_WorkerState],
                     stable: Optional[List[str]] = None) -> _WorkerState:
        """Rowgroup-affine choice among workers with spare capacity: the
        same rowgroup prefers the same worker (warm-tier locality), falling
        back to least-loaded.

        The affine worker is ``crc32(path:rowgroup)`` modulo the stable
        name-sorted list of ALL live workers - a deterministic digest
        (built-in ``hash()`` is PYTHONHASHSEED-randomized per process) over
        a membership-stable list (indexing the momentary free list would
        move the mapping whenever fleet load shifts), so affinity survives
        dispatcher restarts and load churn.  Only when the affine worker is
        saturated does the item go to the least-loaded free one.

        ``stable`` lets _pump hoist the sorted name list out of its
        assignment loop (membership cannot change while it holds the lock).
        """
        if isinstance(item, WireItem):
            # the wire plane lifts the affinity key out structurally so the
            # dispatcher never opens the item blob
            rg_key = (f"{item.rg[0]}:{item.rg[1]}"
                      if isinstance(item.rg, (list, tuple))
                      and len(item.rg) == 2 else None)
        else:
            # direct VentilatedItem (tests, in-process callers)
            work = getattr(item, "item", None)
            rg = getattr(work, "row_group", None)
            rg_key = (f"{getattr(rg, 'path', '')}:"
                      f"{getattr(rg, 'row_group', 0)}"
                      if rg is not None else None)
        if rg_key is not None:
            if stable is None:
                stable = sorted(w.name for w in self._workers.values()
                                if not w.gone)
            key = zlib.crc32(rg_key.encode())
            affine = self._workers.get(stable[key % len(stable)])
            if affine is not None and affine in free:
                return affine
        return min(free, key=lambda w: len(w.inflight))

    #: WDRR burst bound: a client's deficit never exceeds this many times
    #: its (floored-at-1) weight, so credit earned while briefly unscheduled
    #: cannot pile into an unbounded burst later
    _DEFICIT_BURST = 2.0

    def _next_client_locked(self) -> Optional[str]:
        """Pick the next client to assign for: **strict-priority tiers**
        (the highest tier with eligible pending work is served exclusively)
        and **weighted deficit-round-robin** within the tier (each refill
        adds credit proportional to ``weight``; one assignment spends one
        unit; an emptied queue resets its deficit - classic DRR, so
        long-run shares converge to the weight ratio and every positive
        weight keeps making progress).  A client at
        ``max_client_inflight`` is skipped (``service.qos.capped_deferrals``
        counts pumps where ONLY capped clients had pending work).  Caller
        holds the lock; returns None when nothing is assignable."""
        eligible = []
        capped_only = False
        for cid in self._client_order:
            c = self._clients[cid]
            if not c.pending:
                continue
            if self._max_client_inflight is not None \
                    and len(c.inflight) >= self._max_client_inflight:
                capped_only = True
                continue
            eligible.append(cid)
        if not eligible:
            if capped_only:
                self._m_capped_deferrals.add(1)
            return None
        top = max(self._clients[cid].priority for cid in eligible)
        tier = [cid for cid in eligible if self._clients[cid].priority == top]
        if len(tier) == 1:
            return tier[0]
        if all(self._clients[cid].deficit < 1.0 for cid in tier):
            # proportional refill sized so the first client to afford one
            # item lands exactly at 1.0 (virtual-time DRR: credit per
            # refill is weight-proportional; no fixed quantum to tune, no
            # refill loop that crawls for tiny weights)
            quantum = min((1.0 - self._clients[cid].deficit)
                          / self._clients[cid].weight for cid in tier)
            for cid in tier:
                c = self._clients[cid]
                c.deficit = min(c.deficit + c.weight * quantum,
                                self._DEFICIT_BURST * max(1.0, c.weight))
        affordable = [cid for cid in tier
                      if self._clients[cid].deficit >= 1.0] or tier
        # rotate the tie-break start so equal-deficit clients alternate
        self._rr = (self._rr + 1) % len(affordable)
        rotated = affordable[self._rr:] + affordable[:self._rr]
        return max(rotated, key=lambda cid: self._clients[cid].deficit)

    def _pump(self) -> None:
        """Assign pending items to free workers (strict-priority weighted
        deficit-round-robin across clients - :meth:`_next_client_locked`).
        Sends happen outside the lock; assignment state is recorded first,
        so a failed send surfaces as a worker death whose requeue path
        recovers the item."""
        sends: List[Tuple[_WorkerState, Dict]] = []
        with self._lock:
            stable = sorted(w.name for w in self._workers.values()
                            if not w.gone)
            while True:
                free = [w for w in self._workers.values()
                        if not w.gone and not w.draining
                        and len(w.inflight) < w.capacity]
                if not free:
                    break
                cid = self._next_client_locked()
                if cid is None:
                    break
                client = self._clients[cid]
                item = client.pending.popleft()
                client.deficit = max(0.0, client.deficit - 1.0)
                client.assigned += 1
                if not client.pending:
                    # DRR: an emptied queue forfeits its residual credit
                    # (idle time must not bank into a later burst)
                    client.deficit = 0.0
                tc = getattr(item, "tc", None)
                if isinstance(tc, dict):
                    # traced item: close the dispatcher-queue hop (receive/
                    # requeue -> assignment, same-process monotonic delta -
                    # skew-free) and stamp the assignment for the merged
                    # trace's relay hop
                    now_ns = time.perf_counter_ns()
                    hops = tc.setdefault("hops", [])
                    if self.telemetry.enabled:
                        for who, hname, _a, t_ns, _off in reversed(hops):
                            if who == "d" and hname in ("recv", "requeue"):
                                self.telemetry.histogram(
                                    "service.hop.dispatcher_queue").record(
                                        max(0, now_ns - t_ns) / 1e9)
                                break
                    hops.append(["d", "assign",
                                 getattr(item, "attempt", 0), now_ns, 0])
                worker = self._pick_worker(item, free, stable)
                client.inflight[item.ordinal] = _Assignment(item, worker.name)
                worker.inflight.add((cid, item.ordinal))
                if cid not in worker.jobs_sent:
                    worker.jobs_sent.add(cid)
                    same_host = bool(client.hostname
                                     and client.hostname == worker.hostname)
                    sends.append((worker, {
                        "t": "job", "client": cid, "factory": client.factory,
                        "shm_ok": client.shm_ok and same_host,
                        # BATCH-body compression for this pair: off for
                        # co-located hops, negotiated for cross-host ones
                        "codec": negotiate_codec(
                            self._wire_codec, same_host, client.codecs,
                            worker.codecs)}))
                sends.append((worker, {"t": "work", "client": cid,
                                       "item": item.to_wire()}))
                self._m_assigned.add(1)
        for worker, msg in sends:
            try:
                self._m_bytes_out.add(worker.conn.send(msg))
            except OSError:
                # dying worker: its read loop will run _worker_gone, which
                # requeues everything it held (including this item)
                logger.debug("send to worker %s failed", worker.name)
        if sends:
            self._stamp_gauges()

    def _stamp_gauges(self) -> None:
        with self._lock:
            pending = sum(len(c.pending) for c in self._clients.values())
            inflight = sum(len(c.inflight) for c in self._clients.values())
            tiers = len({c.priority for c in self._clients.values()
                         if c.connected})
            replay_bytes = self._replay_bytes
            # drop released tombstones off the front of the accounting
            # deque so it tracks live entries, not history
            while self._replay_order \
                    and self._replay_order[0][1].get("_body") is None:
                self._replay_order.popleft()
        self._g_pending.set(pending)
        self._g_inflight.set(inflight)
        self._g_priority_tiers.set(tiers)
        self._g_replay_bytes.set(replay_bytes)

    # -- monitoring / scaling -------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop_event.wait(0.5):
            now = time.monotonic()
            dead = []
            hung = {}
            with self._lock:
                for name, w in self._workers.items():
                    if now - w.last_heartbeat > self._heartbeat_timeout_s:
                        dead.append(name)
                if self._assignment_deadline_s is not None:
                    # liveness backstop for workers wedged INSIDE user code:
                    # they keep heartbeating (the heartbeat thread is
                    # independent), so a stuck ASSIGNMENT is the signal
                    for c in self._clients.values():
                        for ordinal, assign in c.inflight.items():
                            age = now - assign.assigned_at
                            if (age > self._assignment_deadline_s
                                    and assign.worker in self._workers):
                                hung.setdefault(assign.worker,
                                                (ordinal, age))
                expired = [cid for cid, c in self._clients.items()
                           if not c.connected and c.disconnected_at is not None
                           and now - c.disconnected_at > self._client_grace_s]
                # recovery leftovers whose client never reconnected: claims
                # and orphan results age out on the same grace clock
                for key in [k for k, (_w, ts) in self._claims.items()
                            if now - ts > self._client_grace_s]:
                    del self._claims[key]
                for key in [k for k, (_o, ts) in self._orphan_results.items()
                            if now - ts > self._client_grace_s]:
                    out, _ts = self._orphan_results.pop(key)
                    self._release_body_locked(out)
            for name in dead:
                logger.warning("Worker %s missed heartbeats for %.0fs;"
                               " declaring it dead", name,
                               self._heartbeat_timeout_s)
                self._worker_gone(name)
            for name, (ordinal, age) in hung.items():
                if name in dead:
                    continue
                logger.warning(
                    "Worker %s has held item %s for %.1fs >"
                    " assignment_deadline_s=%.1f; declaring it hung and"
                    " dropping it (its items requeue; the remote process"
                    " exits on the closed connection)", name, ordinal, age,
                    self._assignment_deadline_s)
                if self.telemetry.enabled:
                    self.telemetry.counter(
                        "service.hung_workers_dropped").add(1)
                self._worker_gone(name)
            for cid in expired:
                self._purge_client(cid, reason="reconnect grace expired")
            self._g_pressure.set(self.scaling_signal()["pressure"])
            self._stamp_gauges()

    def scaling_signal(self, window_s: float = 10.0,
                       threshold: Optional[float] = None) -> Dict[str, Any]:
        """Fleet-size pressure from the clients' queue-wait signals.

        ``pressure`` is the aggregate consumer starved-seconds per second
        over the last ``window_s`` (clients report their
        ``queue.results_empty_wait_s`` deltas - the exact signal
        petastorm_tpu.autotune grows local worker pools on).  Crossing
        ``threshold`` with work queued means the fleet is the bottleneck
        -> ``'grow'``; an idle fleet with nothing pending -> ``'shrink'``;
        else ``'ok'`` (:func:`compute_recommendation` is the exact rule -
        the autoscale supervisor applies the same one to remote
        ``stats`` probes).

        ``threshold`` defaults to the dispatcher's configured
        ``starved_threshold`` (ctor / ``--starved-threshold``), which
        itself defaults to the in-process ``AutotunePolicy``'s value - so
        service fleets and local pools judge starvation identically unless
        an operator tunes them apart.
        """
        if threshold is None:
            threshold = self._starved_threshold
        if threshold is None:
            from petastorm_tpu.autotune import AutotunePolicy

            threshold = AutotunePolicy.starved_threshold
        now = time.monotonic()
        with self._lock:
            starved = sum(delta for t, delta in self._starved_reports
                          if now - t <= window_s)
            pending = sum(len(c.pending) for c in self._clients.values())
            inflight = sum(len(c.inflight) for c in self._clients.values())
            # draining workers are leaving: they finish their in-flight
            # items but take no new ones, so they are not capacity
            capacity = sum(w.capacity for w in self._workers.values()
                           if not w.draining)
            workers = sum(1 for w in self._workers.values()
                          if not w.draining)
            clients = sum(1 for c in self._clients.values() if c.connected)
        pressure = starved / window_s
        busy_frac = (inflight / capacity) if capacity else 0.0
        recommendation = compute_recommendation(
            pressure=pressure, threshold=threshold, pending=pending,
            capacity=capacity, busy_fraction=busy_frac, clients=clients)
        return {"pressure": round(pressure, 4),
                "starved_threshold": threshold,
                "busy_fraction": round(busy_frac, 4),
                "pending_items": pending, "worker_capacity": capacity,
                "workers": workers, "connected_clients": clients,
                "recommendation": recommendation}

    def fleet_stats(self) -> Dict[str, Any]:
        """Fleet aggregation snapshot (the ``fleet?`` frame; also the raw
        material of the per-worker-labeled Prometheus families and the
        ``stats --watch`` fleet view): per-worker cumulative counters and
        stage-histogram quantiles, fleet-merged histograms (fixed buckets
        merge element-wise - :func:`merge_hist_snapshots`), the fleet
        event tail, and the scaling signal."""
        from petastorm_tpu.telemetry.report import (hist_quantile,
                                                    merge_hist_snapshots)

        now = time.monotonic()
        with self._lock:
            workers = {}
            hist_groups: Dict[str, List[Dict]] = {}
            for name, w in self._workers.items():
                stages = {}
                for hname, snap in (w.hists or {}).items():
                    if not isinstance(snap, dict):
                        continue
                    hist_groups.setdefault(hname, []).append(snap)
                    if snap.get("count"):
                        stages[hname] = {
                            "count": int(snap.get("count", 0)),
                            "p50_s": hist_quantile(snap, 0.5),
                            "p99_s": hist_quantile(snap, 0.99)}
                workers[name] = {
                    "busy": w.busy, "capacity": w.capacity,
                    "inflight": len(w.inflight), "draining": w.draining,
                    "hostname": w.hostname,
                    "heartbeat_age_s": round(now - w.last_heartbeat, 2),
                    "counters": dict(w.counters), "hists": stages}
            events = list(self._events)[-64:]
        merged = {}
        for hname, snaps in hist_groups.items():
            m = merge_hist_snapshots(snaps)
            if m.get("count"):
                merged[hname] = {"count": m["count"],
                                 "p50_s": hist_quantile(m, 0.5),
                                 "p99_s": hist_quantile(m, 0.99),
                                 "snapshot": m}
        fleet_counters = {}
        if self.telemetry.enabled:
            prefix = "service.fleet."
            fleet_counters = {
                k[len(prefix):]: v for k, v in
                self.telemetry.snapshot()["counters"].items()
                if k.startswith(prefix)}
        return {"boot": self.boot_id, "epoch": self.epoch,
                "uptime_s": round(now - self._started_at, 1),
                "workers": workers, "merged_hists": merged,
                "fleet_counters": fleet_counters, "events": events,
                "scaling": self.scaling_signal()}

    def _fleet_prometheus(self) -> str:
        """Extra text block for the ``--metrics-port`` scrape: the
        per-worker-labeled and fleet-merged families."""
        from petastorm_tpu.telemetry.export import render_fleet_prometheus

        return render_fleet_prometheus(self.fleet_stats())

    def stats(self) -> Dict[str, Any]:
        """Point-in-time service snapshot (CLI ``stats`` / tests /
        operators): fleet membership, per-client progress, counters, and
        the scaling signal."""
        with self._lock:
            workers = {name: {"capacity": w.capacity, "busy": w.busy,
                              "inflight": len(w.inflight),
                              "hostname": w.hostname,
                              "draining": w.draining,
                              "heartbeat_age_s": round(
                                  time.monotonic() - w.last_heartbeat, 2)}
                       for name, w in self._workers.items()}
            clients = {cid: {"connected": c.connected,
                             "pending": len(c.pending),
                             "inflight": len(c.inflight),
                             "unacked": len(c.unacked),
                             "rows": c.rows, "results": c.results,
                             "requeued": c.requeued}
                       for cid, c in self._clients.items()}
            # per-client QoS share report: exact + unbounded (satellite of
            # the per-client counter-name cap - THIS is the canonical
            # per-client accounting, whatever the registry capped)
            total_assigned = sum(c.assigned for c in self._clients.values())
            qos = {cid: {"weight": c.weight, "priority": c.priority,
                         "assigned": c.assigned,
                         "share": round(c.assigned / total_assigned, 4)
                         if total_assigned else 0.0}
                   for cid, c in self._clients.items()}
        counters = {}
        if self.telemetry.enabled:
            counters = {k: v for k, v in
                        self.telemetry.snapshot()["counters"].items()
                        if k.startswith("service.")}
        with self._lock:
            recovery = {"claims": len(self._claims),
                        "orphan_results": len(self._orphan_results),
                        "replay_buffer_bytes": self._replay_bytes,
                        "journal": self._journal_path}
        out = {"uptime_s": round(time.monotonic() - self._started_at, 1),
               "port": self.port, "boot": self.boot_id, "epoch": self.epoch,
               "workers": workers, "clients": clients, "qos": qos,
               "recovery": recovery,
               "counters": counters, "scaling": self.scaling_signal()}
        # HA health from EITHER role's one-shot stats probe: a primary
        # reports the sync position of every subscribed standby (journal
        # seq fed vs live - standby_lag_items without scraping the standby
        # process), a standby reports its own view of the stream
        jseq = self._journal.seq if self._journal is not None else 0
        with self._lock:
            feeds = dict(self._standby_feeds)
        ha: Dict[str, Any] = {
            "role": "standby" if self._standby else "primary",
            "epoch": self.epoch, "journal_seq": jseq,
            "standbys": {peer: {"synced_seq": pos,
                                "standby_lag_items": max(0, jseq - pos)}
                         for peer, pos in feeds.items()}}
        if self._standby_of is not None:
            ha["standby_lag_items"] = self._standby_lag
            ha["synced_records"] = self._standby_synced
        out["ha"] = ha
        if self._standby_of is not None:
            out["standby"] = {
                "standby": self._standby,
                "of": self._standby_of,
                "promoted": self.standby_promoted.is_set(),
                "primary_epoch": self._primary_epoch,
                "primary_boot": self._primary_boot,
                "synced_records": self._standby_synced,
                "lag_items": self._standby_lag}
        return out
