#!/usr/bin/env python
"""Thread-pool wedge soak: hammer the oversubscribed reader until it wedges.

Post-mortem tool for the RESULTS.md hang watch item (a full-suite run froze
with one worker stuck inside a timed queue get while ``join()`` waited on it
forever).  Runs the oversubscribed stress-test loop continuously with a
PROGRESS-based watchdog: wall-clock slowness from competing load never
fires it; only a genuine absence of batches for ``--wedge-after`` seconds
does.  On a wedge it writes every thread's Python stack AND each OS
thread's in-flight syscall + kernel wait channel (/proc/self/task) to the
dump file — enough to distinguish "stuck in a C-level timed lock wait"
from "waiting for the GIL" — then exits 3.

Usage:  python tools/stress_soak.py [--seconds 14400] [--dump /tmp/soak_dump.txt]
"""
import argparse
import collections
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.schema import Field, Schema
from tools.soak_common import start_progress_watchdog, validated_dataset

ROWS = 192  # 48 rowgroups x 4 rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=14400)
    ap.add_argument("--wedge-after", type=float, default=150,
                    help="seconds without a consumed batch that count as a wedge")
    ap.add_argument("--dump", default="/tmp/soak_dump.txt")
    ap.add_argument("--dataset", default="/tmp/stress_soak_ds")
    args = ap.parse_args()

    def build(url):
        schema = Schema("Stress", [
            Field("id", np.int64),
            Field("payload", np.float32, (64,), NdarrayCodec()),
        ])
        write_dataset(url, schema,
                      [{"id": i, "payload": np.full(64, i, np.float32)}
                       for i in range(ROWS)],
                      row_group_size_rows=4)

    validated_dataset(args.dataset, ROWS, build)
    progress = [0]
    start_progress_watchdog(progress, args.wedge_after, args.dump,
                            label="stress_soak")

    t_start = time.time()
    i = 0
    while time.time() - t_start < args.seconds:
        i += 1
        for workers in (8, 16):
            for epochs in (1, 3):
                with make_batch_reader(args.dataset, reader_pool_type="thread",
                                       workers_count=workers, shuffle_seed=2,
                                       num_epochs=epochs) as r:
                    seen = []
                    for b in r.iter_batches():
                        seen.extend(int(v) for v in b.columns["id"])
                        progress[0] += 1
                    state = r.state_dict()
                counts = collections.Counter(seen)
                assert sorted(counts) == list(range(ROWS)), f"iter {i} loss/dup"
                assert set(counts.values()) == {epochs}
                assert state["position"] == epochs * 48
        progress[0] += 1
        if i % 25 == 0:
            print(f"iter {i} ok t={time.time() - t_start:.0f}s", flush=True)
    print(f"done: {i} iterations, no wedge in {args.seconds:.0f}s", flush=True)


if __name__ == "__main__":
    main()
