"""stack_batches=K: scan-feed delivery as a first-class loader capability.

VERDICT round-4 item 1: the lax.scan dispatch-amortization win moves from
example code into ``JaxDataLoader`` - ONE ``(K, batch, ...)`` transfer per K
steps, with drain/resume and the valid-mask contract defined at stack
granularity.  Reference analog: none (the reference feeds BatchedDataLoader
one batch per step, petastorm/pytorch.py:257-367).
"""

import collections

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.jax import JaxDataLoader
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.schema import Field, Schema

SCHEMA = Schema("Stack", [
    Field("idx", np.int64),
    Field("vec", np.float32, (6,)),
    Field("tag", np.dtype("object")),
])
N_ROWS = 64


@pytest.fixture(scope="module")
def stack_ds(tmp_path_factory):
    url = str(tmp_path_factory.mktemp("stack") / "ds")
    rng = np.random.default_rng(0)
    write_dataset(url, SCHEMA,
                  [{"idx": i, "vec": rng.standard_normal(6).astype(np.float32),
                    "tag": f"t{i}"} for i in range(N_ROWS)],
                  row_group_size_rows=8)
    return url


def test_stack_single_device_shapes_and_order(stack_ds):
    # serial pool: rowgroups arrive in ventilation order, so the delivered
    # order is the exact feed order the stack must preserve
    reader = make_reader(stack_ds, shuffle_row_groups=False,
                         reader_pool_type="serial",
                         schema_fields=["idx", "vec"])
    with JaxDataLoader(reader, batch_size=8, fields=["idx", "vec"],
                       stack_batches=4) as loader:
        units = list(loader)
        diag = loader.diagnostics
    assert len(units) == 2  # 8 batches of 8 rows -> 2 stacks of 4
    u = units[0]
    assert isinstance(u["idx"], jax.Array) and u["idx"].shape == (4, 8)
    assert u["idx"].dtype == np.int32  # promotion happens once, on the stack
    assert u["vec"].shape == (4, 8, 6)
    assert "_valid_rows" not in u  # all steps full
    flat = np.concatenate([np.asarray(u["idx"]).reshape(-1) for u in units])
    assert flat.tolist() == list(range(N_ROWS))  # stack preserves feed order
    assert diag["stack_batches"] == 4
    assert diag["delivered_batches"] == 2  # units, not row batches


def test_stack_drop_last_semantics(stack_ds):
    # 64 rows / batch 8 = 8 batches; K=3 -> 2 full stacks + 2 leftover batches
    def run(drop_last):
        reader = make_reader(stack_ds, shuffle_row_groups=False,
                             reader_pool_type="serial",
                             schema_fields=["idx"])
        with JaxDataLoader(reader, batch_size=8, fields=["idx"],
                           stack_batches=3, drop_last=drop_last) as loader:
            return list(loader)

    dropped = run(True)
    assert len(dropped) == 2  # short final stack dropped, like a partial batch
    assert all("_valid_rows" not in u for u in dropped)

    padded = run(False)
    assert len(padded) == 3
    tail = padded[-1]
    assert tail["idx"].shape == (3, 8)  # static signature even when short
    np.testing.assert_array_equal(np.asarray(tail["_valid_rows"]), [8, 8, 0])
    assert np.asarray(tail["idx"])[2].tolist() == [0] * 8  # zero-pad step
    flat = [int(v) for u in padded
            for k, step in enumerate(np.asarray(u["idx"]))
            for v in step[:int(np.asarray(u.get("_valid_rows", [8] * 3))[k])]]
    assert flat == list(range(N_ROWS))


def test_stack_on_mesh_sharding_and_mask(stack_ds):
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    # 64 rows / global batch 24 -> 2 full + 1 partial(16); K=2 -> the second
    # stack is [partial(16), missing]
    reader = make_reader(stack_ds, shuffle_row_groups=False,
                         schema_fields=["idx", "vec"])
    with JaxDataLoader(reader, batch_size=24, mesh=mesh, stack_batches=2,
                       fields=["idx", "vec"], drop_last=False,
                       shardings={"idx": P("data"), "vec": P("data")},
                       valid_mask_field="mask") as loader:
        units = list(loader)
    assert len(units) == 2
    u = units[0]
    assert u["idx"].shape == (2, 24)
    assert u["vec"].shape == (2, 24, 6)
    assert u["mask"].shape == (2, 24)
    assert u["idx"].sharding.spec == P(None, "data")
    shard_shapes = {s.data.shape for s in u["vec"].addressable_shards}
    assert shard_shapes == {(2, 3, 6)}  # stack axis unsharded, 24/8 rows each
    assert np.asarray(u["mask"]).tolist() == [[1.0] * 24] * 2
    tail = units[1]
    np.testing.assert_array_equal(np.asarray(tail["_valid_rows"]), [16, 0])
    mask = np.asarray(tail["mask"])
    assert mask[0].tolist() == [1.0] * 16 + [0.0] * 8
    assert mask[1].tolist() == [0.0] * 24
    assert np.asarray(tail["idx"])[0, 16:].tolist() == [0] * 8
    # every real row delivered exactly once
    ids = []
    for u in units:
        m = np.asarray(u["mask"]) > 0
        ids.extend(np.asarray(u["idx"])[m].tolist())
    assert sorted(ids) == list(range(N_ROWS))


def test_stack_host_fields_and_transform(stack_ds):
    calls = []

    def xform(cols):
        calls.append(len(cols["idx"]))  # runs per BATCH, before stacking
        cols = dict(cols)
        cols["idx"] = cols["idx"] * 2
        return cols

    reader = make_reader(stack_ds, shuffle_row_groups=False,
                         reader_pool_type="serial",
                         schema_fields=["idx", "tag"])
    with JaxDataLoader(reader, batch_size=8, fields=["idx"],
                       host_fields=["tag"], stack_batches=2,
                       transform_fn=xform) as loader:
        units = list(loader)
    assert all(n == 8 for n in calls) and len(calls) == 8
    u = units[0]
    assert u["tag"].shape == (2, 8) and u["tag"].dtype == object
    assert u["tag"][0, 0] == "t0" and u["tag"][1, 0] == "t8"
    assert np.asarray(u["idx"])[0].tolist() == [2 * i for i in range(8)]


def test_stack_lax_scan_consumer(stack_ds):
    """The delivered (K, B, ...) unit drives lax.scan directly - the whole
    point of the capability."""
    reader = make_reader(stack_ds, shuffle_row_groups=False,
                         schema_fields=["idx", "vec"])

    @jax.jit
    def scan_sum(vecs):           # (K, B, 6) -> scalar via K scanned steps
        def body(carry, x):
            return carry + x.sum(), None
        total, _ = jax.lax.scan(body, jnp.float32(0), vecs)
        return total

    total = 0.0
    expect = 0.0
    with JaxDataLoader(reader, batch_size=8, fields=["idx", "vec"],
                       stack_batches=4) as loader:
        for u in loader:
            total += float(scan_sum(u["vec"]))
            expect += float(np.asarray(u["vec"]).sum())
    assert total == pytest.approx(expect, rel=1e-5)


def test_stack_drain_exact_resume(tmp_path):
    """drain()/state_dict() at stack granularity: zero re-read rows."""
    # enough rowgroups that the in-flight window (queues + the accumulating
    # stack group) cannot swallow the whole dataset before quiesce
    url = str(tmp_path / "drain_ds")
    rng = np.random.default_rng(1)
    n_rows = 128
    write_dataset(url, SCHEMA,
                  [{"idx": i, "vec": rng.standard_normal(6).astype(np.float32),
                    "tag": f"t{i}"} for i in range(n_rows)],
                  row_group_size_rows=2)
    seen = []
    with make_batch_reader(url, reader_pool_type="thread", workers_count=2,
                           results_queue_size=2, shuffle_seed=7,
                           num_epochs=1) as r:
        with JaxDataLoader(r, batch_size=4, stack_batches=2,
                           fields=["idx", "vec"], drop_last=False) as loader:
            it = iter(loader)
            u = next(it)
            seen.extend(np.asarray(u["idx"]).reshape(-1).tolist())
            for u in loader.drain():
                valid = np.asarray(u.get("_valid_rows", [4, 4]))
                for k, step in enumerate(np.asarray(u["idx"])):
                    seen.extend(step[:valid[k]].tolist())
            state = loader.state_dict()
    assert state["reader"]["ordinal_exact"]
    assert state["stack_batches"] == 2

    resumed = []
    with make_batch_reader(url, reader_pool_type="thread", workers_count=2,
                           shuffle_seed=7, num_epochs=1,
                           resume_from=state["reader"]) as r:
        with JaxDataLoader(r, batch_size=4, stack_batches=2,
                           fields=["idx", "vec"], drop_last=False) as loader:
            for u in loader:
                valid = np.asarray(u.get("_valid_rows", [4, 4]))
                for k, step in enumerate(np.asarray(u["idx"])):
                    resumed.extend(step[:valid[k]].tolist())
    counts = collections.Counter(seen + resumed)
    assert sorted(counts) == list(range(n_rows)), "rows lost"
    assert max(counts.values()) == 1, "rows re-read: cursor was not exact"
    assert resumed, "drain consumed everything; resume proved nothing"


def test_stack_drain_multihost_alignment(stack_ds):
    """Short hosts pad with zero STACKS whose '_valid_rows' is a (K,) zero
    array and whose mask is all-zero - the pod-safe drain contract at stack
    granularity."""
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    with make_batch_reader(stack_ds, shuffle_row_groups=False,
                           num_epochs=1) as r:
        with JaxDataLoader(r, batch_size=16, mesh=mesh, stack_batches=2,
                           drop_last=False, fields=["idx", "vec"],
                           shardings={"idx": P("data"), "vec": P("data")},
                           valid_mask_field="mask") as loader:
            it = iter(loader)
            next(it)
            drained = list(loader.drain(
                all_gather_counts=lambda mine: [mine, mine + 2]))
    pads = drained[-2:]
    for pad in pads:
        np.testing.assert_array_equal(np.asarray(pad["_valid_rows"]), [0, 0])
        assert pad["idx"].shape == (2, 16)
        assert pad["idx"].sharding.spec == P(None, "data")
        assert np.asarray(pad["mask"]).sum() == 0
        assert np.asarray(pad["vec"]).sum() == 0


def test_stack_validation_errors(stack_ds):
    reader = make_reader(stack_ds, schema_fields=["idx", "vec"])
    try:
        with pytest.raises(PetastormTpuError, match="stack_batches must be"):
            JaxDataLoader(reader, batch_size=8, stack_batches=0)
        with pytest.raises(PetastormTpuError, match="device_shuffle_capacity"):
            JaxDataLoader(reader, batch_size=8, fields=["idx", "vec"],
                          stack_batches=2, device_shuffle_capacity=4)
        with pytest.raises(PetastormTpuError, match="multi-bucket"):
            JaxDataLoader(reader, batch_size=8, fields=["vec"],
                          stack_batches=2,
                          pad_shapes={"vec": [(6,), (8,)]})
    finally:
        reader.stop()
        reader.join()


# -- hybrid on-chip decode under stacking -------------------------------------
# cv2/native guards live INSIDE the fixture and tests: a module-level
# importorskip would silently skip the eight core stack tests above, which
# need neither

from petastorm_tpu.native import image as native_image  # noqa: E402

needs_native = pytest.mark.skipif(not native_image.available(),
                                  reason="native image library unavailable")


@pytest.fixture(scope="module")
def jpeg_ds(tmp_path_factory):
    pytest.importorskip("cv2")
    from petastorm_tpu.codecs import CompressedImageCodec

    from tests.test_jpeg_hybrid import _smooth_rgb

    schema = Schema("StackJpeg", [
        Field("idx", np.int64),
        Field("image", np.uint8, (24, 32, 3),
              CompressedImageCodec("jpeg", quality=92)),
    ])
    url = str(tmp_path_factory.mktemp("stack_jpeg") / "ds")
    write_dataset(url, schema,
                  [{"idx": i, "image": _smooth_rgb(24, 32, seed=i)}
                   for i in range(32)],
                  row_group_size_rows=8)
    return url


@needs_native
def test_stack_device_decode_uniform(jpeg_ds):
    """decode_placement='device' + stack_batches: the K batches' coefficient
    planes ship as one (K, B, ...) transfer and decode in one on-chip call;
    pixels match the host decode."""
    from tests.test_jpeg_hybrid import _cv2_decode, _encode, _smooth_rgb

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    with make_batch_reader(jpeg_ds, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=8, mesh=mesh, stack_batches=2,
                           fields=["idx", "image"],
                           shardings={"idx": P("data"),
                                      "image": P("data")}) as loader:
            units = list(loader)
    assert len(units) == 2
    u = units[0]
    assert u["image"].shape == (2, 8, 24, 32, 3)
    assert u["image"].sharding.spec == P(None, "data")
    got = {}
    for u in units:
        idxs = np.asarray(u["idx"])
        imgs = np.asarray(u["image"])
        for k in range(idxs.shape[0]):
            for j, i in enumerate(idxs[k]):
                got[int(i)] = imgs[k, j]
    assert sorted(got) == list(range(32))
    for i in (0, 9, 31):
        ref = _cv2_decode(_encode(_smooth_rgb(24, 32, seed=i), quality=92))
        diff = np.abs(ref.astype(int) - got[i].astype(int))
        assert diff.max() <= 6 and diff.mean() < 1.0, f"idx {i}"


@needs_native
def test_stack_device_decode_partial_tail(jpeg_ds):
    """Short final stack + partial rows with on-chip decode: zero-gray pad
    rows, per-step '_valid_rows', mask marks exactly the real rows."""
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    # 32 rows / batch 24 -> full + partial(8); K=2 -> one stack [24, 8]
    with make_batch_reader(jpeg_ds, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device"}) as r:
        with JaxDataLoader(r, batch_size=24, mesh=mesh, stack_batches=2,
                           drop_last=False, fields=["idx", "image"],
                           shardings={"idx": P("data"), "image": P("data")},
                           valid_mask_field="mask") as loader:
            units = list(loader)
    assert len(units) == 1
    u = units[0]
    np.testing.assert_array_equal(np.asarray(u["_valid_rows"]), [24, 8])
    mask = np.asarray(u["mask"])
    assert mask[0].tolist() == [1.0] * 24
    assert mask[1].tolist() == [1.0] * 8 + [0.0] * 16
    ids = np.asarray(u["idx"])[mask > 0]
    assert sorted(ids.tolist()) == list(range(32))


@needs_native
def test_stack_mixed_decode(tmp_path):
    """decode_placement='device-mixed' + stack_batches: the K batches' cells
    decode as one flat bucket pass, reshape to (K, B, ...), scatter."""
    from petastorm_tpu.codecs import CompressedImageCodec

    from tests.test_jpeg_hybrid import _cv2_decode, _encode, _smooth_rgb

    geometries = [(16, 24), (24, 16)]
    target = (24, 24, 3)
    schema = Schema("StackMixed", [
        Field("idx", np.int64),
        Field("image", np.uint8, (None, None, 3),
              CompressedImageCodec("jpeg", quality=92)),
    ])
    url = str(tmp_path / "ds")
    write_dataset(url, schema,
                  [{"idx": i,
                    "image": _smooth_rgb(*geometries[i % 2], seed=i)}
                   for i in range(16)],
                  row_group_size_rows=4)
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    with make_batch_reader(url, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device-mixed"}) as r:
        with JaxDataLoader(r, batch_size=8, mesh=mesh, stack_batches=2,
                           fields=["idx", "image"],
                           pad_shapes={"image": target},
                           shardings={"idx": P("data"),
                                      "image": P("data")}) as loader:
            units = list(loader)
    assert len(units) == 1
    u = units[0]
    assert u["image"].shape == (2, 8) + target
    assert u["image"].sharding.spec == P(None, "data")
    idxs, imgs = np.asarray(u["idx"]), np.asarray(u["image"])
    for k in range(2):
        for j, i in enumerate(idxs[k]):
            h, w = geometries[int(i) % 2]
            ref = _cv2_decode(_encode(_smooth_rgb(h, w, seed=int(i)),
                                      quality=92))
            diff = np.abs(ref.astype(int) - imgs[k, j, :h, :w].astype(int))
            assert diff.max() <= 6 and diff.mean() < 1.0, f"idx {int(i)}"
