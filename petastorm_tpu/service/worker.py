"""Remote ingest worker: runs client worker-factories against dispatched items.

One ``ServiceWorker`` process serves every client of its dispatcher: for
each client it unpickles the client's worker factory (the exact
``pool.WorkerFactory`` the in-process executors would have started -
normally a :class:`~petastorm_tpu.worker.RowGroupDecoderWorker`, possibly
chaos-wrapped) and runs ``fn(VentilatedItem) -> ColumnBatch`` over its
assigned items on ``capacity`` processor threads (pyarrow IO and native
decode release the GIL, same reasoning as the in-process thread pool).

Decode-once sharing: a factory carrying ``cache_type='shared'`` attaches
this host's warm tier on unpickle, so co-located workers (and repeated
epochs, and other clients' jobs with matching cache keys) decode each
rowgroup once fleet-wide - the tier IS the cross-worker data plane
(docs/operations.md "Warm cache").

Heartbeats carry the worker's busy count plus telemetry counter deltas
(``decode.*`` / ``worker.*`` / ``cache.*``), which the dispatcher folds
into its registry as ``service.fleet.*`` - the fleet-wide observable proof
that each rowgroup decoded at most once.

Crash semantics match the process pool: an exception whose
``petastorm_tpu_simulated_crash`` attribute is set (the chaos harness's
hard-kill injection) exits the process with ``os._exit`` - no result, no
goodbye - and the dispatcher's death detection requeues the in-flight
items onto surviving workers.

Dispatcher-restart survival (``reconnect_attempts > 0``): losing the
dispatcher connection does NOT drop this worker's state.  The processor
threads keep executing their in-flight items through the outage; finished
outcomes buffer in a bounded outbox; and the rejoin hello reports the
still-held assignments plus the client jobs this process already holds -
the restarted dispatcher records them as claims so a reconnecting client's
resync re-attaches those ordinals here instead of double-assigning them,
then the outbox flushes (docs/operations.md "Fault domains").  An outcome
the outbox must shed (overflow) simply forgets its assignment: the
client's resync re-enqueues that item and it re-executes - correctness by
re-fetch, never by unbounded buffering.
"""

from __future__ import annotations

import collections
import logging
import os
import pickle
import queue
import socket
import threading
import time
from typing import Any, Dict, Optional, Tuple

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.pool import VentilatedItem, _Failure
from petastorm_tpu.service.protocol import (PROTOCOL_VERSION,
                                            FrameClosedError, FrameSocket,
                                            connect_frames, encode_result,
                                            parse_address_list,
                                            resolve_auth_token,
                                            shm_transport_available)
from petastorm_tpu.service.wire import SUPPORTED_CODECS, WireFormatError
from petastorm_tpu.telemetry import Telemetry
from petastorm_tpu.telemetry import resolve as _resolve_telemetry

logger = logging.getLogger(__name__)

#: outbox bounds while disconnected: results past these are shed oldest-
#: first (their assignments are forgotten, so clients re-fetch them)
OUTBOX_MAX_ITEMS = 512
OUTBOX_MAX_BYTES = 256 << 20


def _inject_telemetry(factory: Any, telemetry) -> None:
    """Point a (possibly wrapped) worker factory at this process's recorder.

    ``RowGroupDecoderWorker`` resolves its recorder lazily in ``__call__``
    when ``_telemetry`` is None (the pickled state always is - see its
    ``__getstate__``); chaos wrappers hold the real factory in ``_inner``.
    Best-effort by design: an opaque factory just runs unrecorded.
    """
    seen = set()
    while factory is not None and id(factory) not in seen:
        seen.add(id(factory))
        if hasattr(factory, "_telemetry"):
            factory._telemetry = telemetry  # noqa: SLF001 - documented hook
        factory = getattr(factory, "_inner", None) or getattr(
            factory, "_worker_factory", None)


class ServiceWorker:
    """One remote worker process/thread of the ingest-service fleet.

    ``capacity``: concurrent items this worker accepts (the dispatcher
    assigns at most this many in flight); each runs on its own processor
    thread.  ``shm_size_bytes`` > 0 arms the local fast path: results for
    co-located clients are encoded into a named shared-memory arena
    (descriptor on the wire, zero-copy decode client-side) when the native
    transport plane is available - remote clients always get plain frame
    payloads.  ``reconnect_attempts`` > 0 makes a lost dispatcher
    connection a recoverable event instead of a worker exit: in-flight
    work keeps executing, registration retries every
    ``reconnect_backoff_s``, and the rejoin reports held assignments/jobs
    (module docstring).

    ``address`` may be a comma-separated failover list
    (``'primary:port,standby:port'``): registration rotates through it,
    so when a hot-standby dispatcher promotes, the same retry loop lands
    on the survivor with the worker's held state intact.  Epoch fencing
    rides the same handshake: every ``hello_ok``/``hb_ok`` carries the
    dispatcher's fencing epoch, and a dispatcher advertising an epoch
    *below* the highest this worker has seen is a deposed primary - its
    registration is refused (``service.stale_epoch_refusals``) and the
    rotation moves on, so a partitioned ex-primary can never hand this
    worker work its successor also assigned.
    """

    def __init__(self, address, capacity: int = 2, name: Optional[str] = None,
                 telemetry=None, heartbeat_interval_s: float = 2.0,
                 shm_size_bytes: int = 0, auth_token: Optional[str] = None,
                 reconnect_attempts: int = 0,
                 reconnect_backoff_s: float = 1.0):
        if capacity < 1:
            raise PetastormTpuError("ServiceWorker capacity must be >= 1")
        self._addresses = parse_address_list(address)
        self._addr_index = 0
        self._address = self._addresses[0]
        #: highest fencing epoch any dispatcher has advertised to us; a
        #: hello_ok below this is a deposed primary and is refused
        self._dispatcher_epoch = 0
        #: handshake secret (default $PETASTORM_TPU_SERVICE_TOKEN); must
        #: match the dispatcher's when it enforces one
        self._auth_token = resolve_auth_token(auth_token)
        self._capacity = int(capacity)
        self._name = name
        #: a private recorder by default: heartbeat counter deltas must not
        #: entangle with (or pollute) any client telemetry in this process
        self.telemetry = (_resolve_telemetry(telemetry)
                          if telemetry is not None else Telemetry())
        self._hb_interval = float(heartbeat_interval_s)
        self._shm_size_bytes = int(shm_size_bytes)
        self._arena = None
        self._stop_event = threading.Event()
        self._reconnect_attempts = int(reconnect_attempts)
        self._reconnect_backoff_s = float(reconnect_backoff_s)
        self._conn: Optional[FrameSocket] = None
        self._conn_lock = threading.Lock()
        self._connected = threading.Event()
        self._work: "queue.Queue[tuple]" = queue.Queue()
        self._busy = 0
        self._busy_lock = threading.Lock()
        self._jobs: Dict[str, Dict] = {}   # cid -> {"factory": blob, "shm_ok"}
        self._fns: Dict[str, Any] = {}     # cid -> built fn
        self._fn_lock = threading.Lock()
        #: (cid, ordinal) -> attempt for every item this worker holds -
        #: queued or executing - until its outcome reaches a LIVE
        #: connection.  Reported on rejoin so nothing is double-assigned.
        self._held: Dict[Tuple[str, int], int] = {}
        self._held_lock = threading.Lock()
        #: outcomes finished while disconnected: (kind, header, parts, key)
        self._outbox: "collections.deque" = collections.deque()
        self._outbox_bytes = 0
        self._hb_snapshot: Dict[str, float] = {}
        #: estimated offset of the dispatcher's perf_counter_ns clock from
        #: ours (handshake round-trip midpoint; error ~ RTT/2).  Rides every
        #: trace hop stamp we emit so the client can map our stamps into
        #: its own clock domain through the dispatcher's.
        self._clock_offset_ns = 0
        #: structured events to piggyback on the next heartbeat (folded
        #: into the dispatcher's bounded fleet event log under our name)
        self._pending_events: "collections.deque" = collections.deque(
            maxlen=32)
        self._threads = []
        self._threads_started = False
        self.worker_name: Optional[str] = None
        self.items_processed = 0
        self.dispatcher_reconnects = 0
        #: graceful retirement state (begin_retire/retire): the heartbeat
        #: thread drives the drain so arming it is signal-safe
        self._retiring = threading.Event()
        self._retire_acked = threading.Event()
        self._retire_sent = False
        #: dispatcher confirmed (drain_ok) that nothing is in flight
        #: toward us - the structural half of the drain handshake
        self._drain_confirmed = threading.Event()
        self.retired_gracefully = False

    # -- lifecycle ------------------------------------------------------------

    def stop(self) -> None:
        """Stop serving: close the dispatcher connection (in-flight items
        are requeued onto surviving workers by the dispatcher)."""
        self._stop_event.set()
        self._connected.clear()
        conn = self._conn
        if conn is not None:
            conn.close()

    def begin_retire(self) -> None:
        """Arm **graceful retirement** (idempotent, signal-safe: only sets
        a flag).  The heartbeat thread then runs the drain protocol: send
        ``retiring`` (the dispatcher stops assigning to us and acks with
        ``retire_ok``), finish every held item, flush the outbox, say
        ``bye``, and exit - nothing is dropped or requeued, so
        ``deterministic='seed'`` streams ride a scale-down untouched.
        A retirement that cannot finish (dispatcher gone for good) is
        force-resolved by the caller's timeout (:meth:`retire`) - the
        dispatcher's death detection requeues whatever was left."""
        self._retiring.set()

    def retire(self, timeout: Optional[float] = None) -> bool:
        """Blocking graceful retirement: arm the drain and wait up to
        ``timeout`` (None = forever) for it to complete.  Returns True when
        the worker drained and said goodbye; False on timeout (the caller
        decides whether to :meth:`stop` it hard)."""
        self.begin_retire()
        self._stop_event.wait(timeout)
        return self.retired_gracefully

    def run(self) -> int:
        """Connect, register, and serve until the dispatcher goes away
        (for longer than the reconnect budget) or :meth:`stop` is called.
        Returns an exit code (0 = clean, 1 = never registered)."""
        attempts_left = self._reconnect_attempts
        registered_once = False
        try:
            while not self._stop_event.is_set():
                conn = None
                addr = self._addresses[self._addr_index
                                       % len(self._addresses)]
                self._address = addr
                try:
                    conn = connect_frames(addr)
                    self._register(conn)
                except (OSError, PetastormTpuError) as exc:
                    # covers unreachable/refused dispatchers AND a
                    # dispatcher mid-restart that accepts then resets
                    # inside the hello; a standby refuses worker hellos
                    # until promoted, which lands here too - the rotation
                    # below walks the failover list until the live
                    # (highest-epoch) dispatcher answers
                    if conn is not None:
                        conn.close()
                    self._addr_index += 1
                    if attempts_left <= 0:
                        if registered_once:
                            logger.warning(
                                "Dispatcher gone and the reconnect budget"
                                " is spent; worker exiting (%s)", exc)
                            return 0
                        logger.error("Cannot register with dispatcher at"
                                     " %s:%d: %s", addr[0], addr[1], exc)
                        return 1
                    attempts_left -= 1
                    # a multi-address fleet retries the next address
                    # immediately (the whole point of a hot standby is
                    # failing over in heartbeat time, not backoff time);
                    # only a full rotation with no winner backs off
                    if len(self._addresses) == 1 \
                            or self._addr_index % len(self._addresses) == 0:
                        logger.info("Dispatcher unavailable (%s); retrying"
                                    " registration in %.1fs (%d attempt(s)"
                                    " left)", exc, self._reconnect_backoff_s,
                                    attempts_left + 1)
                        self._stop_event.wait(self._reconnect_backoff_s)
                    continue
                if registered_once:
                    self.dispatcher_reconnects += 1
                registered_once = True
                attempts_left = self._reconnect_attempts  # reset on success
                self._start_threads()
                self._attach(conn)
                self._serve(conn)
                with self._conn_lock:
                    self._connected.clear()
                conn.close()
                if self._stop_event.is_set() or attempts_left <= 0:
                    break
        finally:
            self.stop()
            if self._arena is not None:
                self._arena.close()
        return 0 if registered_once else 1

    def _register(self, conn: FrameSocket) -> None:
        """One registration handshake; raises OSError/PetastormTpuError on
        refusal.  A re-registration (rejoin) reports held assignments and
        jobs so the dispatcher can re-attach instead of double-assigning."""
        with self._held_lock:
            assignments = [[cid, ordinal, attempt]
                           for (cid, ordinal), attempt in self._held.items()]
        with self._fn_lock:
            jobs = list(self._jobs)
        resume = self.worker_name is not None
        t0 = time.perf_counter_ns()
        conn.send({"t": "worker_hello", "protocol": PROTOCOL_VERSION,
                   "worker": self._name or self.worker_name,
                   "capacity": self._capacity,
                   "hostname": socket.gethostname(), "pid": os.getpid(),
                   "codecs": list(SUPPORTED_CODECS),
                   "token": self._auth_token,
                   "resume": resume,
                   "assignments": assignments, "jobs": jobs})
        hello = conn.recv(timeout=10.0)
        t1 = time.perf_counter_ns()
        if not hello or hello.get("t") != "hello_ok":
            raise PetastormTpuError(
                f"dispatcher refused registration: {hello!r}")
        epoch = hello.get("epoch")
        if isinstance(epoch, int):
            if epoch < self._dispatcher_epoch:
                # split-brain fencing: this is a deposed primary (its
                # epoch predates one we already worked under) - refuse
                # it and let the rotation find the promoted standby
                self.telemetry.counter(
                    "service.stale_epoch_refusals").add(1)
                raise PetastormTpuError(
                    f"dispatcher at {self._address[0]}:{self._address[1]}"
                    f" advertises stale epoch {epoch} <"
                    f" {self._dispatcher_epoch}: refusing a deposed"
                    " primary")
            self._dispatcher_epoch = epoch
        clock_ns = hello.get("clock_ns")
        if isinstance(clock_ns, int):
            # offset estimate: the dispatcher stamped clock_ns somewhere
            # inside our [t0, t1] round-trip; the midpoint bounds the
            # error at RTT/2 (per-hop histogram deltas never use this -
            # only the merged cross-process trace timeline does)
            self._clock_offset_ns = clock_ns - (t0 + t1) // 2
        self.worker_name = hello.get("worker")
        if resume:
            self._pending_events.append(
                {"kind": "worker_rejoin",
                 "held_items": len(assignments),
                 "buffered_outcomes": len(self._outbox)})
            logger.info("Rejoined dispatcher as %s (still holding %d"
                        " item(s), %d buffered outcome(s))",
                        self.worker_name, len(assignments),
                        len(self._outbox))
        else:
            logger.info("Registered with dispatcher as %s (capacity %d)",
                        self.worker_name, self._capacity)

    def _start_threads(self) -> None:
        if self._threads_started:
            return
        self._threads_started = True
        for i in range(self._capacity):
            t = threading.Thread(target=self._processor_loop, daemon=True,
                                 name=f"petastorm-tpu-service-proc-{i}")
            t.start()
            self._threads.append(t)
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True,
                              name="petastorm-tpu-service-heartbeat")
        hb.start()
        self._threads.append(hb)

    def _attach(self, conn: FrameSocket) -> None:
        """Swap the live connection in and flush buffered outcomes."""
        with self._conn_lock:
            self._conn = conn
            self._connected.set()
        # a retirement armed across a reconnect must re-announce itself to
        # the (possibly restarted) dispatcher before the drain can finish
        self._retire_sent = False
        self._retire_acked.clear()
        self._drain_confirmed.clear()
        self._flush_outbox()

    def _serve(self, conn: FrameSocket) -> None:
        """The dispatcher read loop for one connection; returns when it is
        lost (the run loop decides between reconnect and exit)."""
        try:
            while not self._stop_event.is_set():
                msg = conn.recv(timeout=1.0)
                if msg is None:
                    continue
                kind = msg.get("t")
                if kind == "job":
                    with self._fn_lock:
                        self._jobs[msg["client"]] = {
                            "factory": msg["factory"],
                            "shm_ok": bool(msg.get("shm_ok")),
                            # negotiated BATCH-body compression for this
                            # (worker, client) pair ('' = off)
                            "codec": msg.get("codec") or ""}
                elif kind == "work":
                    # the item blob is the trusted client->worker job plane:
                    # this is the ONE place (beyond the factory bootstrap)
                    # service bytes are unpickled, and only for items the
                    # auth-gated dispatcher assigned to us
                    wi = msg["item"]
                    item = VentilatedItem(wi["o"], pickle.loads(wi["blob"]),
                                          wi.get("a", 0))
                    cid = msg["client"]
                    tc = wi.get("tc")
                    if isinstance(tc, dict):
                        # traced item: stamp its arrival (the worker-queue
                        # hop opens here); our clock offset rides each
                        # stamp so the client can remap it
                        tc.setdefault("hops", []).append(
                            [self.worker_name or "w?", "recv",
                             item.attempt, time.perf_counter_ns(),
                             self._clock_offset_ns])
                    else:
                        tc = None
                    with self._held_lock:
                        self._held[(cid, item.ordinal)] = item.attempt
                    self._work.put((cid, item, tc))
                elif kind == "job_done":
                    with self._fn_lock:
                        self._jobs.pop(msg["client"], None)
                        self._fns.pop(msg["client"], None)
                elif kind == "retire_ok":
                    # the dispatcher marked us draining (no new work will
                    # be assigned); the heartbeat thread completes the
                    # drain once everything held has been delivered
                    self._retire_acked.set()
                elif kind == "hb_ok":
                    epoch = msg.get("epoch")
                    if isinstance(epoch, int) \
                            and epoch > self._dispatcher_epoch:
                        self._dispatcher_epoch = epoch
                elif kind == "drain_ok":
                    # dispatcher-confirmed: nothing is in flight toward
                    # us (recorded-before-send on its side makes this
                    # structural, not a timing window)
                    self._drain_confirmed.set()
                elif kind == "drain_wait":
                    self._drain_confirmed.clear()
                elif kind == "stop":
                    self._stop_event.set()
                    break
        except FrameClosedError:
            if not self._stop_event.is_set():
                logger.warning("Dispatcher connection closed")
        except WireFormatError:
            if not self._stop_event.is_set():
                logger.warning("Dispatcher sent an undecodable frame;"
                               " dropping the connection", exc_info=True)

    # -- processing -----------------------------------------------------------

    def _fn_for(self, cid: str):
        """The built worker function for one client (built once, under a
        lock: factories open datasets lazily so the build is cheap, but two
        processor threads must not race it).

        A work frame can arrive moments BEFORE its client's job frame: two
        dispatcher threads pumping the same worker send job+work1 and work2
        concurrently, and only bytes - not cross-thread order - are
        serialized.  The job frame is guaranteed in flight (the dispatcher
        marks the pair before sending any work for it), so wait briefly
        for it instead of failing the item; the wait loop releases the lock
        so the read loop can register the arriving job."""
        deadline = time.monotonic() + 5.0
        while True:
            with self._fn_lock:
                fn = self._fns.get(cid)
                if fn is not None:
                    return fn
                job = self._jobs.get(cid)
                if job is not None:
                    factory = pickle.loads(job["factory"])
                    _inject_telemetry(factory, self.telemetry)
                    fn = factory()
                    self._fns[cid] = fn
                    return fn
            if time.monotonic() > deadline or self._stop_event.is_set():
                raise PetastormTpuError(
                    f"work for unknown client {cid!r} (no job spec received"
                    " within 5s)")
            time.sleep(0.01)

    def _arena_for(self, cid: str):
        """The shm arena for local-fast-path encoding, or None (remote
        client, shm disabled, or the native plane is unavailable)."""
        if self._shm_size_bytes <= 0 or not shm_transport_available():
            return None
        with self._fn_lock:
            job = self._jobs.get(cid)
            if job is None or not job["shm_ok"]:
                return None
            if self._arena is None:
                from petastorm_tpu.native import SharedArena

                self._arena = SharedArena.create(self._shm_size_bytes)
            return self._arena

    def _codec_for(self, cid: str) -> str:
        """The negotiated BATCH-body codec for one client ('' = off)."""
        with self._fn_lock:
            job = self._jobs.get(cid)
            return job["codec"] if job else ""

    def _trace_stamp(self, tc: Dict, name: str, attempt: int,
                     prev: Optional[str] = None,
                     hop: Optional[str] = None) -> int:
        """Append one hop stamp to a traced item's context; when ``prev``/
        ``hop`` name the stamp that opened this hop, record the same-
        process monotonic delta into the ``service.hop.<hop>`` histogram
        (skew-free - both ends are our own clock)."""
        now_ns = time.perf_counter_ns()
        hops = tc.setdefault("hops", [])
        if prev is not None and self.telemetry.enabled:
            for who, hname, _a, t_ns, _off in reversed(hops):
                if hname == prev and who != "d":
                    self.telemetry.histogram(f"service.hop.{hop}").record(
                        max(0, now_ns - t_ns) / 1e9)
                    break
        hops.append([self.worker_name or "w?", name, attempt, now_ns,
                     self._clock_offset_ns])
        return now_ns

    def _processor_loop(self) -> None:
        tele = self.telemetry
        while not self._stop_event.is_set():
            try:
                cid, item, tc = self._work.get(timeout=0.05)
            except queue.Empty:
                continue
            with self._busy_lock:
                self._busy += 1
            ordinal = getattr(item, "ordinal", None)
            attempt = getattr(item, "attempt", 0)
            try:
                try:
                    if tc is not None:
                        self._trace_stamp(tc, "start", attempt,
                                          prev="recv", hop="worker_queue")
                    fn = self._fn_for(cid)
                    result = fn(item)
                except BaseException as exc:  # noqa: BLE001 - forwarded
                    if getattr(exc, "petastorm_tpu_simulated_crash", False):
                        # chaos harness: die like the OOM killer struck -
                        # no result, no goodbye; the dispatcher's death
                        # detection requeues our in-flight items
                        os._exit(137)
                    self._send_failure(cid, ordinal, attempt, exc, item)
                else:
                    try:
                        t0 = (time.perf_counter_ns() if tele.enabled
                              else None)
                        header, parts = encode_result(
                            result, arena=self._arena_for(cid),
                            stop_check=self._stop_event.is_set,
                            codec=self._codec_for(cid))
                        header.update({
                            "t": "result", "client": cid,
                            "ordinal": ordinal, "attempt": attempt,
                            "rows": getattr(result, "num_rows", 0)})
                        if tc is not None:
                            # exec+encode done: close the worker-exec hop
                            # and return the accumulated timeline with the
                            # result header
                            self._trace_stamp(tc, "done", attempt,
                                              prev="start", hop="worker_exec")
                            header["tc"] = tc
                        if t0 is not None:
                            # outbound wire-encoding cost, per direction
                            # (the client records service.decode)
                            tele.record_stage(
                                "service.encode", t0,
                                time.perf_counter_ns() - t0,
                                {"ordinal": ordinal, "pk": header["pk"]})
                        self._deliver("batch", header, parts,
                                      key=(cid, ordinal))
                    except Exception as exc:  # noqa: BLE001 - must answer
                        # an unencodable result (unpicklable transform
                        # output, oversize frame) must become a classified
                        # failure, not a silently-dead processor thread and
                        # a forever-hanging client ordinal
                        logger.warning("result for item %s not encodable;"
                                       " forwarding as failure", ordinal,
                                       exc_info=True)
                        self._send_failure(cid, ordinal, attempt, exc, item)
                    else:
                        self.items_processed += 1
                        if tele.enabled:
                            tele.counter("service.worker_results").add(1)
                            tele.counter(
                                "service.frames_binary"
                                if header["pk"] == "bin" else
                                "service.frames_shm"
                                if header["pk"] == "shm" else
                                "service.frames_pickle_fallback").add(1)
            finally:
                with self._busy_lock:
                    self._busy -= 1

    # -- outcome delivery (live or buffered across a dispatcher outage) -------

    def _deliver(self, kind: str, header: Dict, parts,
                 key: Optional[Tuple[str, int]] = None) -> None:
        """Send one outcome on the live connection, or buffer it in the
        bounded outbox while disconnected.  ``key`` is the held-assignment
        entry the outcome resolves; it is released only once the outcome
        reaches a live connection (or is shed with its outcome)."""
        with self._conn_lock:
            conn = self._conn if self._connected.is_set() else None
        if conn is not None:
            try:
                if kind == "batch":
                    conn.send_batch(header, parts)
                else:
                    conn.send(header)
                self._release_held(key)
                return
            except OSError:
                with self._conn_lock:
                    if self._conn is conn:
                        self._connected.clear()
                conn.close()
        if self._reconnect_attempts <= 0:
            # no rejoin coming: the dispatcher's death detection requeues
            # our items; buffering would just hold memory until exit
            self._release_held(key)
            return
        self._outbox_push(kind, header, parts, key)

    def _release_held(self, key) -> None:
        if key is None:
            return
        with self._held_lock:
            self._held.pop(key, None)

    def _outbox_push(self, kind: str, header: Dict, parts, key) -> None:
        size = sum(len(p) for p in parts or ())
        with self._held_lock:
            self._outbox.append((kind, header, parts, key, size))
            self._outbox_bytes += size
            while self._outbox and (len(self._outbox) > OUTBOX_MAX_ITEMS
                                    or self._outbox_bytes > OUTBOX_MAX_BYTES):
                _k, _h, _p, old_key, old_size = self._outbox.popleft()
                self._outbox_bytes -= old_size
                if old_key is not None:
                    # shedding the outcome forgets the assignment too: the
                    # client's resync re-enqueues it (re-fetch, not a hang)
                    self._held.pop(old_key, None)
                self._pending_events.append({"kind": "outbox_shed",
                                             "outbox_items":
                                                 len(self._outbox)})
                logger.warning("outbox overflow while disconnected: shed one"
                               " buffered outcome (client will re-fetch)")

    def _flush_outbox(self) -> None:
        """Drain buffered outcomes onto the fresh connection (rejoin)."""
        while True:
            with self._held_lock:
                if not self._outbox:
                    return
                kind, header, parts, key, size = self._outbox.popleft()
                self._outbox_bytes -= size
            with self._conn_lock:
                conn = self._conn if self._connected.is_set() else None
            if conn is None:
                with self._held_lock:
                    self._outbox.appendleft((kind, header, parts, key, size))
                    self._outbox_bytes += size
                return
            try:
                if kind == "batch":
                    conn.send_batch(header, parts)
                else:
                    conn.send(header)
                self._release_held(key)
            except OSError:
                with self._held_lock:
                    self._outbox.appendleft((kind, header, parts, key, size))
                    self._outbox_bytes += size
                with self._conn_lock:
                    if self._conn is conn:
                        self._connected.clear()
                return

    def _send(self, msg: Dict) -> None:
        """Best-effort control send on the live connection (heartbeats):
        never buffered, dropped while disconnected."""
        with self._conn_lock:
            conn = self._conn if self._connected.is_set() else None
        if conn is None:
            return
        try:
            conn.send(msg)
        except OSError:
            # dispatcher gone mid-send: the read loop notices EOF and the
            # run loop reconnects (or exits); it requeues whatever we held
            logger.debug("send failed (dispatcher gone?)")

    def _send_failure(self, cid: str, ordinal, attempt, exc: BaseException,
                      item) -> None:
        """Forward one classified failure as plain wire fields (the pool's
        ``_Failure`` envelope supplies the formatting/classification; no
        object crosses the socket - the client recovers the item from its
        own ledger)."""
        failure = _Failure(exc, ordinal=ordinal, item=item)
        self._deliver("ctrl", {"t": "failure", "client": cid,
                               "ordinal": ordinal, "attempt": attempt,
                               "formatted": failure.formatted,
                               "kind": failure.kind,
                               "exc_type": failure.exc_type},
                      None, key=(cid, ordinal))

    # -- heartbeat ------------------------------------------------------------

    def _counter_deltas(self) -> Dict[str, float]:
        """Per-heartbeat deltas of this process's decode/cache/worker
        counters (FLEET_COUNTER_PREFIXES on the dispatcher side)."""
        if not self.telemetry.enabled:
            return {}
        counters = self.telemetry.snapshot().get("counters", {})
        deltas = {}
        for name, value in counters.items():
            prev = self._hb_snapshot.get(name, 0.0)
            if value > prev:
                deltas[name] = value - prev
            self._hb_snapshot[name] = value
        return deltas

    def _hb_hists(self) -> Dict[str, Dict]:
        """Cumulative histogram snapshots to ship with the heartbeat:
        stage latencies plus our same-process trace hops.  Cumulative (not
        deltas) - the dispatcher keeps the latest per worker and merges
        fleet-wide via the fixed shared bucket bounds."""
        if not self.telemetry.enabled:
            return {}
        hists = self.telemetry.snapshot().get("histograms", {})
        return {n: s for n, s in hists.items()
                if n.startswith("service.hop.")
                or (n.startswith("stage.") and n.endswith(".latency_s"))}

    def _heartbeat_loop(self) -> None:
        # wakes every 0.25s so a drain completes promptly, but heartbeats
        # still go out only every _hb_interval
        next_hb = 0.0
        while not self._stop_event.wait(0.25):
            now = time.monotonic()
            if self._retiring.is_set():
                if not self._retire_sent and self._connected.is_set():
                    self._retire_sent = True
                    self._send({"t": "retiring"})
                if self._check_drained(now):
                    return
            if now < next_hb:
                continue
            next_hb = now + self._hb_interval
            if not self._connected.is_set():
                continue
            with self._busy_lock:
                busy = self._busy + self._work.qsize()
            hb = {"t": "heartbeat", "busy": busy,
                  "counters": self._counter_deltas()}
            hists = self._hb_hists()
            if hists:
                hb["hists"] = hists
            evs = []
            while self._pending_events:
                try:
                    evs.append(self._pending_events.popleft())
                except IndexError:
                    break
            if evs:
                hb["events"] = evs
            self._send(hb)

    def _check_drained(self, now: float) -> bool:
        """Drain-completion check (heartbeat thread): everything this
        worker held has reached the dispatcher, AND the dispatcher has
        confirmed - via the ``drained?``/``drain_ok`` probe - that it has
        nothing recorded in flight toward us.  Because the dispatcher
        records an assignment *before* sending its work frame and stops
        assigning once it acks ``retiring``, a ``drain_ok`` structurally
        rules out a work frame racing our goodbye - no timing window to
        tune.  On completion: ``bye``, stop, done."""
        if not self._retire_acked.is_set():
            return False
        with self._held_lock:
            empty = not self._held and not self._outbox
        if not empty:
            # a straggler work frame landed since the last probe; any
            # earlier confirmation is stale
            self._drain_confirmed.clear()
            return False
        if self._drain_confirmed.is_set():
            logger.info("Worker %s drained; retiring gracefully",
                        self.worker_name or "?")
            self._send({"t": "bye"})
            self.retired_gracefully = True
            self.stop()
            return True
        self._send({"t": "drained?"})
        return False


def run_worker(address, capacity: int = 2, name: Optional[str] = None,
               shm_size_bytes: int = 0,
               reconnect_attempts: int = 0,
               reconnect_backoff_s: float = 1.0,
               auth_token: Optional[str] = None,
               install_signal_handlers: bool = False) -> int:
    """Blocking worker entry (the CLI's ``worker`` subcommand).

    ``reconnect_attempts`` > 0 makes the worker survive dispatcher
    restarts WITHOUT dropping its in-flight work: registration retries
    that many times with a fixed backoff, and every successful rejoin
    resets the budget (elastic fleets keep workers running while the
    control plane reschedules - see the module docstring for what a
    rejoin reports).

    ``install_signal_handlers``: SIGTERM triggers **graceful retirement**
    (drain in-flight items, flush, goodbye - the autoscale supervisor's
    scale-down path); a second SIGTERM stops hard.  Main-thread only (the
    CLI sets it)."""
    worker = ServiceWorker(address, capacity=capacity, name=name,
                           shm_size_bytes=shm_size_bytes,
                           auth_token=auth_token,
                           reconnect_attempts=reconnect_attempts,
                           reconnect_backoff_s=reconnect_backoff_s)
    if install_signal_handlers:
        import signal as _signal

        def _on_term(_signum, _frame):
            if worker._retiring.is_set():  # noqa: SLF001 - own module
                worker.stop()  # second SIGTERM: stop hard
            else:
                worker.begin_retire()

        try:
            _signal.signal(_signal.SIGTERM, _on_term)
        except ValueError:
            logger.warning("not the main thread; SIGTERM graceful-drain"
                           " handler not installed")
    return worker.run()
