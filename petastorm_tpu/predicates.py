"""Row predicates: vectorized row filtering pushed down to reader workers.

Reference parity: petastorm/predicates.py - PredicateBase.get_fields/do_include
(predicates.py:26-36), combinators in_set/in_intersection/in_lambda/in_negate/
in_reduce (predicates.py:44-141), and in_pseudorandom_split's deterministic
md5-hash bucketing (predicates.py:144-182).

Difference: the primary contract is **columnar** - ``do_include_vectorized`` maps a
dict of numpy column arrays to a boolean mask, so workers filter whole rowgroups
without per-row python (the reference's row path calls do_include per row,
py_dict_reader_worker.py:188-252; its batch path got vectorization bolted on via
pandas, arrow_reader_worker.py:224-283).  ``do_include`` (per-row) remains as the
compatibility/escape hatch and is the default implementation target for in_lambda.
"""

from __future__ import annotations

import hashlib
from abc import ABC, abstractmethod
from typing import Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from petastorm_tpu.errors import PetastormTpuError


class PredicateBase(ABC):
    @abstractmethod
    def get_fields(self) -> List[str]:
        """Field names this predicate reads (the reader decodes these FIRST
        and masks rows before decoding the rest - the split-read)."""

    def do_include(self, row: Dict) -> bool:
        """Per-row check; default delegates to the vectorized form."""
        cols = {k: np.asarray([v], dtype=object) for k, v in row.items()}
        return bool(self.do_include_vectorized(cols)[0])

    def do_include_vectorized(self, columns: Dict[str, np.ndarray]) -> np.ndarray:
        """Boolean mask over the batch; default loops ``do_include``."""
        names = self.get_fields()
        n = len(next(iter(columns.values())))
        return np.fromiter(
            (self.do_include({k: columns[k][i] for k in names}) for i in range(n)),
            dtype=bool, count=n)


class in_set(PredicateBase):
    """Keep rows whose field value is in a set (predicates.py:44-67)."""

    def __init__(self, values: Iterable, field_name: str):
        self._values = set(values)
        self._field = field_name

    def get_fields(self) -> List[str]:
        return [self._field]

    def do_include_vectorized(self, columns: Dict[str, np.ndarray]) -> np.ndarray:
        col = columns[self._field]
        return np.isin(col, list(self._values))


class in_intersection(PredicateBase):
    """Keep rows where ALL listed fields' values fall in the set (predicates.py:70-92)."""

    def __init__(self, values: Iterable, field_names: Sequence[str]):
        self._values = set(values)
        self._fields = list(field_names)

    def get_fields(self) -> List[str]:
        return list(self._fields)

    def do_include_vectorized(self, columns: Dict[str, np.ndarray]) -> np.ndarray:
        mask = None
        values = list(self._values)
        for f in self._fields:
            m = np.isin(columns[f], values)
            mask = m if mask is None else (mask & m)
        return mask


class in_lambda(PredicateBase):
    """Arbitrary user predicate over named fields, with optional shared state
    (predicates.py:95-118).  ``vectorized=True`` marks the function as taking
    column arrays and returning a mask directly."""

    def __init__(self, fields: Sequence[str], func: Callable, state=None,
                 vectorized: bool = False):
        self._fields = list(fields)
        self._func = func
        self._state = state
        self._vectorized = vectorized

    def get_fields(self) -> List[str]:
        return list(self._fields)

    def do_include(self, row: Dict) -> bool:
        if self._vectorized:
            return super().do_include(row)
        args = {k: row[k] for k in self._fields}
        return bool(self._func(args, self._state) if self._state is not None
                    else self._func(args))

    def do_include_vectorized(self, columns: Dict[str, np.ndarray]) -> np.ndarray:
        cols = {k: columns[k] for k in self._fields}
        if self._vectorized:
            out = (self._func(cols, self._state) if self._state is not None
                   else self._func(cols))
            return np.asarray(out, dtype=bool)
        n = len(next(iter(cols.values())))
        return np.fromiter(
            (self.do_include({k: cols[k][i] for k in self._fields}) for i in range(n)),
            dtype=bool, count=n)


class in_negate(PredicateBase):
    """Logical NOT of another predicate (predicates.py:121-130)."""

    def __init__(self, predicate: PredicateBase):
        self._p = predicate

    def get_fields(self) -> List[str]:
        return self._p.get_fields()

    def do_include_vectorized(self, columns: Dict[str, np.ndarray]) -> np.ndarray:
        return ~self._p.do_include_vectorized(columns)


class in_reduce(PredicateBase):
    """Reduce multiple predicates with np.all / np.any / custom (predicates.py:133-141)."""

    def __init__(self, predicates: Sequence[PredicateBase], reduce_func=np.all):
        self._preds = list(predicates)
        self._reduce = reduce_func

    def get_fields(self) -> List[str]:
        out: List[str] = []
        for p in self._preds:
            for f in p.get_fields():
                if f not in out:
                    out.append(f)
        return out

    def do_include_vectorized(self, columns: Dict[str, np.ndarray]) -> np.ndarray:
        masks = np.stack([p.do_include_vectorized(columns) for p in self._preds])
        return np.asarray(self._reduce(masks, axis=0), dtype=bool)


class in_pseudorandom_split(PredicateBase):
    """Deterministic fractional split by md5-hash bucketing of a key field.

    Reference: predicates.py:144-182 - hash(value) maps each row to [0,1);
    ``fractions`` partition the unit interval; rows land in the sub-range of
    ``subset_index``.  Deterministic across runs/hosts, so train/val/test splits
    are stable properties of the data, not of the run.
    """

    def __init__(self, fractions: Sequence[float], subset_index: int,
                 field_name: str, compat: Optional[str] = None):
        """``compat='reference'`` reproduces the original petastorm's bucket
        membership bit-exactly (md5-of-str mod sys.maxsize against
        fraction*(sys.maxsize-1) bounds, reference predicates.py:39-41,
        171-182) so an existing train/val/test split migrates with identical
        row assignment.  Default (None) uses this library's native bucketing
        (md5-first-8-hex / 2^32) - same statistics, different membership.
        """
        if not 0 <= subset_index < len(fractions):
            raise PetastormTpuError(f"subset_index {subset_index} out of range")
        if sum(fractions) > 1.0 + 1e-9:
            raise PetastormTpuError(f"fractions sum to {sum(fractions)} > 1")
        if compat not in (None, "reference"):
            raise PetastormTpuError(
                f"compat must be None or 'reference', got {compat!r}")
        self._field = field_name
        self._compat = compat == "reference"
        lo = float(sum(fractions[:subset_index]))
        hi = lo + float(fractions[subset_index])
        self._lo, self._hi = lo, hi

    def get_fields(self) -> List[str]:
        return [self._field]

    @staticmethod
    def _hash01(value) -> float:
        digest = hashlib.md5(str(value).encode()).hexdigest()[:8]
        return int(digest, 16) / float(0xFFFFFFFF)

    @staticmethod
    def _reference_bucket(value) -> int:
        """Reference ``_string_to_bucket`` (predicates.py:39-41)."""
        import sys as _sys

        return int(hashlib.md5(str(value).encode("utf-8")).hexdigest(),
                   16) % _sys.maxsize

    def do_include_vectorized(self, columns: Dict[str, np.ndarray]) -> np.ndarray:
        col = columns[self._field]
        if self._compat:
            import sys as _sys

            # exact reference arithmetic: float bounds, full-int bucket
            # (reference predicates.py:171-182)
            lo = self._lo * (_sys.maxsize - 1)
            hi = self._hi * (_sys.maxsize - 1)
            return np.fromiter((lo <= self._reference_bucket(v) < hi
                                for v in col), dtype=bool, count=len(col))
        h = np.fromiter((self._hash01(v) for v in col), dtype=np.float64, count=len(col))
        return (h >= self._lo) & (h < self._hi)
