"""Executor pools: the host-side concurrency plane feeding the device pipeline.

Reference parity: petastorm/workers_pool/ (~1,100 LoC) - WorkerBase protocol
(worker_base.py:18-35), ThreadPool with bounded results queue + stop-aware puts +
exception forwarding (thread_pool.py:78-221), zmq-based ProcessPool with spawned
workers, startup barrier, orphan watchdog and slow-joiner workarounds
(process_pool.py:114-428), DummyPool doing work inside get_results
(dummy_pool.py:20-91), and ConcurrentVentilator with bounded in-flight and per-epoch
reshuffle (ventilator.py:55-166).

Design differences (TPU-first):

* **Threads are the default.** pyarrow parquet IO and decode release the GIL, so the
  reference's zmq process plumbing is usually pure overhead on a TPU host VM;
  ``ProcessExecutor`` (multiprocessing.spawn, no zmq) remains for GIL-bound python
  transforms.  Spawn (not fork) for the same reason the reference documents
  (process_pool.py:15-17: forked JVM/arrow handles break).
* **Completion-order results with explicit epoch accounting.** The consumer knows
  exactly how many items each epoch ventilates (ReadPlan is deterministic), so
  epoch-end is a counted event, not a sentinel race.
* Worker exceptions carry the formatted remote traceback and re-raise at the
  consumer (reference thread_pool.py:68-73,169-172).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import traceback
from abc import ABC, abstractmethod
from typing import Any, Callable, Iterable, Optional

from petastorm_tpu.errors import PetastormTpuError, ReaderClosedError
from petastorm_tpu.telemetry import resolve as _resolve_telemetry

logger = logging.getLogger(__name__)

_POLL_S = 0.05
DEFAULT_RESULTS_QUEUE_SIZE = 50  # reference: reader.py:61


def _env_seconds(name: str, default: float) -> float:
    """Float env var with a logged fallback (shared with reader.py)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("Ignoring non-numeric %s=%r (using %.0f)",
                       name, raw, default)
        return default


class WorkerError(PetastormTpuError):
    """A worker failed; message includes the remote traceback."""


class VentilationCancelled(Exception):
    """An ``executor.put`` blocked on a full queue was withdrawn by its
    cancel_event (Ventilator.pause_and_join with a saturated pipeline); the
    item was NOT enqueued.  Internal control flow, never user-visible."""


class _Failure:
    __slots__ = ("formatted",)

    def __init__(self, exc: BaseException):
        self.formatted = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))


#: worker factory: () -> process_fn(item) -> result.  Must be picklable for
#: ProcessExecutor (a module-level class instance holding plain-data config).
WorkerFactory = Callable[[], Callable[[Any], Any]]


class VentilatedItem:
    """A work item tagged with its absolute ventilation ordinal.

    Pools may complete items out of ventilation order; the ordinal lets the
    consumer reconstruct the exact contiguous consumed prefix (the only
    resume cursor that can guarantee no item is ever lost).  Picklable for
    the process pool.
    """

    __slots__ = ("ordinal", "item")

    def __init__(self, ordinal: int, item: Any):
        self.ordinal = ordinal
        self.item = item

    def __getstate__(self):
        return (self.ordinal, self.item)

    def __setstate__(self, state):
        self.ordinal, self.item = state


class ExecutorBase(ABC):
    """start -> (put*/get*) -> stop -> join lifecycle, mirroring the reference pool
    protocol (start/ventilate/get_results/stop/join)."""

    def __init__(self, telemetry=None):
        self._stopped = False
        self._ventilated = 0
        self._consumed = 0
        #: petastorm_tpu.telemetry recorder (no-op unless enabled); executors
        #: record queue-full wait time - the signal that tells the pipeline
        #: report whether backpressure points upstream or downstream
        self._telemetry = _resolve_telemetry(telemetry)
        self._m_input_full = self._telemetry.counter("queue.input_full_wait_s")
        self._m_results_full = self._telemetry.counter(
            "queue.results_full_wait_s")

    @abstractmethod
    def start(self, worker_factory: WorkerFactory) -> None:
        ...

    @abstractmethod
    def put(self, item: Any, cancel_event=None) -> None:
        """Enqueue a work item; blocks on a full input queue.  When
        ``cancel_event`` is set while blocked, raises VentilationCancelled
        WITHOUT having enqueued the item (quiesce with a full pipeline)."""
        ...

    @abstractmethod
    def get(self, timeout: Optional[float] = None) -> Any:
        ...

    @abstractmethod
    def stop(self) -> None:
        ...

    @abstractmethod
    def join(self) -> None:
        ...

    @property
    def diagnostics(self) -> dict:
        return {"ventilated": self._ventilated, "consumed": self._consumed,
                "stopped": self._stopped}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        self.join()


class SerialExecutor(ExecutorBase):
    """Synchronous executor: work happens inside ``get`` (reference DummyPool,
    dummy_pool.py:20-91) - for tests, profiling, and debugging.

    The input queue is bounded so a Ventilator with ``num_epochs=None`` cannot
    enqueue unboundedly ahead of the consumer.

    Stall detection: work happens synchronously inside ``get``, so the
    reader-side stall loop (which only runs between ``get`` calls) can never
    observe a work item wedged inside user code.  ONE long-lived daemon
    watchdog thread (started lazily on the first ``get``) therefore polls a
    heartbeat slot: if ``fn(item)`` runs longer than
    ``PETASTORM_TPU_STALL_WARN_S`` a WARNING names the item (once per item).
    ``PETASTORM_TPU_STALL_ABORT_S`` remains inoperative here - synchronous
    user code cannot be safely interrupted from another thread; use the
    thread or process pool when abort matters (docs/operations.md).
    """

    def __init__(self, in_queue_size: int = 32, telemetry=None):
        super().__init__(telemetry=telemetry)
        self._items: "queue.Queue[Any]" = queue.Queue(maxsize=in_queue_size)
        self._fn: Optional[Callable] = None
        self._stall_warn_s = _env_seconds("PETASTORM_TPU_STALL_WARN_S", 120.0)
        # heartbeat slot for the watchdog (single writer: the get() caller;
        # same write-order contract as the thread pool's worker_state)
        self._watch_thread: Optional[threading.Thread] = None
        self._watch_item: Any = None     # None = no item in flight
        self._watch_since = 0.0
        self._watch_gen = 0              # one warning per item, not per poll

    def start(self, worker_factory: WorkerFactory) -> None:
        self._fn = worker_factory()

    def put(self, item: Any, cancel_event=None) -> None:
        t0 = time.perf_counter() if self._telemetry.enabled else None
        while not self._stopped:
            try:
                self._items.put(item, timeout=_POLL_S)
                self._ventilated += 1
                if t0 is not None:
                    self._m_input_full.add(time.perf_counter() - t0)
                return
            except queue.Full:
                if cancel_event is not None and cancel_event.is_set():
                    raise VentilationCancelled()
                continue
        raise ReaderClosedError("Executor is stopped")

    def _watch_loop(self) -> None:
        warned_gen = -1
        poll_s = min(max(self._stall_warn_s / 4.0, 0.05), 5.0)
        while not self._stopped:
            time.sleep(poll_s)
            item = self._watch_item
            if item is None:
                continue
            gen, elapsed = self._watch_gen, time.monotonic() - self._watch_since
            if elapsed > self._stall_warn_s and gen != warned_gen:
                warned_gen = gen
                logger.warning(
                    "Serial executor work item %s has run for %.0fs inside its"
                    " worker function (PETASTORM_TPU_STALL_WARN_S=%.0f);"
                    " pipeline state: %s", getattr(item, "ordinal", "?"),
                    elapsed, self._stall_warn_s, self.diagnostics)

    def get(self, timeout: Optional[float] = None) -> Any:
        if self._fn is None:
            raise PetastormTpuError("Executor not started")
        try:
            item = self._items.get(timeout=timeout or _POLL_S)
        except queue.Empty:
            raise queue.Empty("No ventilated items to process")
        self._consumed += 1
        if self._stall_warn_s > 0:
            if self._watch_thread is None:
                self._watch_thread = threading.Thread(
                    target=self._watch_loop, daemon=True,
                    name="petastorm-tpu-serial-watchdog")
                self._watch_thread.start()
            # timestamp and generation BEFORE the item (the watchdog guards
            # on item, so a non-None read sees current since/gen)
            self._watch_since = time.monotonic()
            self._watch_gen += 1
            self._watch_item = item
        try:
            return self._fn(item)
        finally:
            self._watch_item = None

    def stop(self) -> None:
        self._stopped = True

    def join(self) -> None:
        pass

    @property
    def diagnostics(self) -> dict:
        return {**super().diagnostics,
                "in_queue_size": self._items.qsize()}


class ThreadedExecutor(ExecutorBase):
    """Bounded-queue thread pool (reference ThreadPool, thread_pool.py:78-221).

    pyarrow IO/decompress and cv2 decode release the GIL, so threads scale on
    multi-core TPU host VMs with zero serialization cost.
    """

    def __init__(self, workers_count: int = 3,
                 results_queue_size: int = DEFAULT_RESULTS_QUEUE_SIZE,
                 in_queue_size: Optional[int] = None,
                 profiling_enabled: bool = False,
                 telemetry=None):
        super().__init__(telemetry=telemetry)
        self._workers_count = workers_count
        # Queue choice is correctness-driven (hang post-mortem, RESULTS.md):
        # CPython's SimpleQueue.get(timeout) WEDGES under multiple
        # concurrent consumers — when a waiter wins the internal lock but a
        # sibling steals the item before it reacquires the GIL, the
        # remaining timeout is recomputed without clamping and a negative
        # value means an INFINITE lock wait (confirmed by disassembly and
        # reproduced standalone: tools/simplequeue_wedge_repro.py; it froze
        # a full suite run via this very pool).  _in_queue has N worker
        # consumers, so it uses the pure-python queue.Queue, whose
        # Condition-based timeout is correct by construction.  The output
        # side keeps the faster C SimpleQueue: it has exactly ONE consumer
        # (the reader thread), which closes the steal window.  Bounds live
        # in the semaphores either way (reference bounds ventilation at
        # workers_count + 2, reader.py:45-47,412, and treats a non-positive
        # results size as unbounded).
        self._in_queue: "queue.Queue[Any]" = queue.Queue()
        self._in_slots = threading.BoundedSemaphore(in_queue_size or workers_count + 2)
        self._out_queue: "queue.SimpleQueue[Any]" = queue.SimpleQueue()
        self._out_slots = threading.BoundedSemaphore(
            results_queue_size if results_queue_size > 0 else 2 ** 30)
        self._stop_event = threading.Event()
        self._threads = []
        # opt-in worker profiling (reference per-thread cProfile,
        # thread_pool.py:41-49,190-198).  Python 3.12 allows only ONE active
        # profiler process-wide (sys.monitoring), so profiling is SAMPLED: a
        # single designated worker thread is profiled; workers are homogeneous,
        # so its profile is representative of all of them.
        self._profiling_enabled = profiling_enabled
        self._profiles = []
        self._profiles_lock = threading.Lock()
        # per-worker heartbeat: [ordinal-or-None, monotonic-since].  Written
        # only by the owning worker (single-writer per slot, no lock needed);
        # read by diagnostics to attribute a pipeline stall to the exact
        # worker and work item (RESULTS.md hang watch item).
        self._worker_state: list = []

    def start(self, worker_factory: WorkerFactory) -> None:
        if self._threads:
            raise PetastormTpuError("Executor already started")
        for i in range(self._workers_count):
            fn = worker_factory()
            self._worker_state.append([None, time.monotonic()])
            t = threading.Thread(target=self._worker_loop,
                                 args=(fn, i, self._profiling_enabled and i == 0),
                                 name=f"petastorm-tpu-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def _worker_loop(self, fn: Callable, index: int = 0,
                     profile_this_worker: bool = False) -> None:
        state = self._worker_state[index]
        profile = None
        if profile_this_worker:
            import cProfile

            profile = cProfile.Profile()
        while not self._stop_event.is_set():
            try:
                item = self._in_queue.get(timeout=_POLL_S)
            except queue.Empty:
                continue
            self._in_slots.release()
            # timestamp BEFORE ordinal: a concurrent diagnostics read between
            # the two writes must never pair the new item with the old
            # idle-since time (it would report the whole idle gap as "stuck")
            state[1] = time.monotonic()
            state[0] = getattr(item, "ordinal", "?")
            try:
                if profile is not None:
                    try:
                        result = profile.runcall(fn, item)
                    except ValueError as exc:
                        # py3.12 allows one active profiler process-wide; if
                        # someone else holds it (second profiling pool, or the
                        # app itself under cProfile), degrade to unprofiled
                        # instead of failing the read
                        if "profiling tool" not in str(exc):
                            raise
                        logger.warning("Worker profiling disabled: %s", exc)
                        profile = None
                        result = fn(item)
                else:
                    result = fn(item)
            except BaseException as exc:  # noqa: BLE001 - forwarded to consumer
                result = _Failure(exc)
            self._put_result_stop_aware(result)
            state[0] = None
            state[1] = time.monotonic()
        if profile is not None:
            with self._profiles_lock:
                self._profiles.append(profile)

    def _put_result_stop_aware(self, value: Any) -> None:
        # reference _stop_aware_put (thread_pool.py:200-214): bound via the
        # slot semaphore, never block indefinitely across a stop
        t0 = time.perf_counter() if self._telemetry.enabled else None
        while not self._stop_event.is_set():
            if self._out_slots.acquire(timeout=_POLL_S):
                self._out_queue.put(value)
                if t0 is not None:
                    # time this worker spent blocked on a full results queue:
                    # sustained values mean the CONSUMER is the bottleneck
                    self._m_results_full.add(time.perf_counter() - t0)
                return

    def put(self, item: Any, cancel_event=None) -> None:
        if self._stopped:
            raise ReaderClosedError("Executor is stopped")
        t0 = time.perf_counter() if self._telemetry.enabled else None
        while not self._stop_event.is_set():
            if self._in_slots.acquire(timeout=_POLL_S):
                self._in_queue.put(item)
                self._ventilated += 1
                if t0 is not None:
                    # time the ventilator spent blocked on a full input queue:
                    # the worker plane is saturated (healthy backpressure)
                    self._m_input_full.add(time.perf_counter() - t0)
                return
            if cancel_event is not None and cancel_event.is_set():
                # caller withdrew the put while the queue was full (quiesce
                # with a saturated pipeline); the item was NOT enqueued
                raise VentilationCancelled()
        raise ReaderClosedError("Executor stopped while putting")

    def get(self, timeout: Optional[float] = None) -> Any:
        result = self._out_queue.get(timeout=timeout)
        # releases are bounded by successful gets, which are bounded by
        # acquired puts: a ValueError here would be a real accounting bug
        self._out_slots.release()
        if isinstance(result, _Failure):
            self.stop()
            raise WorkerError(f"Worker failed:\n{result.formatted}")
        self._consumed += 1
        if self._telemetry.enabled:
            self._telemetry.gauge("pool.results_queue_depth").set(
                self._out_queue.qsize())
        return result

    def stop(self) -> None:
        self._stopped = True
        self._stop_event.set()

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for worker threads.  ``timeout`` (total, across all workers)
        bounds the wait when a worker may be wedged inside user code — e.g.
        after a stall abort: the threads are daemonic, so abandoning them
        cannot block process exit, and a warning names what was abandoned."""
        if not self._stopped:
            raise PetastormTpuError("call stop() before join()")
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._threads:
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        alive = [t.name for t in self._threads if t.is_alive()]
        if alive:
            logger.warning(
                "Abandoning %d wedged daemon worker thread(s) %s after %.0fs;"
                " pipeline state: %s", len(alive), alive, timeout or 0,
                self.diagnostics)
        if self._profiling_enabled and self._profiles:
            stats = self.profile_stats()
            if stats is not None:
                import io as _io

                out = _io.StringIO()
                stats.stream = out
                stats.sort_stats("cumulative").print_stats(20)
                logger.info("Sampled worker profile (top 20 by cumulative):\n%s",
                            out.getvalue())

    def profile_stats(self):
        """``pstats.Stats`` of the sampled worker thread, or None when
        profiling was off / the sampled worker ran no item yet."""
        import pstats

        with self._profiles_lock:
            profiles = [p for p in self._profiles if p.getstats()]
            if not profiles:
                return None
            stats = pstats.Stats(profiles[0])
            for p in profiles[1:]:
                stats.add(p)
            return stats

    @property
    def diagnostics(self) -> dict:
        now = time.monotonic()
        # snapshot each slot's ordinal ONCE: the worker may clear it between
        # a guard and a second read, which would emit a spurious None entry
        busy = []
        for i, s in enumerate(self._worker_state):
            ordinal = s[0]
            if ordinal is not None:
                # clamp: the worker may stamp a newer time between our `now`
                # snapshot and this read
                busy.append((i, ordinal, round(max(0.0, now - s[1]), 3)))
        return {**super().diagnostics,
                "in_queue_size": self._in_queue.qsize(),
                "results_queue_size": self._out_queue.qsize(),
                "workers_count": self._workers_count,
                # [(worker index, item ordinal, seconds on it)] for workers
                # currently inside fn(item) - a stalled pipeline names the
                # exact worker and work item instead of wedging silently
                "workers_busy": busy}


def _process_worker_main(worker_factory, in_queue, out_queue, stop_event,
                         index=0, heartbeats=None):
    """Worker-process entrypoint (module-level: must be picklable for spawn).

    ``heartbeats``: optional lock-free shared double array, 2 slots per
    worker: [ordinal (-1 = idle), wall-clock since] — same stall-attribution
    contract as ThreadedExecutor's ``workers_busy``, crossing the process
    boundary via shared memory.  Wall clock (time.time), not monotonic:
    monotonic clocks are not comparable across processes on all platforms.
    Reads of the PAIR can tear: each 8-byte slot is individually atomic and
    the write order (timestamp before ordinal) prevents the harmful pairing
    of a NEW item with an OLD idle-since time, but a diagnostics read landing
    between the two stores may still pair the new timestamp with the
    previous ordinal (or an idle marker) for one sample — diagnostics
    consumers must treat a single odd ``workers_busy`` entry as noise, not
    evidence.
    """
    try:
        fn = worker_factory()
    except BaseException as exc:  # noqa: BLE001
        out_queue.put(_Failure(exc))
        return
    if hasattr(fn, "stop_event"):  # shm encoder: abort full-arena waits on stop
        fn.stop_event = stop_event
    base = 2 * index
    while not stop_event.is_set():
        try:
            item = in_queue.get(timeout=_POLL_S)
        except queue.Empty:
            continue
        if item is _ProcessExecutor._STOP_SENTINEL_VALUE:
            break
        if heartbeats is not None:
            try:
                ordinal = float(item.ordinal)
            except (AttributeError, TypeError, ValueError):
                ordinal = -2.0  # busy, ordinal unknown
            # timestamp before ordinal (same reasoning as the thread pool:
            # a concurrent read must never pair a new item with an old time)
            heartbeats[base + 1] = time.time()
            heartbeats[base] = ordinal
        try:
            result = fn(item)
        except BaseException as exc:  # noqa: BLE001
            result = _Failure(exc)
        out_queue.put(result)
        if heartbeats is not None:
            heartbeats[base] = -1.0
            heartbeats[base + 1] = time.time()


class _ProcessExecutor(ExecutorBase):
    """Spawned multiprocessing pool for GIL-bound worker functions.

    Replaces the reference's zmq ProcessPool (process_pool.py:114-428): spawn
    semantics and exception forwarding are kept; the zmq data plane, startup
    barrier, and slow-joiner workarounds fall away because multiprocessing queues
    provide them.  Daemon processes make the parent-death watchdog
    (process_pool.py:324-331) unnecessary.
    """

    _STOP_SENTINEL_VALUE = "__petastorm_tpu_stop__"

    #: default shared-memory arena size for the native data plane
    DEFAULT_SHM_BYTES = 256 * 2**20

    def __init__(self, workers_count: int = 3,
                 results_queue_size: int = DEFAULT_RESULTS_QUEUE_SIZE,
                 in_queue_size: Optional[int] = None,
                 use_shm: Optional[bool] = None,
                 shm_size_bytes: int = DEFAULT_SHM_BYTES,
                 telemetry=None):
        # telemetry: the PARENT process records ventilation/queue waits;
        # worker-side stage metrics recorded in the spawned processes stay
        # there (PETASTORM_TPU_TELEMETRY is inherited, so each child records
        # independently) - thread pool gives one merged report
        super().__init__(telemetry=telemetry)
        import multiprocessing as mp

        self._ctx = mp.get_context("spawn")
        self._workers_count = workers_count
        self._in_queue = self._ctx.Queue(in_queue_size or workers_count + 2)
        self._out_queue = self._ctx.Queue(results_queue_size)
        self._stop_event = self._ctx.Event()
        self._procs = []
        self._arena = None
        self._heartbeats = None
        self._shm_size_bytes = shm_size_bytes
        if use_shm is None:  # auto: use the native transport when it builds
            from petastorm_tpu.native import is_available

            use_shm = is_available()
        self._use_shm = use_shm

    def start(self, worker_factory: WorkerFactory) -> None:
        if self._procs:
            raise PetastormTpuError("Executor already started")
        if self._use_shm:
            from petastorm_tpu.native import SharedArena
            from petastorm_tpu.native.transport import ShmResultEncoder

            self._arena = SharedArena.create(self._shm_size_bytes)
            worker_factory = ShmResultEncoder(worker_factory, self._arena.name)
        # lock-free heartbeat slots (single-writer per pair; see
        # _process_worker_main) - powers workers_busy across processes
        self._heartbeats = self._ctx.RawArray("d", 2 * self._workers_count)
        for i in range(self._workers_count):
            self._heartbeats[2 * i] = -1.0
            p = self._ctx.Process(
                target=_process_worker_main,
                args=(worker_factory, self._in_queue, self._out_queue,
                      self._stop_event, i, self._heartbeats),
                name=f"petastorm-tpu-worker-{i}", daemon=True)
            p.start()
            self._procs.append(p)

    def put(self, item: Any, cancel_event=None) -> None:
        if self._stopped:
            raise ReaderClosedError("Executor is stopped")
        t0 = time.perf_counter() if self._telemetry.enabled else None
        while True:
            try:
                self._in_queue.put(item, timeout=_POLL_S)
                self._ventilated += 1
                if t0 is not None:
                    self._m_input_full.add(time.perf_counter() - t0)
                return
            except queue.Full:
                if self._stopped:
                    raise ReaderClosedError("Executor stopped while putting")
                if cancel_event is not None and cancel_event.is_set():
                    raise VentilationCancelled()

    def get(self, timeout: Optional[float] = None) -> Any:
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                result = self._out_queue.get(timeout=_POLL_S)
                break
            except queue.Empty:
                if deadline is not None and time.monotonic() > deadline:
                    raise
                if self._procs and not any(p.is_alive() for p in self._procs):
                    raise WorkerError("All worker processes died (possible crash/OOM);"
                                      " no result will arrive")
        if isinstance(result, _Failure):
            self.stop()
            raise WorkerError(f"Worker failed:\n{result.formatted}")
        if self._arena is not None:
            from petastorm_tpu.native.transport import decode_batch

            result = decode_batch(self._arena, result)
        self._consumed += 1
        return result

    def stop(self) -> None:
        self._stopped = True
        self._stop_event.set()

    def join(self) -> None:
        if not self._stopped:
            raise PetastormTpuError("call stop() before join()")
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        for q in (self._in_queue, self._out_queue):
            q.cancel_join_thread()
        if self._arena is not None:
            # consumer-side batches may still hold zero-copy views; close()
            # defers the unmap until they are collected
            self._arena.close()

    @property
    def diagnostics(self) -> dict:
        diag = {**super().diagnostics, "workers_count": self._workers_count,
                "workers_alive": sum(p.is_alive() for p in self._procs),
                "shm_transport": self._arena is not None}
        try:  # mp.Queue.qsize raises NotImplementedError on some platforms
            diag["in_queue_size"] = self._in_queue.qsize()
            diag["results_queue_size"] = self._out_queue.qsize()
        except NotImplementedError:
            pass
        if self._heartbeats is not None:
            now = time.time()
            busy = []
            for i in range(self._workers_count):
                ordinal = self._heartbeats[2 * i]
                if ordinal != -1.0:  # -1 = idle; -2 = busy, ordinal unknown
                    # clamp: the worker may stamp a newer wall-clock time
                    # between our `now` snapshot and this read (and
                    # time.time() can step backwards under NTP)
                    busy.append((i, int(ordinal) if ordinal >= 0 else "?",
                                 round(max(0.0, now
                                           - self._heartbeats[2 * i + 1]), 3)))
            diag["workers_busy"] = busy
        if self._arena is not None:
            diag["shm_free_bytes"] = self._arena.free_bytes()
        return diag


def make_executor(kind: str = "thread", workers_count: int = 3,
                  results_queue_size: int = DEFAULT_RESULTS_QUEUE_SIZE,
                  telemetry=None) -> ExecutorBase:
    """'thread' | 'process' | 'serial' (reference: reader_pool_type, reader.py:139-150)."""
    if kind == "thread":
        return ThreadedExecutor(workers_count, results_queue_size,
                                telemetry=telemetry)
    if kind == "process":
        return _ProcessExecutor(workers_count, results_queue_size,
                                telemetry=telemetry)
    if kind in ("serial", "dummy"):
        return SerialExecutor(telemetry=telemetry)
    raise PetastormTpuError(f"Unknown executor kind {kind!r}")


class Ventilator:
    """Background thread feeding epoch work-items into an executor.

    Reference: ConcurrentVentilator (ventilator.py:55-166).  Backpressure comes
    from the executor's bounded input queue; per-epoch ordering comes from the
    deterministic ReadPlan, so this thread holds no shuffle state.
    """

    def __init__(self, executor: ExecutorBase, plan, num_epochs: Optional[int] = 1,
                 start_item: int = 0, telemetry=None):
        if num_epochs is not None and num_epochs < 1:
            raise PetastormTpuError("num_epochs must be >= 1 or None (infinite)")
        if start_item < 0:
            raise PetastormTpuError("start_item must be >= 0")
        self._executor = executor
        self._plan = plan
        self._num_epochs = num_epochs
        self._start_item = start_item
        self._telemetry = _resolve_telemetry(telemetry)
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.items_per_epoch = len(plan.epoch_items(0))
        #: absolute ordinal AFTER the last item actually handed to the
        #: executor (== items guaranteed to flow through to the consumer);
        #: exact once the thread is joined (see pause_and_join)
        self.ventilated = start_item

    @property
    def total_items(self) -> Optional[int]:
        """Items this ventilator will emit (excludes skipped resume prefix)."""
        if self._num_epochs is None:
            return None
        # plans know their own totals (ElasticResumePlan's leftover epoch is
        # shorter than its subsequent epochs)
        return max(self._plan.total_items(self._num_epochs) - self._start_item, 0)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="petastorm-tpu-ventilator",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        # resume: skip whole epochs cheaply, then a within-epoch offset
        if self.items_per_epoch > 0:
            epoch = self._start_item // self.items_per_epoch
            offset = self._start_item % self.items_per_epoch
        else:
            epoch, offset = 0, 0
        ordinal = self._start_item  # absolute position in the full item stream
        while not self._stop_event.is_set():
            if self._num_epochs is not None and epoch >= self._num_epochs:
                return
            tele = self._telemetry
            # same counter object the executor's put updates (same registry
            # name), and put runs in THIS thread - so the delta across one
            # put is exactly that put's queue-full wait
            m_blocked = tele.counter("queue.input_full_wait_s")
            for item in self._plan.epoch_items(epoch)[offset:]:
                if self._stop_event.is_set():
                    return
                try:
                    if tele.enabled:
                        # ventilate busy time must EXCLUDE time blocked on a
                        # full input queue (tracked by the executor as
                        # queue.input_full_wait_s), or a consumer-bound
                        # pipeline would crown 'ventilate' the dominant stage
                        # for doing nothing but waiting
                        t0 = time.perf_counter_ns()
                        blocked0 = m_blocked.value
                        self._executor.put(VentilatedItem(ordinal, item),
                                           cancel_event=self._stop_event)
                        dur_ns = time.perf_counter_ns() - t0
                        blocked_ns = int((m_blocked.value - blocked0) * 1e9)
                        tele.record_stage("ventilate", t0,
                                          max(dur_ns - blocked_ns, 0),
                                          {"ordinal": ordinal})
                    else:
                        self._executor.put(VentilatedItem(ordinal, item),
                                           cancel_event=self._stop_event)
                except (ReaderClosedError, VentilationCancelled):
                    return
                ordinal += 1
                self.ventilated = ordinal
            offset = 0
            epoch += 1

    def stop(self) -> None:
        self._stop_event.set()

    def join(self) -> None:
        if self._thread is not None:
            self._thread.join()

    def pause_and_join(self) -> int:
        """Stop issuing new work items and wait for the thread; returns the
        exact count of items ventilated (items already handed to the executor
        still flow through to the consumer - nothing is retracted).  The
        quiesce half of drain-to-cursor checkpointing (Reader.quiesce)."""
        self._stop_event.set()
        self.join()
        return self.ventilated
