"""Probability-weighted mixing of multiple readers.

Reference parity: petastorm/weighted_sampling_reader.py (106 LoC) -
WeightedSamplingReader draws the next element from reader i with probability
probabilities[i], with schema/ngram/batched compatibility checks
(weighted_sampling_reader.py:26-92).

Difference: the draw is seeded (reproducible mixing) and ``iter_batches`` mixing
is supported for the columnar path.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.seeding import seed_stream


class WeightedSamplingReader:
    """Mix several compatible readers into one stream, drawing each next
    row/batch from reader ``i`` with probability ``probabilities[i]``
    (normalized; seeded for reproducibility).  Schemas must agree on the
    delivered fields; exhausted readers drop out and the remaining weights
    renormalize (reference weighted_sampling_reader semantics)."""

    def __init__(self, readers: Sequence, probabilities: Sequence[float],
                 seed: Optional[int] = None):
        if len(readers) != len(probabilities) or not readers:
            raise PetastormTpuError("readers and probabilities must be same non-zero length")
        p = np.asarray(probabilities, dtype=np.float64)
        if (p < 0).any() or p.sum() <= 0:
            raise PetastormTpuError(f"Invalid probabilities {probabilities}")
        self._p = p / p.sum()
        self._readers = list(readers)
        # centralized derivation (petastorm_tpu.seeding): a seeded mix draws
        # a PYTHONHASHSEED-stable stream independent of every other seeded
        # stage; None keeps the unseeded each-run-differs behavior
        self._rng = (seed_stream(seed, 0, "weighted_sampling")
                     if seed is not None else np.random.default_rng())
        # readers not yet exhausted by __next__; persists across calls so dead
        # readers are not re-drawn/re-polled on every remaining row
        self._alive: List[int] = list(range(len(self._readers)))

        first = readers[0]
        self.batched_output = first.batched_output
        self.ngram = getattr(first, "ngram", None)
        self.schema = first.schema
        self.output_schema = getattr(first, "output_schema", first.schema)
        #: decode_placement='device' fields propagate so JaxDataLoader finds
        #: and finishes the coefficient-plane columns; every sub-reader must
        #: agree (mixing a planes stream with a pixels stream cannot batch)
        self.device_decode_fields = list(
            getattr(first, "device_decode_fields", ()) or ())
        self.device_decode_mixed = frozenset(
            getattr(first, "device_decode_mixed", ()) or ())
        for r in readers[1:]:
            if r.batched_output != self.batched_output:
                raise PetastormTpuError("All readers must share batched_output mode")
            if getattr(r, "ngram", None) != self.ngram:
                raise PetastormTpuError(
                    "All readers must share an identical NGram spec (same"
                    " offsets, fields, delta_threshold, timestamp settings)")
            if list(r.schema.fields) != list(self.schema.fields):
                raise PetastormTpuError(
                    f"Schema mismatch: {list(r.schema.fields)} vs"
                    f" {list(self.schema.fields)}")
            if (list(getattr(r, "device_decode_fields", ()) or ())
                    != self.device_decode_fields
                    or frozenset(getattr(r, "device_decode_mixed", ()) or ())
                    != self.device_decode_mixed):
                raise PetastormTpuError(
                    "All readers must share the same decode_placement: one"
                    f" ships {self.device_decode_fields or 'pixels'} and"
                    f" another {getattr(r, 'device_decode_fields', []) or 'pixels'}"
                    " (mixed-geometry mode must also match)")

    @property
    def last_row_consumed(self) -> bool:
        """True once every underlying reader finished its epochs."""
        return all(r.last_row_consumed for r in self._readers)

    def __iter__(self):
        return self

    def __next__(self):
        if self.device_decode_fields:
            raise PetastormTpuError(
                f"fields {self.device_decode_fields} use"
                " decode_placement='device' (coefficient planes, not pixels);"
                " consume through petastorm_tpu.jax.JaxDataLoader or use"
                " decode_placement='host'")
        while self._alive:
            weights = self._p[self._alive] / self._p[self._alive].sum()
            i = int(self._rng.choice(len(self._alive), p=weights))
            try:
                return next(self._readers[self._alive[i]])
            except StopIteration:
                self._alive.pop(i)
        raise StopIteration

    def iter_batches(self):
        """Columnar batches drawn from the mixed stream (device-feed path)."""
        sources = [r.iter_batches() for r in self._readers]
        alive = list(range(len(sources)))
        while alive:
            weights = self._p[alive] / self._p[alive].sum()
            i = int(self._rng.choice(len(alive), p=weights))
            try:
                yield next(sources[alive[i]])
            except StopIteration:
                alive.pop(i)

    def stop(self) -> None:
        """Stop every underlying reader."""
        for r in self._readers:
            r.stop()

    def join(self) -> None:
        """Wait for every underlying reader to exit (after stop())."""
        for r in self._readers:
            r.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        self.join()
