"""Exception types and failure-handling policy for petastorm_tpu.

Reference parity: petastorm/errors.py (NoDataAvailableError at errors.py:16-17).

Beyond the reference: the fault-tolerance layer (``make_reader(on_error=...)``)
lives here - the :class:`ErrorPolicy` knob, its budget-exhaustion error, and
the data-vs-infrastructure classification the pool applies to worker
failures.  A multi-hour pod epoch must not die on one poisoned jpeg in a
million rows (tf.data service treats skip-and-account semantics as a
prerequisite for production serving); equally, silently skipping half the
dataset must not look like success - hence explicit budgets.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


#: default infra-failure requeue budget (attempts beyond the first
#: delivery) - shared by every pool flavor and by ErrorPolicy, so skip-mode
#: and raise-mode readers can never drift apart.  Lives here (not pool.py)
#: because pool imports errors, not the reverse.
DEFAULT_REQUEUE_ATTEMPTS = 2


class PetastormTpuError(Exception):
    """Base class for all petastorm_tpu errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when a reader shard/predicate/selector combination selects no data.

    Reference: petastorm/errors.py:16, raised at petastorm/reader.py:502-504 when
    there are fewer rowgroups than shards.
    """


class SchemaError(PetastormTpuError):
    """Schema definition, serialization, or validation failure."""


class CodecError(PetastormTpuError):
    """Codec encode/decode failure (bad dtype, non-compliant shape, ...)."""


class MetadataError(PetastormTpuError):
    """Dataset metadata is missing or unreadable (not a petastorm_tpu dataset)."""


class ReaderClosedError(PetastormTpuError):
    """Operation on a reader that has been stopped/joined."""


class EpochNotFinishedError(PetastormTpuError):
    """reset() called mid-epoch.

    Reference prohibits mid-epoch reset (petastorm/reader.py:438-445); we keep the
    same contract because in-flight work items would leak across epochs.
    """


class ErrorBudgetExceededError(PetastormTpuError):
    """An ``on_error`` skip policy ran out of budget.

    Raised by the reader when the number (or fraction) of skipped rowgroups
    exceeds the :class:`ErrorPolicy` limits - too many failures stop looking
    like weather and start looking like a broken dataset or outage, which
    must fail loudly rather than silently train on a shrinking sample.

    ``diagnostics``: the reader's pipeline-state snapshot taken at abort
    time (queue depths, quarantine ledger, and - when telemetry is on - the
    flight-recorder record with the sampled series leading into the
    exhaustion), same contract as
    :class:`~petastorm_tpu.pool.PipelineStallError`.
    """

    def __init__(self, message: str, diagnostics: Optional[dict] = None):
        super().__init__(message)
        self.diagnostics = diagnostics or {}


class CircuitOpenError(OSError, PetastormTpuError):
    """The storage circuit breaker is open: consecutive transient-IO
    failures crossed :class:`~petastorm_tpu.retry.RetryPolicy.circuit_threshold`
    and further IO is failed FAST instead of each worker independently
    burning its full retry-with-backoff budget against a store that is
    plainly down (a retry storm compounds an outage: N workers x
    max_attempts x backoff of traffic against a struggling backend).

    Subclasses ``OSError`` so the existing failure taxonomy holds: the
    exhausted-retry classification (``classify_error`` -> ``'data'``)
    applies, meaning an ``on_error`` skip policy quarantines the affected
    rowgroups and a budgeted policy trips
    :class:`ErrorBudgetExceededError` during a sustained outage - while
    ``is_transient`` explicitly refuses to retry it (the breaker exists to
    STOP retries).  After ``circuit_cooldown_s`` one probe call is let
    through (half-open); success closes the circuit again.
    """


@dataclasses.dataclass(frozen=True)
class ErrorPolicy:
    """Skip-and-account failure policy for ``make_reader(on_error=...)``.

    With a policy in force, *data* errors (corrupt rowgroup, codec/transform
    failure - see :func:`classify_error`) no longer kill the read: the
    failing work item is skipped, quarantined in ``Reader.diagnostics``
    (``quarantined_rowgroups``) and counted in telemetry
    (``errors.skipped_rowgroups``), and iteration continues.  *Infrastructure*
    errors (worker process crash/OOM) are first requeued transparently onto
    surviving workers up to ``max_requeue_attempts``; only an item that
    exhausts its attempts is handed to the skip path.

    ``max_skipped_rowgroups``: absolute skip budget (None = unlimited).
    ``max_skipped_fraction``: skipped / expected items (None = unlimited);
    the denominator is the total expected item count, or - for
    ``num_epochs=None`` readers, which have no total - the items consumed
    so far, floored at one epoch (so a steady per-epoch corruption rate
    reads as a steady fraction, not a cumulative count).  Exceeding either
    raises :class:`ErrorBudgetExceededError`.
    """

    max_skipped_rowgroups: Optional[int] = None
    max_skipped_fraction: Optional[float] = None
    max_requeue_attempts: int = DEFAULT_REQUEUE_ATTEMPTS

    def __post_init__(self):
        if (self.max_skipped_rowgroups is not None
                and self.max_skipped_rowgroups < 0):
            raise PetastormTpuError(
                "ErrorPolicy.max_skipped_rowgroups must be >= 0 or None")
        if (self.max_skipped_fraction is not None
                and not 0.0 <= self.max_skipped_fraction <= 1.0):
            raise PetastormTpuError(
                "ErrorPolicy.max_skipped_fraction must be in [0, 1] or None")
        if self.max_requeue_attempts < 0:
            raise PetastormTpuError(
                "ErrorPolicy.max_requeue_attempts must be >= 0")


def resolve_error_policy(on_error) -> Optional[ErrorPolicy]:
    """User-facing ``on_error`` knob -> concrete policy (None = raise mode).

    ``'raise'``/None keeps today's fail-fast behavior; ``'skip'`` is an
    unbudgeted :class:`ErrorPolicy`; an ``ErrorPolicy`` passes through.
    """
    if on_error is None or on_error == "raise":
        return None
    if on_error == "skip":
        return ErrorPolicy()
    if isinstance(on_error, ErrorPolicy):
        return on_error
    raise PetastormTpuError(
        f"on_error must be 'raise', 'skip' or an ErrorPolicy; got {on_error!r}")


def classify_error(exc: BaseException) -> str:
    """Classify a worker failure: ``'data'`` (skip-eligible) vs ``'infra'``.

    Anything *raised inside* a worker function - CodecError, pyarrow
    ArrowInvalid, transform exceptions - is treated as a property of the
    work item and classifies as ``'data'``: retrying it on another worker
    would fail identically, so the only useful recovery is skip +
    quarantine.  ``'infra'`` failures are properties of the *worker* (OOM,
    crash): the item itself is healthy and requeues onto a surviving
    worker.  A worker process that dies without delivering a traceback is
    classified ``'infra'`` by the pool directly (it never reaches here).

    Deliberate edge: an IO error that already exhausted its ``io_retries``
    budget ALSO classifies as ``'data'`` - the bounded retry layer is the
    designated defense against weather, and reclassifying its failures as
    requeueable would double-retry every outage.  The consequence is that a
    sustained storage outage under an *unbudgeted* skip policy will skip
    (not fail) every rowgroup it touches; production skip policies should
    set ``ErrorPolicy`` budgets so an outage trips
    :class:`ErrorBudgetExceededError` instead of silently shrinking the
    sample (docs/operations.md "Failure handling").
    """
    if isinstance(exc, MemoryError):
        return "infra"
    return "data"
