"""A mocked pyspark pinned to the EXACT API surface the converter uses.

pyspark cannot be installed in this environment (no package installs, zero
network egress - docs/operations.md 'Spark converter verification'), so the
Spark-DataFrame path of ``make_converter`` is exercised against this
duck-typed stand-in, pinned to the pyspark 3.5 signatures:

* ``pyspark.sql.functions.col(name: str)``
* ``pyspark.ml.functions.vector_to_array(col: Column, dtype)`` with dtype in
  {'float32', 'float64'}
* ``Column.cast('float' | 'double' | 'array<float>' | 'array<double>')``
* ``DataFrame.withColumn`` / ``.schema.fields`` / ``.schema.json()`` /
  ``._jdf.queryExecution().analyzed().toString()`` (plan fingerprint)
* ``DataFrameWriter.mode('overwrite').option('compression', codec)
  .option('parquet.block.size', bytes).parquet(url)``

Every mock REJECTS calls outside those signatures (assertion) instead of
silently accepting drift.  ``MockSparkDataFrame.toPandas()`` raises: the
converter must materialize on the "executors" (``df.write.parquet``), never
collect to the driver (reference spark_dataset_converter.py:546-562).

Shared by ``tests/test_converter.py`` and ``examples/spark_converter/`` (the
example switches to a real local SparkSession when pyspark is importable).
Reference analog: how the reference mocks external systems in its own suite
(SURVEY.md section 4).
"""

from __future__ import annotations

import contextlib
import os
import sys
import types

import numpy as np
import pyarrow as pa


class MockVector:
    """Stand-in for a pyspark.ml.linalg Vector (VectorUDT cell)."""

    def __init__(self, values):
        self._values = np.asarray(values, dtype=np.float64)

    def toArray(self):
        return self._values


class MockType:
    def __init__(self, name, element=None):
        self._name = name
        self.elementType = element

    @property
    def type_name(self):
        return self._name


def mock_type(name, element=None):
    t = MockType(name, element)
    t.__class__ = type(name, (MockType,), {})  # type(x).__name__ drives code
    return t


class MockField:
    def __init__(self, name, data_type):
        self.name = name
        self.dataType = data_type


class MockSchema:
    def __init__(self, fields):
        self.fields = fields

    def json(self):
        return "|".join(f"{f.name}:{type(f.dataType).__name__}"
                        for f in self.fields)


class MockCol:
    def __init__(self, name):
        self.name = name

    def cast(self, target):
        return ("cast", self.name, target)


def build_mock_pyspark_modules() -> dict:
    """{module name: module} for the pinned pyspark surface; install into
    ``sys.modules`` (tests use monkeypatch.setitem, the example uses
    ``installed_mock_pyspark``)."""
    root = types.ModuleType("pyspark")
    sql = types.ModuleType("pyspark.sql")
    sqlf = types.ModuleType("pyspark.sql.functions")
    ml = types.ModuleType("pyspark.ml")
    mlf = types.ModuleType("pyspark.ml.functions")

    def _col(name):
        assert isinstance(name, str) and name, \
            f"pyspark.sql.functions.col takes a column-name string, got {name!r}"
        return MockCol(name)

    def _vector_to_array(col, dtype="float64"):
        assert isinstance(col, MockCol), \
            f"vector_to_array takes a Column (from col()), got {type(col)}"
        assert dtype in ("float32", "float64"), \
            f"vector_to_array dtype must be 'float32'/'float64', got {dtype!r}"
        return ("v2a", col.name, dtype)

    sqlf.col = _col
    mlf.vector_to_array = _vector_to_array
    return {"pyspark": root, "pyspark.sql": sql,
            "pyspark.sql.functions": sqlf, "pyspark.ml": ml,
            "pyspark.ml.functions": mlf}


@contextlib.contextmanager
def installed_mock_pyspark():
    """Context manager installing the mock modules into ``sys.modules`` (and
    removing them after) - for scripts; tests prefer monkeypatch.setitem."""
    mods = build_mock_pyspark_modules()
    saved = {name: sys.modules.get(name) for name in mods}
    sys.modules.update(mods)
    try:
        yield
    finally:
        for name, old in saved.items():
            if old is None:
                sys.modules.pop(name, None)
            else:
                sys.modules[name] = old


class MockSparkDataFrame:
    """Pandas-backed stand-in: withColumn applies the mock expressions, write
    splits into two 'executor' part files, toPandas() is forbidden."""

    def __init__(self, pdf, schema, plan_tag):
        self._pdf = pdf
        self.schema = schema
        self._plan_tag = plan_tag

        class _QE:
            def queryExecution(self_inner):
                class _A:
                    def analyzed(self2):
                        class _S:
                            def toString(self3):
                                return plan_tag
                        return _S()
                return _A()
        self._jdf = _QE()

    def toPandas(self):
        raise AssertionError("driver-side collection: the Spark path must"
                             " materialize on executors")

    def withColumn(self, name, expr):
        pdf = self._pdf.copy()
        fields = list(self.schema.fields)
        idx = next(i for i, f in enumerate(fields) if f.name == name)
        kind = expr[0]
        if kind == "v2a":
            _, src, dtype = expr
            np_t = np.float32 if dtype == "float32" else np.float64
            pdf[name] = [np.asarray(v.toArray(), dtype=np_t)
                         for v in pdf[src]]
            fields[idx] = MockField(name, mock_type(
                "ArrayType", mock_type(
                    "FloatType" if dtype == "float32" else "DoubleType")))
        elif kind == "cast":
            _, src, target = expr
            # pin cast targets to valid Spark SQL type strings (Column.cast
            # accepts a DDL-formatted type name)
            assert target in ("float", "double", "array<float>",
                              "array<double>"), \
                f"Column.cast called with non-Spark type string {target!r}"
            if target in ("float", "double"):
                np_t = np.float32 if target == "float" else np.float64
                pdf[name] = pdf[src].astype(np_t)
                fields[idx] = MockField(name, mock_type(
                    "FloatType" if target == "float" else "DoubleType"))
            else:  # array<float> / array<double>
                np_t = np.float32 if "float" in target else np.float64
                pdf[name] = [np.asarray(v, dtype=np_t) for v in pdf[src]]
                fields[idx] = MockField(name, mock_type(
                    "ArrayType", mock_type(
                        "FloatType" if "float" in target else "DoubleType")))
        else:
            raise AssertionError(f"unknown mock expr {expr!r}")
        return MockSparkDataFrame(pdf, MockSchema(fields),
                                  self._plan_tag + f"+{name}:{kind}")

    #: DataFrameWriter call sequences, one list per .write chain (pinned-API
    #: assertion surface; cleared by tests that inspect it)
    write_calls = []

    @property
    def write(self):
        df = self
        calls = []
        MockSparkDataFrame.write_calls.append(calls)

        class _Writer:
            def mode(self_inner, m):
                # converter.py must write mode('overwrite') into its fresh tmp
                # dir (DataFrameWriter.mode accepts a saveMode string)
                assert m == "overwrite", f"unexpected write mode {m!r}"
                calls.append(("mode", m))
                return self_inner

            def option(self_inner, k, v):
                # the two options the reference sets (spark_dataset_converter
                # .py:553-555): parquet codec + target block size
                assert k in ("compression", "parquet.block.size"), \
                    f"unexpected DataFrameWriter.option key {k!r}"
                if k == "parquet.block.size":
                    assert isinstance(v, int) and v > 0, v
                else:
                    assert isinstance(v, str) and v, v
                calls.append(("option", k, v))
                return self_inner

            def parquet(self_inner, url):
                assert isinstance(url, str) and "://" in url or url.startswith("/"), \
                    f"DataFrameWriter.parquet takes a path/URL string, got {url!r}"
                calls.append(("parquet", url))
                path = url[len("file://"):] if url.startswith("file://") else url
                os.makedirs(path, exist_ok=True)
                n = len(df._pdf)
                for part, sl in enumerate((slice(0, n // 2), slice(n // 2, n))):
                    table = pa.Table.from_pandas(df._pdf.iloc[sl],
                                                 preserve_index=False)
                    import pyarrow.parquet as pq
                    pq.write_table(table,
                                   os.path.join(path, f"part-{part:05d}.parquet"))
                open(os.path.join(path, "_SUCCESS"), "w").close()
        return _Writer()


def mock_spark_dataframe(n=32):
    """A small MockSparkDataFrame with long/double/VectorUDT columns - the
    three Spark types the converter's dtype/vector handling covers."""
    import pandas as pd

    pdf = pd.DataFrame({
        "id": np.arange(n, dtype=np.int64),
        "x": np.linspace(0, 1, n).astype(np.float64),
        "vec": [MockVector([i, i + 0.5, i + 0.25]) for i in range(n)],
    })
    schema = MockSchema([
        MockField("id", mock_type("LongType")),
        MockField("x", mock_type("DoubleType")),
        MockField("vec", mock_type("VectorUDT")),
    ])
    return MockSparkDataFrame(pdf, schema, plan_tag=f"mock-plan-{n}")
