"""Rowgroup selectors: whole-rowgroup filtering via stored indexes.

Reference parity: petastorm/selectors.py - RowGroupSelectorBase
(selectors.py:19-29), SingleIndexSelector (selectors.py:32-55),
IntersectIndexSelector / UnionIndexSelector (selectors.py:58-100).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Sequence, Set

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.indexing import RowGroupIndexer


class RowGroupSelectorBase(ABC):
    @abstractmethod
    def get_index_names(self) -> List[str]:
        """Names of the stored rowgroup indexes this selector consults."""
        ...

    @abstractmethod
    def select_row_groups(self, indexes: Dict[str, RowGroupIndexer]) -> Set[int]:
        """Global rowgroup indexes to read, resolved against the dataset's
        stored indexes (missing index names raise with the available set)."""
        ...

    def _require(self, indexes: Dict[str, RowGroupIndexer], name: str) -> RowGroupIndexer:
        if name not in indexes:
            raise PetastormTpuError(
                f"Index {name!r} is not stored in this dataset; available:"
                f" {sorted(indexes)}. Build it with build_rowgroup_index().")
        return indexes[name]


class SingleIndexSelector(RowGroupSelectorBase):
    """Union of rowgroups holding any of the given values of one index."""

    def __init__(self, index_name: str, values: Sequence):
        self._name = index_name
        self._values = list(values)

    def get_index_names(self) -> List[str]:
        return [self._name]

    def select_row_groups(self, indexes: Dict[str, RowGroupIndexer]) -> Set[int]:
        ix = self._require(indexes, self._name)
        out: Set[int] = set()
        for v in self._values:
            out |= ix.get_row_group_indexes(v)
        return out


class IntersectIndexSelector(RowGroupSelectorBase):
    """Rowgroups selected by ALL child selectors."""

    def __init__(self, selectors: Sequence[RowGroupSelectorBase]):
        self._selectors = list(selectors)

    def get_index_names(self) -> List[str]:
        return [n for s in self._selectors for n in s.get_index_names()]

    def select_row_groups(self, indexes: Dict[str, RowGroupIndexer]) -> Set[int]:
        sets = [s.select_row_groups(indexes) for s in self._selectors]
        return set.intersection(*sets) if sets else set()


class UnionIndexSelector(RowGroupSelectorBase):
    """Rowgroups selected by ANY child selector."""

    def __init__(self, selectors: Sequence[RowGroupSelectorBase]):
        self._selectors = list(selectors)

    def get_index_names(self) -> List[str]:
        return [n for s in self._selectors for n in s.get_index_names()]

    def select_row_groups(self, indexes: Dict[str, RowGroupIndexer]) -> Set[int]:
        out: Set[int] = set()
        for s in self._selectors:
            out |= s.select_row_groups(indexes)
        return out
