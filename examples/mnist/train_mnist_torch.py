"""MNIST-style training through the PyTorch delivery layer.

Reference parity: examples/mnist/pytorch_example.py - kept for users migrating
torch training loops; the JAX example (train_mnist_jax.py) is the TPU path.
"""

import argparse
import tempfile

import numpy as np
import torch
import torch.nn.functional as F

from petastorm_tpu.pytorch import BatchedDataLoader
from petastorm_tpu.reader import make_reader


def train(dataset_url: str, epochs: int = 1, batch_size: int = 32,
          lr: float = 1e-3) -> float:
    model = torch.nn.Sequential(
        torch.nn.Flatten(), torch.nn.Linear(28 * 28, 128), torch.nn.ReLU(),
        torch.nn.Linear(128, 10))
    opt = torch.optim.Adam(model.parameters(), lr=lr)
    acc = 0.0
    for epoch in range(epochs):
        reader = make_reader(dataset_url, num_epochs=1,
                             schema_fields=["image", "digit"],
                             shuffle_seed=epoch)
        accs = []
        with BatchedDataLoader(reader, batch_size=batch_size,
                               shuffling_queue_capacity=256) as loader:
            for batch in loader:
                x = batch["image"].float() / 255.0
                y = batch["digit"]
                opt.zero_grad()
                logits = model(x)
                loss = F.cross_entropy(logits, y)
                loss.backward()
                opt.step()
                accs.append((logits.argmax(-1) == y).float().mean().item())
        acc = float(np.mean(accs))
        print(f"epoch {epoch}: acc {acc:.3f}")
    return acc


if __name__ == "__main__":
    from examples.mnist.train_mnist_jax import generate_dataset

    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-url", default=None)
    parser.add_argument("--rows", type=int, default=2048)
    parser.add_argument("--epochs", type=int, default=1)
    args = parser.parse_args()
    url = args.dataset_url or tempfile.mkdtemp(prefix="mnist_tpu_") + "/mnist"
    generate_dataset(url, args.rows)
    print(f"final train accuracy: {train(url, epochs=args.epochs):.3f}")
