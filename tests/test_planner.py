"""Pipeline planner tests (ISSUE 15 tentpole b + satellites): the parquet
footer metadata pass, per-knob provenance, the flight-profile store (atomic
writes, corrupt/stale tolerance, dataset-fingerprint keying so a rewritten
dataset never replays stale knobs), the reader e2e (cold run writes a
profile at stop, the next reader starts from it), the loader prefetch seed,
and the CLI renderings."""

import json
import os
import time

import numpy as np
import pytest

from petastorm_tpu.autotune import AutotunePolicy
from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.etl.metadata import open_dataset
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.planner import (PROFILE_VERSION, ProfileStore,
                                   dataset_fingerprint, footer_stats,
                                   plan_reader, schema_hash)
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.test_util.synthetic import synthetic_rgb_image


def _write_scalar_ds(path, rows=200, rg=4):
    schema = Schema("P", [Field("x", np.int64, (), ScalarCodec())])
    write_dataset(str(path), schema, [{"x": i} for i in range(rows)],
                  row_group_size_rows=rg)
    return str(path)


def _write_image_ds(path, rows=32, rg=8):
    schema = Schema("Img", [
        Field("label", np.int64, (), ScalarCodec()),
        Field("image", np.uint8, (48, 48, 3),
              CompressedImageCodec("jpeg", quality=90)),
    ])
    write_dataset(str(path), schema,
                  [{"label": i, "image": synthetic_rgb_image(i, 48, 48)}
                   for i in range(rows)], row_group_size_rows=rg)
    return str(path)


_FAST = AutotunePolicy(warmup_s=0.2, settle_s=0.2, tick_s=0.05,
                       eval_points=2, cooldown_s=0.1)


# -- footer metadata pass ------------------------------------------------------

def test_footer_stats_summarizes_read_columns(tmp_path):
    url = _write_image_ds(tmp_path / "img")
    info = open_dataset(url, require_stored_schema=False)
    meta = footer_stats(info, ["label", "image"])
    assert meta["rowgroups_sampled"] >= 1
    assert meta["rowgroups_total"] == 4
    assert meta["rows_total"] == 32
    assert meta["avg_rowgroup_compressed_bytes"] > 0
    assert meta["avg_rowgroup_uncompressed_bytes"] > 0
    assert meta["expansion"] >= 1.0
    assert set(meta["columns"]) == {"label", "image"}
    # field filtering: asking for one column shrinks the span
    label_only = footer_stats(info, ["label"])
    assert (label_only["avg_rowgroup_uncompressed_bytes"]
            < meta["avg_rowgroup_uncompressed_bytes"])


def test_footer_stats_failure_degrades_to_empty(tmp_path):
    url = _write_scalar_ds(tmp_path / "ds")
    info = open_dataset(url, require_stored_schema=False)

    class _Broken:
        def open_input_file(self, path):
            raise OSError("no footer for you")

    info.filesystem = _Broken()
    assert footer_stats(info, ["x"]) == {}


# -- fingerprint / schema hash -------------------------------------------------

def test_fingerprint_changes_when_dataset_rewritten(tmp_path):
    url = _write_scalar_ds(tmp_path / "ds", rows=40)
    fp1 = dataset_fingerprint(open_dataset(url, require_stored_schema=False))
    assert fp1 == dataset_fingerprint(
        open_dataset(url, require_stored_schema=False))
    time.sleep(0.01)  # ensure a distinct mtime_ns even on coarse clocks
    import shutil

    shutil.rmtree(url)
    _write_scalar_ds(tmp_path / "ds", rows=40)
    fp2 = dataset_fingerprint(open_dataset(url, require_stored_schema=False))
    assert fp1 != fp2


def test_schema_hash_keys_fields_and_transform():
    assert schema_hash(["a", "b"], "-") != schema_hash(["a"], "-")
    assert schema_hash(["a"], "sig1") != schema_hash(["a"], "sig2")
    assert schema_hash(["a"], "sig1") == schema_hash(["a"], "sig1")


# -- profile store -------------------------------------------------------------

def test_profile_store_roundtrip_atomic(tmp_path):
    store = ProfileStore(str(tmp_path))
    path = store.save("f" * 32, "s" * 16, {"knobs": {"workers": 3}})
    assert path and os.path.exists(path)
    assert not [n for n in os.listdir(store.directory)
                if n.endswith(".tmp")]
    profile = store.load("f" * 32, "s" * 16)
    assert profile["knobs"] == {"workers": 3}
    assert profile["version"] == PROFILE_VERSION


def test_profile_store_tolerates_corrupt_and_mismatched(tmp_path, caplog):
    import logging

    store = ProfileStore(str(tmp_path))
    path = store.save("f" * 32, "s" * 16, {"knobs": {"workers": 3}})
    with open(path, "w") as f:
        f.write("{not json")
    with caplog.at_level(logging.WARNING, logger="petastorm_tpu.planner"):
        assert store.load("f" * 32, "s" * 16) is None
    assert any("corrupt" in r.getMessage() for r in caplog.records)
    # valid JSON but wrong fingerprint inside (tampered/moved file)
    with open(path, "w") as f:
        json.dump({"version": PROFILE_VERSION, "fingerprint": "other",
                   "schema_hash": "s" * 16, "knobs": {}}, f)
    assert store.load("f" * 32, "s" * 16) is None
    # a DIFFERENT dataset fingerprint simply finds no profile
    assert store.load("x" * 32, "s" * 16) is None


def test_profile_store_sweeps_to_cap(tmp_path, monkeypatch):
    import petastorm_tpu.planner as planner_mod

    monkeypatch.setattr(planner_mod, "MAX_PROFILES", 3)
    store = ProfileStore(str(tmp_path))
    for i in range(6):
        store.save(f"{i:032d}", "s" * 16, {"knobs": {}})
        os.utime(store.path_for(f"{i:032d}", "s" * 16),
                 (i + 1.0, i + 1.0))
    store.save("f" * 32, "s" * 16, {"knobs": {}})
    kept = [n for n in os.listdir(store.directory) if n.endswith(".json")]
    assert len(kept) <= 4  # cap + the one just written


# -- plan_reader provenance ----------------------------------------------------

def test_plan_provenance_metadata_vs_pinned(tmp_path):
    url = _write_scalar_ds(tmp_path / "ds")
    info = open_dataset(url, require_stored_schema=False)
    v = plan_reader(info, ["x"], policy=_FAST, cores=4,
                    cache_location=str(tmp_path / "loc"))
    assert v.knobs["workers"].source == "metadata"
    assert v.knobs["workers"].value == 2  # lightweight columnar heuristic
    assert v.knobs["prefetch"].source == "metadata"
    assert v.profile is None
    pinned = plan_reader(info, ["x"], policy=_FAST, cores=4,
                         workers_count=7, results_queue_size=5,
                         results_queue_pinned=True,
                         cache_location=str(tmp_path / "loc"))
    assert pinned.knobs["workers"].source == "pinned"
    assert pinned.knobs["workers"].value == 7
    assert pinned.knobs["results_queue"].source == "pinned"
    assert pinned.knobs["results_queue"].value == 5


def test_plan_profile_wins_and_clamps(tmp_path):
    url = _write_scalar_ds(tmp_path / "ds")
    info = open_dataset(url, require_stored_schema=False)
    fp = dataset_fingerprint(info)
    sh = schema_hash(["x"], "-")
    ProfileStore(str(tmp_path / "loc")).save(
        fp, sh, {"knobs": {"workers": 99, "prefetch": 3}})
    v = plan_reader(info, ["x"], policy=_FAST, cores=4,
                    cache_location=str(tmp_path / "loc"))
    assert v.knobs["workers"].source == "profile"
    assert v.knobs["workers"].value == _FAST.max_workers  # clamped
    assert v.knobs["prefetch"].value == 3
    assert v.profile is not None


def test_plan_image_dataset_gets_wide_pool(tmp_path):
    url = _write_image_ds(tmp_path / "img")
    info = open_dataset(url, require_stored_schema=False)
    v = plan_reader(info, ["label", "image"], policy=_FAST, cores=8,
                    cache_location=str(tmp_path / "loc"),
                    image_fields=["image"])
    assert v.knobs["workers"].source == "metadata"
    assert v.knobs["workers"].value == 7  # cores - 1: decode-heavy
    assert v.knobs["decode_threads"].value == 1


def test_plan_cache_mem_fits_dataset(tmp_path):
    url = _write_image_ds(tmp_path / "img")
    info = open_dataset(url, require_stored_schema=False)
    v = plan_reader(info, ["label", "image"], policy=_FAST, cores=2,
                    cache_type="shared",
                    cache_location=str(tmp_path / "loc"),
                    image_fields=["image"])
    assert "cache_mem" in v.knobs
    assert v.knobs["cache_mem"].value >= 16
    assert v.knobs["cache_mem"].source == "metadata"


# -- reader e2e ----------------------------------------------------------------

def test_reader_writes_profile_and_next_reader_starts_from_it(tmp_path):
    url = _write_scalar_ds(tmp_path / "ds")
    loc = str(tmp_path / "loc")
    with make_batch_reader(url, reader_pool_type="thread",
                           workers_count="auto", shuffle_row_groups=False,
                           autotune=_FAST, cache_location=loc,
                           sample_interval_s=0.1, num_epochs=2) as r:
        assert r.planner is not None
        assert sum(b.num_rows for b in r.iter_batches()) == 400
        profile_path = r.planner.profile_path
    assert os.path.exists(profile_path)
    with open(profile_path) as f:
        profile = json.load(f)
    assert profile["knobs"]["workers"] >= 1
    assert profile["source"] == "autotune"

    with make_batch_reader(url, reader_pool_type="thread",
                           workers_count="auto", shuffle_row_groups=False,
                           autotune=_FAST, cache_location=loc,
                           sample_interval_s=0.1) as r2:
        verdict = r2.planner
        assert verdict.knobs["workers"].source == "profile"
        assert verdict.knobs["workers"].value == profile["knobs"]["workers"]
        # the acceptance shape the CI smoke asserts too: at least one
        # planned knob is non-default
        assert any(k.source in ("profile", "metadata")
                   for k in verdict.knobs.values())
        assert sum(b.num_rows for b in r2.iter_batches()) == 200
        diag = r2.diagnostics
    assert diag["planner"]["knobs"]["workers"]["source"] == "profile"


def test_explicit_default_results_queue_is_pinned(tmp_path):
    """results_queue_size=10 passed EXPLICITLY must pin (the None-sentinel
    default is what distinguishes 'user asked for the default value' from
    'user said nothing' - review finding)."""
    url = _write_scalar_ds(tmp_path / "ds")
    with make_batch_reader(url, reader_pool_type="thread",
                           workers_count="auto", results_queue_size=10,
                           autotune=_FAST, sample_interval_s=0.1,
                           cache_location=str(tmp_path / "loc")) as r:
        knob = r.planner.knobs["results_queue"]
        assert knob.source == "pinned" and knob.value == 10
        list(r.iter_batches())
    with make_batch_reader(url, reader_pool_type="thread",
                           workers_count="auto", autotune=_FAST,
                           sample_interval_s=0.1,
                           cache_location=str(tmp_path / "loc2")) as r:
        assert r.planner.knobs["results_queue"].source in ("metadata",
                                                           "default")
        list(r.iter_batches())


def test_planner_disabled_by_policy_and_without_autotune(tmp_path):
    import dataclasses

    url = _write_scalar_ds(tmp_path / "ds")
    with make_batch_reader(url, reader_pool_type="thread",
                           workers_count="auto",
                           autotune=dataclasses.replace(_FAST,
                                                        planner=False),
                           sample_interval_s=0.1) as r:
        assert r.planner is None
        list(r.iter_batches())
    with make_batch_reader(url, workers_count=2, autotune=False) as r:
        assert r.planner is None
        list(r.iter_batches())


def test_unconsumed_reader_writes_no_profile(tmp_path):
    url = _write_scalar_ds(tmp_path / "ds")
    loc = str(tmp_path / "loc")
    with make_batch_reader(url, reader_pool_type="thread",
                           workers_count="auto", autotune=_FAST,
                           cache_location=loc,
                           sample_interval_s=0.1) as r:
        path = r.planner.profile_path
    assert not os.path.exists(path)


@pytest.mark.skipif(
    not __import__("petastorm_tpu.native", fromlist=["allocator_available"])
    .allocator_available() and not os.environ.get(
        "PETASTORM_TPU_REQUIRE_ARENA"),
    reason="native shm_arena library unavailable")
def test_planner_seeds_shared_tier_residency_once(tmp_path):
    from petastorm_tpu.cache_shared import SharedWarmCache

    url = _write_image_ds(tmp_path / "img")
    loc = str(tmp_path / "tier")
    try:
        with make_batch_reader(url, reader_pool_type="thread",
                               workers_count="auto", shuffle_row_groups=False,
                               autotune=_FAST, cache_type="shared",
                               cache_location=loc,
                               sample_interval_s=0.1) as r:
            planned = r.planner.knobs["cache_mem"].value
            target = r.warm_cache.get_target_bytes()
            default = int(0.8 * r.warm_cache.l1_size_bytes)
            assert target != default
            assert target == min(planned * 2 ** 20, default)
            list(r.iter_batches())
            # a second reader must NOT re-seed a target someone moved
            moved = r.warm_cache.set_target_bytes(32 * 2 ** 20)
        with make_batch_reader(url, reader_pool_type="thread",
                               workers_count="auto", shuffle_row_groups=False,
                               autotune=_FAST, cache_type="shared",
                               cache_location=loc,
                               sample_interval_s=0.1) as r2:
            assert r2.warm_cache.get_target_bytes() == moved
            list(r2.iter_batches())
    finally:
        SharedWarmCache(location=loc).cleanup()


def test_loader_prefetch_seeded_from_plan(tmp_path):
    from petastorm_tpu.jax import JaxDataLoader

    url = _write_scalar_ds(tmp_path / "ds")
    reader = make_batch_reader(url, reader_pool_type="thread",
                               workers_count="auto", shuffle_row_groups=False,
                               autotune=_FAST,
                               cache_location=str(tmp_path / "loc"),
                               sample_interval_s=0.1)
    planned = reader.planner.knobs["prefetch"]
    assert planned.source == "metadata" and planned.value == 4
    with JaxDataLoader(reader, batch_size=8) as loader:
        assert loader.prefetch == 4
        for _ in loader:
            break
    reader2 = make_batch_reader(url, reader_pool_type="thread",
                                workers_count="auto",
                                shuffle_row_groups=False, autotune=_FAST,
                                cache_location=str(tmp_path / "loc"),
                                sample_interval_s=0.1)
    with JaxDataLoader(reader2, batch_size=8, prefetch=3) as loader:
        assert loader.prefetch == 3  # explicit pin beats the plan
        for _ in loader:
            break


# -- renderings ----------------------------------------------------------------

def test_render_planner_verdict_and_watch_line():
    from petastorm_tpu.tools.diagnose import (render_planner_verdict,
                                              render_watch_frame)

    planner = {
        "knobs": {"workers": {"value": 4, "source": "profile",
                              "why": "recorded flight profile"},
                  "prefetch": {"value": 2, "source": "default",
                               "why": "static default depth"}},
        "profile": {"written_at": 1.0, "observed_rows_per_sec": 1234.0,
                    "knobs": {"workers": 4}},
        "profile_path": "/tmp/p.json",
    }
    text = render_planner_verdict(planner)
    assert "workers=4(profile)" in text
    assert "observed 1234 rows/s" in text
    compact = render_planner_verdict(planner, compact=True)
    assert compact.startswith("planner: ")
    assert "\n" not in compact
    frame = render_watch_frame({"dt_s": 1.0, "rates": {}, "counters": {},
                                "gauges": {}, "stages": {}},
                               {"planner": planner, "consumed_items": 0})
    assert "planner: " in frame


def test_diagnose_json_carries_planner(tmp_path):
    from petastorm_tpu.tools.diagnose import run_diagnosis

    url = _write_scalar_ds(tmp_path / "ds", rows=40)
    result = run_diagnosis(url, workers_count=2, autotune=_FAST,
                           sample_interval_s=0.1,
                           cache_location=str(tmp_path / "loc"))
    assert result["rows"] == 40
    assert result["planner"] is not None
    assert result["planner"]["knobs"]["workers"]["source"] == "pinned"
