"""JAX loader tests on the 8-device virtual CPU mesh.

Validates device-sharded delivery the way the driver's dryrun does: explicit
meshes over the forced-host-platform devices (tests/conftest.py env).
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.jax import JaxDataLoader, make_jax_loader
from petastorm_tpu.parallel import (data_parallel_mesh, local_data_slice,
                                    sharding_for_batch)
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.etl.writer import write_dataset


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, "tests expect the 8-device virtual CPU platform"
    return devs


@pytest.fixture(scope="module")
def num_ds(tmp_path_factory):
    schema = Schema("Num", [
        Field("idx", np.int64),
        Field("vec", np.float32, (6,)),
        Field("img", np.uint8, (8, 8, 3)),
        Field("tag", np.dtype("object")),
    ])
    url = str(tmp_path_factory.mktemp("jax") / "num")
    rng = np.random.default_rng(0)
    rows = [{"idx": i, "vec": rng.standard_normal(6).astype(np.float32),
             "img": rng.integers(0, 255, (8, 8, 3), dtype=np.uint8),
             "tag": f"t{i}"} for i in range(64)]
    write_dataset(url, schema, rows, row_group_size_rows=8)
    return url, rows


def test_single_device_loader(num_ds):
    url, rows = num_ds
    reader = make_reader(url, shuffle_row_groups=False, schema_fields=["idx", "vec"])
    with JaxDataLoader(reader, batch_size=16) as loader:
        batches = list(loader)
    assert len(batches) == 4
    b = batches[0]
    assert isinstance(b["idx"], jax.Array) and b["idx"].shape == (16,)
    assert b["idx"].dtype == np.int32  # int64 promoted at the device boundary
    all_idx = np.concatenate([np.asarray(b["idx"]) for b in batches])
    assert sorted(all_idx.tolist()) == list(range(64))


def test_data_parallel_mesh_sharding(num_ds, devices):
    url, rows = num_ds
    mesh = data_parallel_mesh("data")
    reader = make_reader(url, shuffle_row_groups=False,
                         schema_fields=["idx", "img"])
    with JaxDataLoader(reader, batch_size=32, mesh=mesh) as loader:
        b = next(iter(loader))
    arr = b["img"]
    assert arr.shape == (32, 8, 8, 3)
    assert isinstance(arr.sharding, NamedSharding)
    assert arr.sharding.spec == P("data")
    # each of the 8 devices holds 4 rows
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(4, 8, 8, 3)}
    loader.stop()


def test_2d_mesh_sequence_sharding(num_ds, devices):
    # context-parallel style: batch on 'data' (2), feature dim on 'seq' (4)
    url, _ = num_ds
    mesh = Mesh(np.array(devices).reshape(2, 4), ("data", "seq"))
    reader = make_reader(url, shuffle_row_groups=False, schema_fields=["idx", "img"])
    shardings = {"idx": P("data"), "img": P("data", "seq")}
    with JaxDataLoader(reader, batch_size=16, mesh=mesh,
                       shardings=shardings) as loader:
        b = next(iter(loader))
    arr = b["img"]
    assert arr.shape == (16, 8, 8, 3)
    assert arr.sharding.spec == P("data", "seq")
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(8, 2, 8, 3)}  # 16/2 rows, 8/4 seq each
    loader.stop()


def test_string_field_rejected(num_ds):
    url, _ = num_ds
    reader = make_reader(url, schema_fields=["idx", "tag"])
    with pytest.raises(PetastormTpuError) as ei:
        JaxDataLoader(reader, batch_size=8)
    assert "tag" in str(ei.value)
    reader.stop(); reader.join()


def test_string_field_as_host_field(num_ds):
    url, _ = num_ds
    reader = make_reader(url, shuffle_row_groups=False, schema_fields=["idx", "tag"])
    with JaxDataLoader(reader, batch_size=16, host_fields=["tag"]) as loader:
        b = next(iter(loader))
    assert isinstance(b["idx"], jax.Array)
    assert isinstance(b["tag"], np.ndarray) and b["tag"].dtype == object


def test_variable_shape_needs_pad(tmp_path):
    schema = Schema("V", [Field("idx", np.int64), Field("pts", np.float32, (None, 2))])
    url = str(tmp_path / "var")
    rng = np.random.default_rng(1)
    write_dataset(url, schema,
                  [{"idx": i, "pts": rng.standard_normal((int(rng.integers(1, 9)), 2))
                    .astype(np.float32)} for i in range(32)],
                  row_group_size_rows=8)
    reader = make_reader(url, shuffle_row_groups=False)
    with pytest.raises(PetastormTpuError) as ei:
        JaxDataLoader(reader, batch_size=8)
    assert "pad_shapes" in str(ei.value)
    reader.stop(); reader.join()

    reader2 = make_reader(url, shuffle_row_groups=False)
    with JaxDataLoader(reader2, batch_size=8,
                       pad_shapes={"pts": (8, 2)}, pad_values=-1.0) as loader:
        batches = list(loader)
    assert all(b["pts"].shape == (8, 8, 2) for b in batches)
    first = np.asarray(batches[0]["pts"])
    assert (first == -1.0).any()  # padding present somewhere


def test_shuffling_buffer_decorrelates(num_ds):
    url, _ = num_ds
    reader = make_reader(url, shuffle_row_groups=False, schema_fields=["idx"])
    with JaxDataLoader(reader, batch_size=16, shuffling_queue_capacity=48,
                       buffer_seed=3) as loader:
        batches = [np.asarray(b["idx"]) for b in loader]
    got = np.concatenate(batches)
    assert sorted(got.tolist()) == list(range(64))
    assert got.tolist() != list(range(64))  # order changed


def test_drop_last_false_partial_batch(num_ds):
    url, _ = num_ds
    reader = make_reader(url, shuffle_row_groups=False, schema_fields=["idx"])
    with JaxDataLoader(reader, batch_size=24, drop_last=False) as loader:
        sizes = [int(b["idx"].shape[0]) for b in loader]
    assert sizes == [24, 24, 16]


def test_partial_batch_on_mesh_is_padded_static(num_ds, devices):
    # drop_last=False + mesh: final batch zero-padded to the static shape,
    # with '_valid_rows' carrying the true count (no shape change -> no recompile)
    url, _ = num_ds
    mesh = data_parallel_mesh()
    reader = make_reader(url, shuffle_row_groups=False, schema_fields=["idx"])
    with JaxDataLoader(reader, batch_size=24, mesh=mesh, drop_last=False) as loader:
        batches = list(loader)
    assert [int(b["idx"].shape[0]) for b in batches] == [24, 24, 24]
    assert "_valid_rows" not in batches[0]
    assert batches[-1]["_valid_rows"] == 16
    tail = np.asarray(batches[-1]["idx"])
    assert (tail[16:] == 0).all()


def test_worker_error_reaches_consumer(num_ds):
    url, _ = num_ds

    def broken(cols):
        raise ValueError("loader transform exploded")

    reader = make_reader(url, schema_fields=["idx"])
    with pytest.raises(ValueError):
        with JaxDataLoader(reader, batch_size=8, transform_fn=broken) as loader:
            next(iter(loader))


def test_make_jax_loader_one_call(num_ds, devices):
    url, _ = num_ds
    mesh = data_parallel_mesh()
    with make_jax_loader(url, batch_size=32, mesh=mesh,
                         schema_fields=["idx", "vec"], shuffle_row_groups=False,
                         num_epochs=1) as loader:
        batches = list(loader)
    assert len(batches) == 2
    assert batches[0]["vec"].sharding.spec == P("data")


def test_stop_midstream_ends_producer_thread(num_ds):
    # reader.stop() must terminate iter_batches (and the loader producer), not
    # leave a daemon thread busy-polling forever
    import threading
    import time

    url, _ = num_ds
    before = threading.active_count()
    reader = make_reader(url, shuffle_row_groups=False, schema_fields=["idx"],
                         num_epochs=None)
    loader = JaxDataLoader(reader, batch_size=16)
    next(iter(loader))
    loader.stop()
    loader.join()
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_make_jax_loader_failure_stops_reader(num_ds):
    import threading
    url, _ = num_ds
    before = threading.active_count()
    with pytest.raises(PetastormTpuError):
        make_jax_loader(url, batch_size=8, fields=["nonexistent_field"])
    import time
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_no_device_fields_clear_error(num_ds):
    url, _ = num_ds
    reader = make_reader(url, schema_fields=["tag"])
    with pytest.raises(PetastormTpuError) as ei:
        JaxDataLoader(reader, batch_size=8, host_fields=["tag"])
    assert "device-deliverable" in str(ei.value)
    reader.stop(); reader.join()


def test_exhausted_loader_raises_stopiteration_repeatably(num_ds):
    url, _ = num_ds
    reader = make_reader(url, shuffle_row_groups=False, schema_fields=["idx"])
    loader = JaxDataLoader(reader, batch_size=32)
    list(loader)
    with pytest.raises(StopIteration):
        next(loader)
    with pytest.raises(StopIteration):
        next(loader)  # still StopIteration, not 'producer died'
    loader.stop(); loader.join()


def test_make_jax_loader_narrows_reader_columns(num_ds):
    url, _ = num_ds
    with make_jax_loader(url, batch_size=16, fields=["idx"],
                         shuffle_row_groups=False, num_epochs=1) as loader:
        assert [f.name for f in loader._reader.schema] == ["idx"]
        b = next(iter(loader))
    assert set(b) == {"idx"}


def test_pad_rank_mismatch_clear_error_stacked():
    from petastorm_tpu.jax.loader import _pad_to
    col = np.zeros((4, 5), np.float32)  # rows rank-1, target rank-2
    with pytest.raises(PetastormTpuError) as ei:
        _pad_to(col, (8, 2), 0, np.float32)
    assert "rank mismatch" in str(ei.value)


def test_local_data_slice_single_process(devices):
    mesh = Mesh(np.array(devices).reshape(2, 4), ("data", "seq"))
    sharding = NamedSharding(mesh, P("data", "seq"))
    sl = local_data_slice(sharding, (16, 8))
    # single process addresses every device -> full array
    assert sl == (slice(0, 16), slice(0, 8))


def test_jit_consumes_sharded_batch(num_ds, devices):
    # the actual consumer contract: jit with sharded inputs compiles + runs
    url, _ = num_ds
    mesh = data_parallel_mesh()
    reader = make_reader(url, shuffle_row_groups=False, schema_fields=["vec"])

    @jax.jit
    def step(v):
        return (v ** 2).sum()

    with JaxDataLoader(reader, batch_size=64, mesh=mesh) as loader:
        b = next(iter(loader))
        out = step(b["vec"])
    assert np.isfinite(float(out))


def test_loader_diagnostics_and_trace(num_ds, tmp_path):
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    url, _ = num_ds
    trace_dir = str(tmp_path / "jax_trace")
    with make_batch_reader(url, shuffle_row_groups=False,
                           num_epochs=1) as reader:
        with JaxDataLoader(reader, batch_size=8, fields=["idx", "vec"],
                           trace_dir=trace_dir) as loader:
            n = sum(1 for _ in loader)
            diag = loader.diagnostics
    assert n > 0
    assert diag["delivered_batches"] == n
    assert diag["prefetch_capacity"] >= 1
    assert "reader" in diag
    import os

    assert os.path.isdir(trace_dir) and os.listdir(trace_dir)  # trace written


def test_trace_flushed_on_exhaustion_without_stop(num_ds, tmp_path):
    # plain `for b in loader` with no context manager: exhausting the iterator
    # must stop the process-wide jax trace (else a later start_trace raises)
    import os

    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    url, _ = num_ds
    trace_dir = str(tmp_path / "jax_trace_exhaust")
    reader = make_batch_reader(url, shuffle_row_groups=False, num_epochs=1)
    loader = JaxDataLoader(reader, batch_size=8, fields=["idx"],
                           trace_dir=trace_dir)
    n = sum(1 for _ in loader)
    assert n > 0
    assert not loader._tracing
    assert os.path.isdir(trace_dir) and os.listdir(trace_dir)
    loader.stop()  # idempotent after exhaustion
    loader.join()


def test_device_shuffle_buffer_delivers_all_rows_shuffled(num_ds):
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    url, _ = num_ds

    def run(capacity, seed=3):
        with make_batch_reader(url, shuffle_row_groups=False,
                               reader_pool_type="serial", num_epochs=1) as r:
            with JaxDataLoader(r, batch_size=4, fields=["idx"],
                               device_shuffle_capacity=capacity,
                               device_shuffle_seed=seed) as loader:
                return [int(v) for b in loader for v in np.asarray(b["idx"])]

    with make_batch_reader(url, shuffle_row_groups=False,
                           reader_pool_type="serial", num_epochs=1) as r:
        with JaxDataLoader(r, batch_size=4, fields=["idx"]) as loader:
            plain = [int(v) for b in loader for v in np.asarray(b["idx"])]
    shuffled = run(4)
    # every row exactly once, order changed, deterministic per seed
    assert sorted(shuffled) == sorted(plain)
    assert shuffled != plain
    assert run(4) == shuffled
    assert run(4, seed=9) != shuffled


def test_device_shuffle_buffer_on_mesh(num_ds, devices):
    from jax.sharding import Mesh, PartitionSpec

    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    url, rows = num_ds
    total = len(rows)
    mesh = Mesh(np.array(devices).reshape(8), ("data",))
    with make_batch_reader(url, shuffle_row_groups=False,
                           reader_pool_type="serial", num_epochs=1) as r:
        with JaxDataLoader(r, batch_size=16, mesh=mesh,
                           shardings=PartitionSpec("data"), fields=["idx"],
                           device_shuffle_capacity=2, drop_last=False) as loader:
            seen = []
            for b in loader:
                assert b["idx"].sharding.spec == PartitionSpec("data") \
                    or "_valid_rows" in b
                seen.extend(int(v) for v in np.asarray(b["idx"])[
                    :b.get("_valid_rows", b["idx"].shape[0])])
    assert sorted(seen) == list(range(total))


def test_device_shuffle_rejects_host_fields(num_ds):
    from petastorm_tpu.errors import PetastormTpuError
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    url, _ = num_ds
    with make_batch_reader(url, num_epochs=1) as r:
        with pytest.raises(PetastormTpuError, match="host_fields"):
            JaxDataLoader(r, batch_size=4, fields=["idx"], host_fields=["tag"],
                          device_shuffle_capacity=2)


def test_device_shuffle_partial_fill_still_shuffles(num_ds):
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    url, _ = num_ds
    with make_batch_reader(url, shuffle_row_groups=False,
                           reader_pool_type="serial", num_epochs=1) as r:
        # capacity far beyond the stream: everything drains from warm-up
        with JaxDataLoader(r, batch_size=4, fields=["idx"],
                           device_shuffle_capacity=100,
                           device_shuffle_seed=5) as loader:
            got = [int(v) for b in loader for v in np.asarray(b["idx"])]
    assert sorted(got) == list(range(64))
    assert got != list(range(64))  # drained shuffled, not insertion order


def test_device_shuffle_tail_batch_stays_last(num_ds, devices):
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader

    url, rows = num_ds
    mesh = data_parallel_mesh()
    with make_batch_reader(url, shuffle_row_groups=False,
                           reader_pool_type="serial", num_epochs=1) as r:
        with JaxDataLoader(r, batch_size=24, mesh=mesh, drop_last=False,
                           fields=["idx"], device_shuffle_capacity=2,
                           device_shuffle_seed=7) as loader:
            batches = list(loader)
    # 64 rows / 24 = 2 full + 1 padded tail; the '_valid_rows' batch ends the
    # stream even though resident batches drained after it was produced
    assert [("_valid_rows" in b) for b in batches] == [False, False, True]
    seen = []
    for b in batches:
        n = b.get("_valid_rows", b["idx"].shape[0])
        seen.extend(int(v) for v in np.asarray(b["idx"])[:n])
    assert sorted(seen) == list(range(64))


def test_pad_to_bucket_bounded_shapes(tmp_path):
    """Multi-bucket pad policy (SURVEY.md section 7 hard part (d)): each batch
    lands on the smallest fitting bucket, bounding XLA recompiles."""
    schema = Schema("B", [Field("idx", np.int64),
                          Field("pts", np.float32, (None, 2))])
    url = str(tmp_path / "buckets")
    rng = np.random.default_rng(2)
    # cluster lengths per rowgroup (8 rows) so batches land in different
    # buckets: groups cycle small (<=8), mid (<=16), large (<=32)
    caps = [8, 16, 32]
    lengths = [int(rng.integers(1, caps[(i // 8) % 3] + 1)) for i in range(64)]
    write_dataset(url, schema,
                  [{"idx": i,
                    "pts": np.full((lengths[i], 2), i, dtype=np.float32)}
                   for i in range(64)], row_group_size_rows=8)
    buckets = [(8, 2), (16, 2), (32, 2)]
    reader = make_reader(url, shuffle_row_groups=False)
    with JaxDataLoader(reader, batch_size=8,
                       pad_shapes={"pts": buckets}, pad_values=-1.0) as loader:
        seen_shapes = set()
        for b in loader:
            shape = tuple(b["pts"].shape[1:])
            seen_shapes.add(shape)
            assert shape in set(buckets)
            # each row's real prefix is intact, padding is the pad value
            for k, i in enumerate(np.asarray(b["idx"])):
                row = np.asarray(b["pts"][k])
                n = lengths[int(i)]
                assert (row[:n] == float(i)).all()
                assert (row[n:] == -1.0).all()
    assert len(seen_shapes) > 1  # multiple buckets actually exercised


def test_pad_bucket_validation(num_ds):
    url, _ = num_ds
    reader = make_reader(url, schema_fields=["idx"])
    with pytest.raises(PetastormTpuError, match="share one rank"):
        JaxDataLoader(reader, batch_size=8,
                      pad_shapes={"idx": [(4,), (4, 2)]})
    reader.stop(); reader.join()

    reader2 = make_reader(url, shuffle_row_groups=False,
                          schema_fields=["idx", "vec"])
    with pytest.raises(PetastormTpuError, match="uniform batch shapes"):
        JaxDataLoader(reader2, batch_size=8,
                      pad_shapes={"vec": [(6,), (8,)]},
                      device_shuffle_capacity=2)
    reader2.stop(); reader2.join()


def test_valid_mask_field_full_and_partial(num_ds, devices):
    """valid_mask_field adds a globally-consistent per-row validity column:
    1.0 on real rows, 0.0 on the zero-padding of a partial final batch -
    the only pod-safe signal to weight losses by (host-local '_valid_rows'
    differs across hosts; see JaxDataLoader.drain docs)."""
    url, _ = num_ds
    mesh = data_parallel_mesh()
    reader = make_reader(url, shuffle_row_groups=False,
                         schema_fields=["idx", "vec"])
    with JaxDataLoader(reader, batch_size=24, mesh=mesh, drop_last=False,
                       valid_mask_field="mask") as loader:
        batches = list(loader)
    assert len(batches) == 3  # 64 rows = 24 + 24 + 16(+8 pad)
    for b in batches[:2]:
        assert isinstance(b["mask"], jax.Array)
        assert b["mask"].shape == (24,)
        assert np.asarray(b["mask"]).tolist() == [1.0] * 24
        # mask shards its only axis like the data fields shard their batch axis
        assert b["mask"].sharding.spec[0] == b["idx"].sharding.spec[0]
    tail = batches[-1]
    assert tail["_valid_rows"] == 16
    assert np.asarray(tail["mask"]).tolist() == [1.0] * 16 + [0.0] * 8
    # masked mean ignores the zero-padded rows
    vec = np.asarray(tail["vec"]).sum(axis=1)
    mask = np.asarray(tail["mask"])
    assert np.isclose((vec * mask).sum() / mask.sum(), vec[:16].mean())


def test_valid_mask_field_validation(num_ds):
    url, _ = num_ds
    reader = make_reader(url, schema_fields=["idx"])
    with pytest.raises(PetastormTpuError, match="only applies to mesh"):
        JaxDataLoader(reader, batch_size=8, valid_mask_field="mask")
    reader.stop(); reader.join()

    mesh = data_parallel_mesh()
    reader2 = make_reader(url, schema_fields=["idx", "vec"])
    with pytest.raises(PetastormTpuError, match="collides with a schema field"):
        JaxDataLoader(reader2, batch_size=8, mesh=mesh,
                      valid_mask_field="vec")
    reader2.stop(); reader2.join()


def test_valid_mask_rides_device_shuffle_buffer(tmp_path):
    """The mask column is a uniform device field, so it must ride the HBM
    exchange-shuffle buffer like any data field, and the held-back partial
    tail batch must still arrive LAST with its zero-mask padding."""
    schema = Schema("M", [Field("id", np.int64)])
    url = str(tmp_path / "ds")
    write_dataset(url, schema, [{"id": i} for i in range(72)],
                  row_group_size_rows=8)
    mesh = data_parallel_mesh()
    reader = make_reader(url, shuffle_row_groups=False)
    with JaxDataLoader(reader, batch_size=16, mesh=mesh,
                       shardings={"id": P("data")},
                       device_shuffle_capacity=2, device_shuffle_seed=1,
                       valid_mask_field="mask", drop_last=False) as loader:
        batches = list(loader)
    assert len(batches) == 5  # 4 full + the 8-row padded tail
    tail = batches[-1]
    assert tail["_valid_rows"] == 8
    assert np.asarray(tail["mask"]).tolist() == [1.0] * 8 + [0.0] * 8
    for b in batches[:-1]:
        assert np.asarray(b["mask"]).tolist() == [1.0] * 16
    ids = sorted(int(i) for b in batches
                 for i, m in zip(np.asarray(b["id"]), np.asarray(b["mask"]))
                 if m == 1.0)
    assert ids == list(range(72))


def test_valid_mask_transform_collision_raises(num_ds):
    """A transform_fn minting a field with the mask's name must fail loudly
    (the schema collision is caught at construction; this one can only
    surface at runtime)."""
    url, _ = num_ds
    mesh = data_parallel_mesh()
    reader = make_reader(url, schema_fields=["idx", "vec"])

    def sneaky(cols):
        cols["mask"] = np.ones_like(cols["idx"], dtype=np.float32)
        return cols

    with pytest.raises(PetastormTpuError, match="collides with"):
        with JaxDataLoader(reader, batch_size=8, mesh=mesh,
                           transform_fn=sneaky,
                           valid_mask_field="mask") as loader:
            next(iter(loader))
