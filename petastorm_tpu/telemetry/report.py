"""Human-readable bottleneck summary from a telemetry snapshot.

``render_pipeline_report`` is a pure function of ``Telemetry.snapshot()``
output, so a parent process can render a report from a child's JSON snapshot
(the benchmark CLI's ``--isolated`` mode) and tests can assert on stable
dict inputs rather than live registries.
"""

from __future__ import annotations

from typing import Dict, List

#: canonical pipeline order (SURVEY.md section 7).  Stages outside this list
#: (component-private sub-stages) render after the known ones.  ``service``
#: is the disaggregated-ingest client stage (result receive/decode for
#: ``make_reader(service_address=...)`` readers) - between ventilation and
#: the local decode path it replaces.
STAGE_ORDER = ("ventilate", "service", "decode", "transform",
               "host-assemble", "host-prep", "device-transfer")

#: queue-wait counters -> how the report explains them.  Queue-FULL waits
#: point the finger downstream (the stage after the queue is the bottleneck);
#: queue-EMPTY waits point upstream.
_QUEUE_WAITS = (
    ("queue.input_full_wait_s",
     "ventilator blocked on full input queue (workers saturated - healthy"
     " backpressure)"),
    ("queue.results_full_wait_s",
     "workers blocked on full results queue (consumer is the bottleneck)"),
    ("queue.results_empty_wait_s",
     "consumer starved on empty results queue (worker plane is the"
     " bottleneck)"),
)


def _stage_rows(snapshot: Dict) -> List[Dict]:
    counters = snapshot.get("counters", {})
    histograms = snapshot.get("histograms", {})
    names = {n.split(".", 2)[1] for n in counters
             if n.startswith("stage.") and n.endswith(".busy_s")}
    # stages registered ahead of their first execution
    # (Telemetry.register_stage, or a histogram minted by hedge_after='auto')
    # must still get a row - rendered as "no samples yet", never silently
    # omitted, so early --watch frames and short runs cannot misname the
    # dominant stage by eliding a late-starting one
    names |= {n.split(".", 2)[1] for n in histograms
              if n.startswith("stage.") and n.endswith(".latency_s")}
    ordered = [s for s in STAGE_ORDER if s in names]
    ordered += sorted(names - set(STAGE_ORDER))
    rows = []
    for stage in ordered:
        busy = counters.get(f"stage.{stage}.busy_s", 0.0)
        count = int(counters.get(f"stage.{stage}.count", 0))
        hist = histograms.get(f"stage.{stage}.latency_s")
        p50 = p99 = None
        if hist and hist.get("count"):
            p50 = _hist_quantile(hist, 0.5)
            p99 = _hist_quantile(hist, 0.99)
        rows.append({"stage": stage, "busy_s": busy, "count": count,
                     "mean_ms": (busy / count * 1e3) if count else 0.0,
                     "p50_s": p50, "p99_s": p99})
    return rows


def _hist_quantile(hist: Dict, q: float) -> float:
    total = hist["count"]
    rank = q * total
    seen = 0
    buckets = hist["buckets"]
    for i, c in enumerate(hist["counts"]):
        seen += c
        if seen >= rank:
            return buckets[min(i, len(buckets) - 1)]
    return buckets[-1]


def hist_quantile(hist: Dict, q: float) -> float:
    """Approximate quantile of a ``Histogram.snapshot()`` dict: the upper
    bound of the bucket holding the q-th observation (0.0 when empty).
    Public twin of the report's internal helper - the fleet aggregation
    plane derives per-worker and merged quantiles from wire-shipped
    snapshots with it."""
    if not hist or not hist.get("count"):
        return 0.0
    return _hist_quantile(hist, q)


def merge_hist_snapshots(snaps) -> Dict:
    """Merge fixed-bucket ``Histogram.snapshot()`` dicts element-wise.

    The registry's histograms use a fixed bucket shape precisely so
    snapshots from different processes are mergeable: counts add, sums
    add.  Snapshots whose bucket bounds differ from the first one's are
    skipped (a foreign/fuzzed frame must degrade coverage, not poison the
    merge).  Returns an empty-count snapshot when nothing merges.
    """
    buckets = None
    counts: List[int] = []
    total_sum = 0.0
    total_count = 0
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        b = snap.get("buckets")
        c = snap.get("counts")
        if not isinstance(b, (list, tuple)) or not isinstance(c, (list,
                                                                  tuple)):
            continue
        if buckets is None:
            buckets = list(b)
            counts = [0] * len(c)
        if list(b) != buckets or len(c) != len(counts):
            continue
        counts = [x + int(y) for x, y in zip(counts, c)]
        total_sum += float(snap.get("sum", 0.0))
        total_count += int(snap.get("count", 0))
    return {"buckets": buckets or [], "counts": counts,
            "sum": total_sum, "count": total_count}


def dominant_stage(snapshot: Dict) -> str:
    """Name of the stage with the most cumulative busy time ('' if none).
    Stages that are registered but have recorded no execution yet are not
    candidates - an early frame must say "nothing yet", not crown whichever
    zero-count stage happened to sort first."""
    rows = [r for r in _stage_rows(snapshot) if r["count"] > 0]
    if not rows:
        return ""
    return max(rows, key=lambda r: r["busy_s"])["stage"]


def render_pipeline_report(snapshot: Dict) -> str:
    """Render the stage-utilization / queue-time bottleneck summary."""
    wall = float(snapshot.get("uptime_s", 0.0)) or 1e-9
    counters = snapshot.get("counters", {})
    lines = ["== petastorm-tpu pipeline report ==",
             f"observed wall clock: {wall:.2f} s"]
    rows = _stage_rows(snapshot)
    if rows:
        lines.append(f"{'stage':<16} {'busy_s':>8} {'util%':>7} {'count':>7}"
                     f" {'mean_ms':>9} {'p50_ms':>8} {'p99_ms':>8}")
        for r in rows:
            if r["count"] == 0:
                # registered but not yet executed: a visible placeholder row
                # beats omission (the stage exists; it just hasn't run)
                lines.append(f"{r['stage']:<16} {'-':>8} {'-':>7} {'-':>7}"
                             f" {'-':>9} {'-':>8} {'-':>8}  (no samples yet)")
                continue
            p50 = f"{r['p50_s'] * 1e3:>8.1f}" if r["p50_s"] is not None else f"{'-':>8}"
            p99 = f"{r['p99_s'] * 1e3:>8.1f}" if r["p99_s"] is not None else f"{'-':>8}"
            lines.append(
                f"{r['stage']:<16} {r['busy_s']:>8.3f}"
                f" {100.0 * r['busy_s'] / wall:>6.1f}% {r['count']:>7d}"
                f" {r['mean_ms']:>9.2f} {p50} {p99}")
        sampled = [r for r in rows if r["count"] > 0]
        if sampled:
            best = max(sampled, key=lambda r: r["busy_s"])
            lines.append(
                f"dominant stage: {best['stage']}"
                f" ({best['busy_s']:.3f} s busy,"
                f" {100.0 * best['busy_s'] / wall:.1f}% of wall;"
                " util% can exceed 100 - stages run on parallel workers)")
        else:
            lines.append("dominant stage: (no samples yet)")
    else:
        lines.append("no stage samples recorded (telemetry enabled but no"
                     " instrumented work ran)")
    queue_lines = []
    for name, meaning in _QUEUE_WAITS:
        v = counters.get(name)
        if v:
            queue_lines.append(f"  {v:>8.3f} s  {meaning}")
    if queue_lines:
        lines.append("queue time:")
        lines.extend(queue_lines)
    # fault ledger: skipped/quarantined rowgroups, requeued work items,
    # transient-IO retries and liveness interventions (hung-worker kills,
    # hedges, circuit opens) get their own section - recurring weather must
    # be visible in the report, not only in scrolled-away log warnings
    faults = {n: v for n, v in counters.items()
              if n.startswith(("errors.", "io.retries", "liveness."))}
    if faults:
        lines.append("faults (skips / requeues / IO retries / liveness):")
        for n, v in sorted(faults.items()):
            lines.append(f"  {n} = {v:g}")
    interesting = {n: v for n, v in counters.items()
                   if not n.startswith(("stage.", "queue.", "errors.",
                                        "io.retries", "liveness."))}
    if interesting:
        lines.append("counters:")
        for n, v in sorted(interesting.items()):
            lines.append(f"  {n} = {v:g}")
    gauges = snapshot.get("gauges", {})
    if gauges:
        lines.append("gauges (last value):")
        for n, v in sorted(gauges.items()):
            lines.append(f"  {n} = {v:g}")
    return "\n".join(lines)
