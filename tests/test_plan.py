"""ReadPlan tests: determinism, sharding, row-drop splits.

Reference models: shard tests test_end_to_end.py:395,454 and the
normalize/row-drop logic reader.py:565-592.
"""

import numpy as np
import pytest

from petastorm_tpu.errors import NoDataAvailableError, PetastormTpuError
from petastorm_tpu.etl.metadata import RowGroupRef
from petastorm_tpu.plan import ReadPlan, WorkItem, _drop_slice


def _rgs(n, rows_each=10):
    return [RowGroupRef(path=f"/f{i // 4}.parquet", row_group=i % 4,
                        num_rows=rows_each, global_index=i) for i in range(n)]


def test_no_shuffle_is_sequential():
    plan = ReadPlan(_rgs(8), shuffle_row_groups=False)
    items = plan.epoch_items(0)
    assert [it.row_group.global_index for it in items] == list(range(8))


def test_shuffle_deterministic_per_seed_and_epoch():
    plan = ReadPlan(_rgs(32), shuffle_seed=7)
    e0a = [it.row_group.global_index for it in plan.epoch_items(0)]
    e0b = [it.row_group.global_index for it in plan.epoch_items(0)]
    e1 = [it.row_group.global_index for it in plan.epoch_items(1)]
    assert e0a == e0b            # reproducible
    assert e0a != e1             # reshuffled per epoch
    assert sorted(e0a) == sorted(e1) == list(range(32))
    other_seed = [it.row_group.global_index for it in ReadPlan(_rgs(32), shuffle_seed=8)
                  .epoch_items(0)]
    assert e0a != other_seed


def test_static_sharding_disjoint_and_complete():
    # reference: test_partition_multi_node (test_end_to_end.py:454)
    shards = [ReadPlan(_rgs(10), shard_index=i, shard_count=3, shuffle_seed=1,
                       shard_mode="static") for i in range(3)]
    per_shard = [{it.row_group.global_index for it in s.epoch_items(0)} for s in shards]
    assert set().union(*per_shard) == set(range(10))
    for i in range(3):
        for j in range(i + 1, 3):
            assert not (per_shard[i] & per_shard[j])
    # static: same membership every epoch
    assert per_shard[0] == {it.row_group.global_index for it in shards[0].epoch_items(5)}


def test_epoch_sharding_redeals_but_stays_disjoint():
    shards = [ReadPlan(_rgs(12), shard_index=i, shard_count=4, shuffle_seed=3,
                       shard_mode="epoch") for i in range(4)]
    for epoch in (0, 1):
        per_shard = [{it.row_group.global_index for it in s.epoch_items(epoch)}
                     for s in shards]
        assert set().union(*per_shard) == set(range(12))
        assert sum(len(p) for p in per_shard) == 12
    e0 = {it.row_group.global_index for it in shards[0].epoch_items(0)}
    e1 = {it.row_group.global_index for it in shards[0].epoch_items(1)}
    assert e0 != e1  # membership re-dealt across epochs (global shuffle)


def test_items_per_epoch_constant():
    plan = ReadPlan(_rgs(13), shard_index=1, shard_count=4, shard_mode="epoch",
                    shuffle_seed=0)
    lengths = {len(plan.epoch_items(e)) for e in range(5)}
    assert len(lengths) == 1


def test_too_many_shards_raises():
    # reference: test_too_many_shards (test_end_to_end.py:395)
    with pytest.raises(NoDataAvailableError):
        ReadPlan(_rgs(2), shard_index=0, shard_count=5)


def test_shard_args_validation():
    with pytest.raises(PetastormTpuError):
        ReadPlan(_rgs(4), shard_index=1)
    with pytest.raises(PetastormTpuError):
        ReadPlan(_rgs(4), shard_index=4, shard_count=4)


def test_row_drop_partitions_cover_all_rows():
    plan = ReadPlan(_rgs(3, rows_each=11), shuffle_row_drop_partitions=3,
                    shuffle_seed=2)
    items = plan.epoch_items(0)
    assert len(items) == 9
    by_rg = {}
    for it in items:
        by_rg.setdefault(it.row_group.global_index, []).append(it.row_slice())
    for slices in by_rg.values():
        covered = sorted(slices)
        assert covered[0][0] == 0 and covered[-1][1] == 11
        for (a, b), (c, d) in zip(covered, covered[1:]):
            assert b == c  # contiguous, non-overlapping
    assert plan.rows_per_epoch() == 33


def test_drop_slice_arithmetic():
    assert _drop_slice(10, 0, 3) == (0, 4)
    assert _drop_slice(10, 1, 3) == (4, 7)
    assert _drop_slice(10, 2, 3) == (7, 10)


def test_work_item_num_rows():
    rg = RowGroupRef("/f", 0, 10, 0)
    assert WorkItem(rg).num_rows == 10
    assert WorkItem(rg, (0, 4)).num_rows == 3
