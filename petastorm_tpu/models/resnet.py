"""ResNet-50 in flax.linen (the north-star ingest benchmark consumer).

Standard bottleneck ResNet v1.5 (stride-2 in the 3x3 conv).  BatchNorm runs in
inference mode by default so the forward pass is a pure function of (params,
images) - what the driver's single-chip compile check wants; training uses
``train=True`` with a mutable 'batch_stats' collection.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9,
                       epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(self.num_filters * 2 ** i, strides, conv, norm,
                                    nn.relu)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def ResNet50(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], num_classes=num_classes, dtype=dtype)
