"""``petastorm-tpu-diagnose``: one-command pipeline bottleneck diagnosis.

Runs a short telemetered read over a dataset (or a generated synthetic one)
and prints the ``pipeline_report()`` bottleneck summary - which stage
(ventilate / decode / transform) dominates, and whether queue time points at
the worker plane or the consumer.  Optionally exports the run's span
timeline as Chrome ``trace_event`` JSON for Perfetto.

Examples::

    petastorm-tpu-diagnose file:///data/imagenet --pool thread --workers 4
    petastorm-tpu-diagnose --synthetic --trace-out /tmp/trace.json
    python -m petastorm_tpu.tools.diagnose --synthetic --json

Deliberately jax-free (reader + pool plane only): it runs anywhere the host
pipeline runs, TPU attached or not.  For the device feed path use
``petastorm-tpu-throughput --method jax --telemetry``.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
from typing import List, Optional

from petastorm_tpu.telemetry import Telemetry, dominant_stage


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-diagnose",
        description="Run a short telemetered read and print the pipeline"
                    " bottleneck report")
    parser.add_argument("dataset_url", nargs="?", default=None,
                        help="dataset to read (omit with --synthetic)")
    parser.add_argument("--synthetic", action="store_true",
                        help="generate a small synthetic dataset in a temp"
                             " dir (default when no dataset_url is given)")
    parser.add_argument("--rows", type=int, default=200,
                        help="synthetic dataset size (--synthetic)")
    parser.add_argument("--row-group-size", type=int, default=20,
                        help="synthetic rowgroup size (--synthetic)")
    parser.add_argument("--method", default="batch", choices=("batch", "row"),
                        help="batch=make_batch_reader (columnar),"
                             " row=make_reader")
    parser.add_argument("-p", "--pool-type", default="thread",
                        choices=("thread", "process", "serial"))
    parser.add_argument("-w", "--workers-count", type=int, default=3)
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--max-batches", type=int, default=0,
                        help="stop after N rowgroup batches (0 = read all)")
    parser.add_argument("--trace-out", metavar="PATH", default=None,
                        help="write the run's Chrome trace_event JSON here"
                             " (open in Perfetto / chrome://tracing)")
    parser.add_argument("--json", action="store_true",
                        help="print the raw telemetry snapshot as JSON"
                             " instead of the human-readable report")
    parser.add_argument("--chaos", metavar="SPEC", default=None,
                        help="diagnose under injected faults (same spec"
                             " syntax as petastorm-tpu-throughput --chaos,"
                             " e.g. 'decode_fail_rate=0.05,"
                             "fail_first_reads=3')")
    parser.add_argument("--on-error", default="raise",
                        choices=("raise", "skip"),
                        help="reader failure policy; 'skip' quarantines"
                             " failing rowgroups (listed in the report)")
    return parser


def run_diagnosis(dataset_url: str, method: str = "batch",
                  pool_type: str = "thread", workers_count: int = 3,
                  num_epochs: int = 1, max_batches: int = 0,
                  telemetry: Optional[Telemetry] = None,
                  chaos=None, on_error: str = "raise") -> dict:
    """Read ``dataset_url`` with telemetry enabled; returns a result dict
    with ``rows``, ``batches``, ``snapshot``, ``report``,
    ``dominant_stage`` and the reader's fault ledger
    (``quarantined_rowgroups``) - also the programmatic entry the tests
    use."""
    from petastorm_tpu.reader import make_batch_reader, make_reader

    tele = telemetry or Telemetry()
    factory = make_batch_reader if method == "batch" else make_reader
    rows = 0
    batches = 0
    with factory(dataset_url, reader_pool_type=pool_type,
                 workers_count=workers_count, num_epochs=num_epochs,
                 shuffle_row_groups=False, telemetry=tele,
                 chaos=chaos, on_error=on_error) as reader:
        if method == "batch":
            for batch in reader.iter_batches():
                rows += batch.num_rows
                batches += 1
                if max_batches and batches >= max_batches:
                    break
        else:
            for _ in reader:
                rows += 1
        quarantined = reader.quarantined_rowgroups
    snapshot = tele.snapshot()
    return {"rows": rows, "batches": batches, "snapshot": snapshot,
            "report": tele.pipeline_report(),
            "dominant_stage": dominant_stage(snapshot),
            "quarantined_rowgroups": quarantined,
            "telemetry": tele}


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.dataset_url is None and not args.synthetic:
        args.synthetic = True
    tmpdir = None
    url = args.dataset_url
    try:
        if url is None:
            from petastorm_tpu.test_util.synthetic import create_test_dataset

            tmpdir = tempfile.mkdtemp(prefix="petastorm_tpu_diagnose_")
            create_test_dataset(tmpdir, num_rows=args.rows,
                                row_group_size_rows=args.row_group_size)
            url = tmpdir
        chaos = None
        if args.chaos:
            from petastorm_tpu.test_util.chaos import ChaosSpec

            chaos = ChaosSpec.parse(args.chaos)
        result = run_diagnosis(url, method=args.method,
                               pool_type=args.pool_type,
                               workers_count=args.workers_count,
                               num_epochs=args.num_epochs,
                               max_batches=args.max_batches,
                               chaos=chaos, on_error=args.on_error)
        if args.trace_out:
            result["telemetry"].export_chrome_trace(args.trace_out)
        if args.json:
            print(json.dumps({"rows": result["rows"],
                              "batches": result["batches"],
                              "dominant_stage": result["dominant_stage"],
                              "quarantined_rowgroups":
                                  result["quarantined_rowgroups"],
                              "snapshot": result["snapshot"]}))
        else:
            what = "synthetic dataset" if tmpdir else url
            print(f"read {result['rows']} rows"
                  + (f" in {result['batches']} rowgroup batches"
                     if args.method == "batch" else "")
                  + f" from {what}")
            print(result["report"])
            for entry in result["quarantined_rowgroups"]:
                print(f"quarantined: {entry['path']}#{entry['row_group']}"
                      f" (work item {entry['ordinal']}, {entry['kind']}"
                      f" error: {entry['error']})")
            if args.trace_out:
                print(f"chrome trace written to {args.trace_out}"
                      " (load in Perfetto / chrome://tracing)")
        return 0
    finally:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
