"""``petastorm-tpu-scaling``: worker-count scaling microbenchmark.

Prints one line per worker count (samples/sec over a synthetic jpeg dataset)
so operators can pick ``workers_count`` for THEIR host instead of trusting a
default - on low-core hosts fewer threads usually wins (docs/operations.md),
on real TPU host VMs the curve keeps climbing for a while.  Reference analog:
the pool sizing advice the reference buries in benchmark/throughput.py flags.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from typing import List, Optional


def build_dataset(url: str, rows: int, height: int, width: int) -> None:
    import numpy as np

    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema
    from petastorm_tpu.test_util.synthetic import synthetic_rgb_image

    schema = Schema("Scaling", [
        Field("id", np.int64),
        Field("image", np.uint8, (height, width, 3),
              CompressedImageCodec("jpeg", quality=85)),
    ])
    write_dataset(url, schema,
                  [{"id": i, "image": synthetic_rgb_image(i, height, width,
                                                          noise=5.0)}
                   for i in range(rows)],
                  row_group_size_rows=max(rows // 16, 1))


def measure(url: str, pool_type: str, workers: int, epochs: int) -> dict:
    from petastorm_tpu.reader import make_batch_reader

    n = 0
    with make_batch_reader(url, reader_pool_type=pool_type,
                           workers_count=workers, num_epochs=epochs,
                           shuffle_row_groups=False) as r:
        # timer starts AFTER reader/pool construction: process workers cost
        # seconds of spawn each, and the startup scales with worker count -
        # including it would invert the exact curve this tool exists to show
        t0 = time.perf_counter()
        for batch in r.iter_batches():
            n += batch.num_rows
        wall = time.perf_counter() - t0
        diag = r.diagnostics
    return {"pool": pool_type, "workers": workers,
            "samples_per_sec": round(n / wall, 2), "samples": n,
            "wall_s": round(wall, 3),
            "shm_transport": bool(diag.get("shm_transport", False))}


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-scaling",
        description="Measure reader throughput across worker counts")
    parser.add_argument("--workers", nargs="+", type=int,
                        default=[1, 2, 4, 8, 16])
    parser.add_argument("--pool-type", default="thread",
                        choices=("thread", "process"))
    parser.add_argument("--rows", type=int, default=512)
    parser.add_argument("--image-size", type=int, nargs=2, default=(128, 128),
                        metavar=("H", "W"))
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--dataset-url", default=None,
                        help="reuse an existing dataset instead of generating")
    args = parser.parse_args(argv)

    url = args.dataset_url
    tmp = None
    if url is None:
        tmp = tempfile.mkdtemp(prefix="pst_scaling_")
        url = tmp + "/ds"
        build_dataset(url, args.rows, *args.image_size)
    try:
        for w in args.workers:
            print(json.dumps(measure(url, args.pool_type, w, args.epochs)),
                  flush=True)
    finally:
        if tmp is not None:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
