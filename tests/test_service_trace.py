"""Fleet-wide distributed tracing + unified observability plane (ISSUE
19): trace-context propagation through the v2 wire, client-side span merge
and ``service.hop.*`` latency decomposition, heartbeat histogram/event
folding into the dispatcher's fleet aggregation point (``fleet?`` /
``events?`` frames, per-worker Prometheus, ``stats --watch``), and the
cross-process flight-recorder enrichment."""

import signal
import threading
import time

import numpy as np
import pytest

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.pool import VentilatedItem
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.service.client import ServiceExecutor
from petastorm_tpu.service.dispatcher import Dispatcher
from petastorm_tpu.service.protocol import WireItem, connect_frames
from petastorm_tpu.service.worker import ServiceWorker
from petastorm_tpu.telemetry import Telemetry
from petastorm_tpu.telemetry.export import (render_fleet_prometheus,
                                            render_prometheus)
from petastorm_tpu.telemetry.report import (hist_quantile,
                                            merge_hist_snapshots)
from petastorm_tpu.telemetry.sampler import (MetricsSampler,
                                             dump_flight_record,
                                             flight_record,
                                             load_flight_records)
from petastorm_tpu.telemetry.trace import TraceBuffer
from petastorm_tpu.test_util.matrix import (MatrixCell, run_cell,
                                            service_fleet)


def _wait_for(cond, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture
def int_dataset(tmp_path):
    url = str(tmp_path / "ds")
    schema = Schema("TraceInts", [Field("x", np.int64)])
    write_dataset(url, schema, [{"x": i} for i in range(200)],
                  row_group_size_rows=10)
    return url


def _traced_read(url, addr, tele, **kwargs):
    with make_batch_reader(url, service_address=addr,
                           shuffle_row_groups=False, telemetry=tele,
                           trace_items=1, **kwargs) as reader:
        rows = sorted(x for b in reader.iter_batches()
                      for x in b.columns["x"])
        diag = reader.diagnostics
    return rows, diag


# -- wire: trace context propagation ------------------------------------------

def test_wireitem_trace_context_roundtrip():
    """An armed item's ``tc`` survives encode -> to_wire -> from_wire with
    appended hop stamps intact; untraced items carry NO tc key (tracing is
    free on the wire when disarmed)."""
    item = VentilatedItem(7, ("payload", 7))
    plain = WireItem.encode(item)
    assert "tc" not in plain
    armed = WireItem.encode(item, trace_id=7)
    assert armed["tc"] == {"id": 7, "hops": []}
    wi = WireItem.from_wire(armed)
    wi.tc["hops"].append(["d", "recv", 0, 123456789, 0])
    wi.tc["hops"].append(["w0", "done", 0, 123456999, -42])
    out = WireItem.from_wire(wi.to_wire())
    assert out.tc["id"] == 7
    assert out.tc["hops"] == [["d", "recv", 0, 123456789, 0],
                              ["w0", "done", 0, 123456999, -42]]
    # a malformed tc (non-dict) is dropped, not fatal
    bad = dict(armed, tc=[1, 2])
    assert WireItem.from_wire(bad).tc is None


def test_trace_buffer_process_tracks():
    """Spans carrying a synthetic pid/proc render as their own named
    process track in the Chrome export, and ``tail()`` carries the proc."""
    buf = TraceBuffer(max_events=64)
    buf.add("local", "service.trace", 1000, 10)
    buf.add("remote", "service.trace", 2000, 20,
            pid=900001, proc="worker:w0", tid=1)
    trace = buf.chrome_trace()
    names = [e for e in trace["traceEvents"]
             if e.get("name") == "process_name"]
    assert any(e["pid"] == 900001
               and e["args"]["name"] == "worker:w0" for e in names)
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert {s["pid"] for s in spans} == {spans[0]["pid"], 900001}
    tail = buf.tail(10)
    assert any(t.get("proc") == "worker:w0" for t in tail)


def test_client_merges_hop_timeline_into_spans_and_hops():
    """Unit: ``_finish_trace`` on a canned returned timeline - remote
    stamps map through the handshake offset into client-clock spans on
    per-process tracks, a requeued attempt opens a SECOND annotated span
    tree under the SAME trace id, and the seven ``service.hop.*``
    histograms telescope exactly to the end-to-end latency."""
    tele = Telemetry()
    ex = ServiceExecutor("127.0.0.1:1", telemetry=tele, trace_items=1)
    ex._disp_clock_offset_ns = 1000  # dispatcher clock = ours + 1000
    ms = 1_000_000
    put, sent, recv, done = 0, 1 * ms, 20 * ms, 21 * ms
    ex._traces[5] = {"id": 5, "put_ns": put, "sent_ns": sent}
    woff = 500  # worker offset to the DISPATCHER clock
    d = 1000    # dispatcher-clock stamps: ours + 1000

    def w(t_ns):  # worker-clock stamp for client-clock time t_ns
        return t_ns + 1000 - woff

    hops = [
        # attempt 0: assigned to w0, which died mid-item
        ["d", "recv", 0, 2 * ms + d, 0],
        ["d", "assign", 0, 3 * ms + d, 0],
        ["w0", "recv", 0, w(4 * ms), woff],
        ["w0", "start", 0, w(5 * ms), woff],
        # attempt 1: requeued to w1, which completed
        ["d", "requeue", 1, 8 * ms + d, 0],
        ["d", "assign", 1, 9 * ms + d, 0],
        ["w1", "recv", 1, w(10 * ms), woff],
        ["w1", "start", 1, w(11 * ms), woff],
        ["w1", "done", 1, w(17 * ms), woff],
        ["d", "relay", 1, 18 * ms + d, 0],
    ]
    ex._finish_trace({"ordinal": 5, "attempt": 1},
                     {"id": 5, "hops": hops}, recv, done)
    spans = {}
    for name, _cat, _tid, start, dur, args, pid in tele.trace._events:
        spans.setdefault(name, []).append((start, dur, args, pid))
    # both attempts under one trace id, requeue annotated
    assert [a["trace_id"] for lst in spans.values()
            for (_s, _d, a, _p) in lst if "trace_id" in a] \
        == [5] * sum(len(v) for v in spans.values())
    assert spans["dispatch.queue"][0][2]["requeued"] is False
    assert spans["dispatch.requeue"][0][2]["requeued"] is True
    # offset mapping: the first dispatcher recv stamp lands at 2ms ours
    assert spans["dispatch.queue"][0][0] == 2 * ms
    # worker spans ride the worker's synthetic process track
    w0_pid = spans["worker.queue"][0][3]
    w1_pid = spans["worker.queue"][1][3]
    assert w0_pid != w1_pid
    assert spans["worker.exec"][0][3] == w1_pid  # only w1 reached exec
    assert spans["worker.exec"][0][0] == 11 * ms
    assert spans["worker.exec"][0][1] == 6 * ms
    # hop decomposition telescopes exactly to done - put
    hists = tele.snapshot()["histograms"]
    hop = {n[len("service.hop."):]: h["sum"]
           for n, h in hists.items() if n.startswith("service.hop.")}
    parts = ("client_serialize", "dispatcher_queue", "relay",
             "worker_queue", "worker_exec", "return_relay",
             "client_deserialize")
    assert set(parts) <= set(hop)
    assert sum(hop[p] for p in parts) == pytest.approx(hop["total"])
    assert hop["total"] == pytest.approx((done - put) / 1e9)
    # dispatcher_queue absorbed the dead first attempt (sent -> assign#1)
    assert hop["dispatcher_queue"] == pytest.approx(8 * ms / 1e9)


def test_trace_disarmed_by_default_and_validated():
    """Tracing is default-off (no tc on the wire, no registry) and
    ``trace_items`` without a service plane is a loud reader error."""
    ex = ServiceExecutor("127.0.0.1:1", telemetry=Telemetry())
    assert ex._trace_every == 0 and not ex._tracing
    assert ex.diagnostics["trace_items"] == 0
    # bool True -> 1-in-16 sampling
    ex16 = ServiceExecutor("127.0.0.1:1", telemetry=Telemetry(),
                           trace_items=True)
    assert ex16._trace_every == 16
    with pytest.raises(PetastormTpuError, match="trace_items"):
        make_batch_reader("file:///nonexistent", trace_items=4)


# -- end-to-end: one item's whole cross-process life --------------------------

def test_trace_end_to_end_merged_timeline(int_dataset):
    """Acceptance core: a traced read through a real fleet yields ONE
    Chrome trace whose spans cover >= 3 distinct processes (client,
    dispatcher, both workers), and the hop decomposition sums (within
    tolerance) to the observed end-to-end latency."""
    with service_fleet(n_workers=2) as (_disp, addr, _workers):
        tele = Telemetry()
        rows, diag = _traced_read(int_dataset, addr, tele)
    assert rows == list(range(200))
    assert diag["trace_items"] == 1
    trace = tele.trace.chrome_trace()
    spans = [e for e in trace["traceEvents"]
             if e.get("cat") == "service.trace" and e.get("ph") == "X"]
    procs = {e["pid"] for e in spans}
    assert len(procs) >= 3, f"expected client+dispatcher+worker: {procs}"
    named = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("name") == "process_name"}
    assert any(n.startswith("dispatcher@") for n in named), named
    assert any(n.startswith("worker:") for n in named), named
    kinds = {e["name"] for e in spans}
    assert {"service.item", "dispatch.queue", "worker.exec",
            "return.relay"} <= kinds
    hists = tele.snapshot()["histograms"]
    hop = {n[len("service.hop."):]: h
           for n, h in hists.items() if n.startswith("service.hop.")}
    parts = ("client_serialize", "dispatcher_queue", "relay",
             "worker_queue", "worker_exec", "return_relay",
             "client_deserialize")
    assert set(parts) <= set(hop), hop.keys()
    # every item recorded the full chain: all parts saw every traced item
    assert len({hop[p]["count"] for p in parts}) == 1
    total = hop["total"]["sum"]
    decomposed = sum(hop[p]["sum"] for p in parts)
    assert decomposed == pytest.approx(total, rel=0.05), \
        (decomposed, total)


@pytest.mark.slow
def test_trace_sigkill_requeue_same_trace_id(int_dataset):
    """Satellite: SIGKILL a worker subprocess mid-item - the merged trace
    for a requeued item shows the retry as a SECOND span tree under the
    SAME trace id, annotated as a requeue."""
    with service_fleet(n_workers=2, subprocess_workers=True) \
            as (disp, addr, procs):
        tele = Telemetry()
        done = threading.Event()
        out = {}

        def read():
            try:
                out["rows"] = _traced_read(int_dataset, addr, tele)[0]
            finally:
                done.set()

        t = threading.Thread(target=read, daemon=True)
        t.start()
        _wait_for(lambda: any(
            w.get("inflight", 0) > 0
            for w in disp.stats()["workers"].values()),
            timeout=30.0, what="a worker holding in-flight work")
        procs[0].send_signal(signal.SIGKILL)
        assert done.wait(timeout=120)
        t.join(timeout=5)
    assert out["rows"] == list(range(200))
    assert disp.stats()["counters"].get("service.requeued_items", 0) >= 1
    spans = [(name, args) for name, cat, _tid, _s, _d, args, _pid
             in tele.trace._events if cat == "service.trace"]
    requeues = [a for n, a in spans if n == "dispatch.requeue"]
    assert requeues, "requeued attempt must surface as its own span"
    tid = requeues[0]["trace_id"]
    # the same trace id carries BOTH attempts' trees
    attempts = {a.get("attempt") for n, a in spans
                if a.get("trace_id") == tid and "attempt" in a}
    assert len(attempts) >= 2, attempts
    # the dispatcher's requeue landed in the fleet event log too
    kinds = [e["kind"] for e in disp.events_tail()]
    assert "requeue" in kinds or "worker_gone" in kinds, kinds


def test_trace_rollover_span_on_dispatcher_failover(int_dataset):
    """Dispatcher loss mid-read: the reconnect window surfaces in the
    merged trace as an annotated ``service.rollover`` gap span."""
    from petastorm_tpu.retry import RetryPolicy
    from petastorm_tpu.test_util.matrix import recoverable_fleet

    with recoverable_fleet(n_workers=2) as fleet:
        tele = Telemetry()
        with make_batch_reader(int_dataset, service_address=fleet.address,
                               shuffle_row_groups=False, telemetry=tele,
                               trace_items=1) as reader:
            reader._executor._reconnect_policy = RetryPolicy(
                max_attempts=40, initial_backoff_s=0.05,
                backoff_multiplier=1.5, max_backoff_s=0.5)
            it = reader.iter_batches()
            rows = []
            for _ in range(4):
                rows.extend(next(it).columns["x"])
            fleet.restart_dispatcher(downtime_s=0.2)
            rows.extend(x for b in it for x in b.columns["x"])
    assert sorted(rows) == list(range(200))
    rollovers = [(dur, args) for name, cat, _tid, _s, dur, args, _pid
                 in tele.trace._events if name == "service.rollover"]
    assert rollovers, "reconnect must emit an annotated rollover span"
    dur, args = rollovers[0]
    assert dur > 0 and args["attempts"] >= 1
    assert "address" in args and "epoch" in args


def test_determinism_tracing_on_off_bit_identical(int_dataset):
    """Satellite: arming tracing must not perturb the delivered stream -
    tracing-on and tracing-off digests are bit-identical."""
    with service_fleet(n_workers=2) as (_disp, addr, _workers):
        plain = run_cell(int_dataset, 1234, MatrixCell(transport="service"),
                         num_epochs=2, service_address=addr)
        traced = run_cell(int_dataset, 1234,
                          MatrixCell(transport="service"),
                          num_epochs=2, service_address=addr,
                          reader_kwargs={"trace_items": 1,
                                         "telemetry": Telemetry()})
    assert traced.digest == plain.digest
    assert traced.rows == plain.rows


# -- fleet aggregation plane --------------------------------------------------

def test_fleet_stats_folds_heartbeat_hists_and_frames(int_dataset):
    """Worker heartbeats piggyback stage/hop histogram snapshots; the
    dispatcher folds them into ``fleet_stats()`` (per-worker + merged) and
    serves the whole thing over one-shot ``fleet?`` / ``events?`` /
    ``event`` frames."""
    disp = Dispatcher(telemetry=Telemetry(), heartbeat_timeout_s=5.0).start()
    addr = f"127.0.0.1:{disp.port}"
    workers = [ServiceWorker(addr, capacity=2, name=f"fw{i}",
                             heartbeat_interval_s=0.2,
                             telemetry=Telemetry())
               for i in range(2)]
    threads = [threading.Thread(target=w.run, daemon=True) for w in workers]
    for t in threads:
        t.start()
    try:
        _wait_for(lambda: len(disp.stats()["workers"]) == 2)
        rows, _diag = _traced_read(int_dataset, addr, Telemetry())
        assert rows == list(range(200))
        _wait_for(lambda: any(
            w.get("hists") for w in disp.fleet_stats()["workers"].values()),
            what="heartbeat histogram fold")
        fleet = disp.fleet_stats()
        assert set(fleet["workers"]) == {"fw0", "fw1"}
        some = [w for w in fleet["workers"].values() if w["hists"]]
        assert some and all(
            {"count", "p50_s", "p99_s"} <= set(next(iter(w["hists"]
                                                         .values())))
            for w in some)
        merged = fleet["merged_hists"]
        assert merged, "fleet-merged histograms must exist"
        name, m = next(iter(merged.items()))
        assert m["count"] > 0 and "snapshot" in m
        # heartbeat counter deltas folded fleet-wide (prefix stripped)
        assert fleet["fleet_counters"].get("worker.rowgroups_decoded",
                                           0) > 0, fleet["fleet_counters"]
        # one-shot frames
        conn = connect_frames(("127.0.0.1", disp.port), timeout=5.0)
        try:
            conn.send({"t": "fleet?"})
            reply = conn.recv(timeout=5.0)
        finally:
            conn.close()
        assert reply["t"] == "fleet"
        assert set(reply["fleet"]["workers"]) == {"fw0", "fw1"}
        conn = connect_frames(("127.0.0.1", disp.port), timeout=5.0)
        try:
            conn.send({"t": "event", "kind": "autoscale.scale_up",
                       "src": "autoscale", "spawned": 1})
            assert conn.recv(timeout=5.0)["t"] == "event_ok"
        finally:
            conn.close()
        conn = connect_frames(("127.0.0.1", disp.port), timeout=5.0)
        try:
            conn.send({"t": "events?", "n": 8})
            events = conn.recv(timeout=5.0)["events"]
        finally:
            conn.close()
        assert any(e["kind"] == "autoscale.scale_up"
                   and e["src"] == "autoscale" for e in events)
    finally:
        for w in workers:
            w.stop()
        disp.stop()
        disp.join()


def test_event_log_sanitizes_peer_events():
    """A peer cannot bloat the bounded fleet log: non-scalar fields drop,
    strings truncate, field count caps at 8."""
    disp = Dispatcher(telemetry=Telemetry())
    try:
        disp._on_peer_event({"t": "event", "kind": "x" * 100,
                             "src": "rogue", "long": "y" * 500,
                             "nested": {"a": 1}, "token": "secret",
                             **{f"f{i}": i for i in range(12)}})
        ev = disp.events_tail()[-1]
        assert len(ev["kind"]) == 64
        assert len(ev["long"]) == 200
        assert "nested" not in ev and "token" not in ev
        assert len([k for k in ev if k not in ("ts", "src", "kind")]) <= 8
        # junk is ignored outright
        disp._on_peer_event({"t": "event"})
        disp._on_peer_event("not a dict")
        assert disp.events_tail()[-1] is ev
    finally:
        disp.stop()
        disp.join()


def test_stats_ha_section_reports_standby_sync():
    """Satellite: ``stats()`` carries the HA sync view - role, fencing
    epoch, journal position, and per-standby lag."""
    disp = Dispatcher(telemetry=Telemetry())
    try:
        ha = disp.stats()["ha"]
        assert ha["role"] == "primary"
        assert ha["epoch"] == disp.epoch
        assert ha["journal_seq"] >= 0
        assert ha["standbys"] == {}
        # a subscribed standby surfaces with its lag (jseq - synced_seq)
        disp._standby_feeds["127.0.0.1:9999"] = max(
            0, ha["journal_seq"] - 3)
        lagged = disp.stats()["ha"]["standbys"]["127.0.0.1:9999"]
        assert lagged["standby_lag_items"] == min(3, ha["journal_seq"])
        assert "synced_seq" in lagged
    finally:
        disp.stop()
        disp.join()


# -- histogram merge / quantile units -----------------------------------------

def test_merge_hist_snapshots_and_quantile():
    tele = Telemetry()
    h = tele.histogram("service.hop.worker_exec")
    for v in (0.001, 0.002, 0.004, 0.008):
        h.record(v)
    snap = tele.snapshot()["histograms"]["service.hop.worker_exec"]
    merged = merge_hist_snapshots([snap, snap])
    assert merged["count"] == 2 * snap["count"]
    assert merged["sum"] == pytest.approx(2 * snap["sum"])
    assert hist_quantile(merged, 0.5) == pytest.approx(
        hist_quantile(snap, 0.5))
    assert hist_quantile({}, 0.5) == 0.0
    # mismatched bucket bounds are skipped, not crashed on
    other = dict(snap, buckets=[1.0, 2.0], counts=[1, 1, 1])
    still = merge_hist_snapshots([snap, other])
    assert still["count"] == snap["count"]
    assert merge_hist_snapshots([]) == {"buckets": [], "counts": [],
                                        "sum": 0.0, "count": 0} \
        or merge_hist_snapshots([])["count"] == 0


# -- exporters / renderers ----------------------------------------------------

def test_prometheus_exposes_hop_families():
    tele = Telemetry()
    tele.histogram("service.hop.worker_exec").record(0.004)
    tele.histogram("service.hop.total").record(0.01)
    body = render_prometheus(tele.snapshot())
    assert 'petastorm_tpu_service_hop_ops_total{hop="worker_exec"} 1' \
        in body
    assert 'petastorm_tpu_service_hop_latency_seconds{hop="worker_exec"' \
        in body
    assert 'quantile="0.99"' in body


def test_render_fleet_prometheus_per_worker_labels():
    fleet = {
        "epoch": 3,
        "workers": {
            "w0": {"busy": 1, "capacity": 2, "inflight": 1,
                   "heartbeat_age_s": 0.4,
                   "counters": {"service.fleet.worker.items_completed": 9},
                   "hists": {"service.hop.worker_exec":
                             {"count": 9, "p50_s": 0.004, "p99_s": 0.02}}},
            "w1": {"busy": 0, "capacity": 2, "inflight": 0,
                   "heartbeat_age_s": 0.1, "counters": {}, "hists": {}},
        },
        "merged_hists": {"service.hop.worker_exec":
                         {"count": 9, "p50_s": 0.004, "p99_s": 0.02}},
        "fleet_counters": {"service.fleet.worker.items_completed": 9},
    }
    body = render_fleet_prometheus(fleet)
    assert 'petastorm_tpu_fleet_worker_up{worker="w0"} 1' in body
    assert 'petastorm_tpu_fleet_worker_up{worker="w1"} 1' in body
    assert 'petastorm_tpu_fleet_worker_counter_total{worker="w0"' in body
    assert ('petastorm_tpu_fleet_worker_latency_seconds{worker="w0",'
            'hist="service.hop.worker_exec",quantile="0.5"}') in body
    assert 'petastorm_tpu_fleet_latency_seconds{' in body
    assert "petastorm_tpu_fleet_epoch 3" in body
    assert render_fleet_prometheus({}) == ""


def test_render_fleet_frame_from_canned_dicts():
    from petastorm_tpu.service.cli import render_fleet_frame

    stats = {"ha": {"role": "primary", "epoch": 2, "journal_seq": 40,
                    "standbys": {"sb": {"synced_seq": 37,
                                        "standby_lag_items": 3}}}}
    fleet = {
        "epoch": 2, "uptime_s": 12.0,
        "workers": {"w0": {"busy": 1, "capacity": 2, "inflight": 1,
                           "heartbeat_age_s": 0.3, "draining": False,
                           "counters": {"service.fleet.worker"
                                        ".items_completed": 100},
                           "hists": {"service.hop.worker_exec":
                                     {"count": 10, "p50_s": 0.004,
                                      "p99_s": 0.02}}}},
        "merged_hists": {"service.hop.worker_exec":
                         {"count": 10, "p50_s": 0.004, "p99_s": 0.02}},
        "fleet_counters": {"service.fleet.worker.items_completed": 100},
        "events": [{"ts": 1.0, "src": "autoscale",
                    "kind": "autoscale.scale_up", "spawned": 1}],
        "scaling": {"verdict": "hold"},
    }
    prev = {"fleet_counters": {"service.fleet.worker.items_completed": 50}}
    frame = render_fleet_frame(stats, fleet, prev_fleet=prev, dt_s=2.0,
                               elapsed_s=4.0)
    assert "petastorm-tpu fleet" in frame and "workers=1" in frame
    assert "primary" in frame and "lag" in frame
    assert "w0" in frame and "4.0" in frame  # exec p50 in ms
    assert "worker_exec" in frame
    assert "autoscale.scale_up" in frame
    # rates line from counter deltas: (100-50)/2s = 25/s
    assert "25.0" in frame
    # unreachable probes render a degraded frame, not a crash
    assert "workers=0" in render_fleet_frame(None, None)


def test_diagnose_watch_renders_hop_line():
    from petastorm_tpu.tools.diagnose import render_watch_frame

    point = {"dt_s": 1.0, "rates": {}, "counters": {},
             "hops": {"worker_exec": {"count": 4, "p50_s": 0.004,
                                      "p99_s": 0.02},
                      "total": {"count": 4, "p50_s": 0.01,
                                "p99_s": 0.05}}}
    frame = render_watch_frame(point)
    assert "hops p50" in frame
    assert "worker_exec=4.0ms" in frame
    assert "total=10.0ms" in frame
    # hopless points render no hops line
    assert "hops p50" not in render_watch_frame({"dt_s": 1.0})


# -- sampler point + flight-record enrichment ---------------------------------

def test_sampler_point_hops_and_flight_record_fleet_events(tmp_path):
    tele = Telemetry()
    sampler = MetricsSampler(tele, interval_s=60.0)
    sampler.sample_now()  # establishes the baseline snapshot
    tele.histogram("service.hop.worker_exec").record(0.004)
    time.sleep(0.005)     # sample_now skips sub-millisecond intervals
    point = sampler.sample_now()
    assert point["hops"]["worker_exec"]["count"] == 1
    assert point["hops"]["worker_exec"]["p50_s"] > 0
    events = [{"ts": 1.0, "src": "dispatcher", "kind": "item_requeued",
               "ordinal": 3}]
    record = flight_record(sampler, reason="test", fleet_events=events)
    assert record["fleet_events"] == events
    path = dump_flight_record(record, str(tmp_path / "fr.jsonl"))
    loaded = load_flight_records(path)[-1]
    assert loaded["fleet_events"] == events
    assert loaded["reason"] == "test"


def test_flight_record_on_failure_carries_fleet_events(int_dataset):
    """The crash-artifact path end to end: a terminal service failure
    fetches the dispatcher's event tail into the reader's flight record."""
    with service_fleet(n_workers=2) as (disp, addr, _workers):
        disp._event("requeue", client="c0", ordinal=7, attempt=1)
        from petastorm_tpu.test_util.chaos import ChaosSpec

        with pytest.raises(Exception):  # noqa: B017 - any terminal failure
            with make_batch_reader(
                    int_dataset, service_address=addr,
                    shuffle_row_groups=False, telemetry=Telemetry(),
                    chaos=ChaosSpec(decode_fail_ordinals=tuple(range(20))),
                    on_error="raise") as reader:
                list(reader.iter_batches())
        record = reader._flight_record
        assert record is not None
        kinds = [e["kind"] for e in record["fleet_events"]]
        assert "requeue" in kinds
