"""The committed API reference must match the live package.

docs/api/*.md is generated (docs/gen_api_reference.py); this test regenerates
into a tmp dir and diffs against the committed copy, so a public signature or
docstring change without a doc regeneration fails CI with a actionable
message.  It also caps the number of undocumented public symbols at zero.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "docs"))


def test_committed_api_docs_are_current(tmp_path):
    from gen_api_reference import generate

    committed_dir = os.path.join(REPO, "docs", "api")
    assert os.path.isdir(committed_dir), "docs/api missing - run" \
        " python docs/gen_api_reference.py"
    written = generate(str(tmp_path))
    fresh = {os.path.basename(p) for p in written}
    committed = {n for n in os.listdir(committed_dir) if n.endswith(".md")}
    assert fresh == committed, (
        "docs/api file set is stale - run python docs/gen_api_reference.py")
    stale = []
    for name in sorted(fresh):
        with open(tmp_path / name) as f:
            new = f.read()
        with open(os.path.join(committed_dir, name)) as f:
            old = f.read()
        if new != old:
            stale.append(name)
    assert not stale, (f"docs/api is stale for {stale} - run"
                       " python docs/gen_api_reference.py")


def test_every_public_symbol_is_documented():
    committed_dir = os.path.join(REPO, "docs", "api")
    undocumented = []
    for name in sorted(os.listdir(committed_dir)):
        if not name.endswith(".md"):
            continue
        with open(os.path.join(committed_dir, name)) as f:
            text = f.read()
        count = text.count("*(undocumented)*")
        if count:
            undocumented.append((name, count))
    assert not undocumented, (
        f"public symbols missing docstrings: {undocumented}")
