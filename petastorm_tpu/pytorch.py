"""PyTorch delivery layer: reader -> shuffled batches of torch tensors.

Reference parity: petastorm/pytorch.py (367 LoC) - dtype promotions for torch
(pytorch.py:39-69), decimal-friendly collate (pytorch.py:72-94), LoaderBase
iteration guard/error latch (pytorch.py:102-127), DataLoader with a row-level
shuffling buffer (pytorch.py:130-254) and BatchedDataLoader with whole-batch
tensor ops + optional transform_fn (pytorch.py:257-367).

Design difference: the reference shuffles *python row objects* (or transposes
batched readers row-wise, pytorch.py:204-214) and re-collates per batch.  Here
the pipeline is columnar end-to-end: ColumnBatches land in the vectorized
numpy shuffling buffer (petastorm_tpu/shuffle.py) and every emitted batch is a
dict of torch tensors created zero-copy via ``torch.from_numpy``.  DataLoader
and BatchedDataLoader therefore share one engine; BatchedDataLoader adds the
whole-batch ``transform_fn`` hook (e.g. ``lambda b: {k: v.to(dev) ...}``).
"""

from __future__ import annotations

import decimal
from typing import Callable, Dict, Optional

import numpy as np
import torch

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.shuffle import (NoopShufflingBuffer, RandomShufflingBuffer,
                                   iter_batched)

# numpy dtypes torch cannot represent -> widened dtype (reference pytorch.py:39-56)
_TORCH_PROMOTIONS = {
    np.dtype(np.uint16): np.dtype(np.int32),
    np.dtype(np.uint32): np.dtype(np.int64),
    np.dtype(np.uint64): np.dtype(np.int64),
}


def _sanitize_column(name: str, col: np.ndarray) -> np.ndarray:
    """Promote dtypes torch lacks; reject strings (reference pytorch.py:57-69)."""
    if col.dtype == object:
        return col
    if col.dtype.kind in "US":
        raise TypeError(
            f"Field {name!r} is a string array: strings are not supported by"
            " torch tensors (reference contract, pytorch.py:61-66). Exclude it"
            " via schema_fields or transform it to a numeric type.")
    promoted = _TORCH_PROMOTIONS.get(col.dtype)
    if promoted is not None:
        return col.astype(promoted)
    return col


def _column_to_torch(name: str, col: np.ndarray):
    """One column -> torch tensor (fixed shape) or list (variable/object rows)."""
    col = _sanitize_column(name, col)
    if col.dtype != object:
        return torch.from_numpy(np.ascontiguousarray(col))
    out = []
    for value in col:
        if isinstance(value, decimal.Decimal):
            out.append(float(value))
        elif isinstance(value, str):
            raise TypeError(
                f"Field {name!r} contains strings, unsupported by torch"
                " (reference contract, pytorch.py:61-66)")
        elif isinstance(value, np.ndarray):
            out.append(torch.from_numpy(
                np.ascontiguousarray(_sanitize_column(name, value))))
        else:
            out.append(value)
    if out and isinstance(out[0], float) and all(
            isinstance(v, float) for v in out):
        return torch.tensor(out, dtype=torch.float64)
    return out


def decimal_friendly_collate(batch):
    """Collate that turns ``decimal.Decimal`` into floats before stacking
    (reference pytorch.py:72-94); useful with hand-rolled row loops."""
    if isinstance(batch, decimal.Decimal):
        return float(batch)
    if isinstance(batch, (list, tuple)) and batch and isinstance(
            batch[0], decimal.Decimal):
        return torch.tensor([float(v) for v in batch], dtype=torch.float64)
    if isinstance(batch, (list, tuple)) and batch and isinstance(batch[0], dict):
        return {k: decimal_friendly_collate([r[k] for r in batch])
                for k in batch[0]}
    from torch.utils.data._utils.collate import default_collate
    return default_collate(batch)


class LoaderBase:
    """Single-pass iteration guard + error latch (reference pytorch.py:102-127)."""

    def __init__(self):
        self._in_iter: Optional[bool] = None
        self._error: Optional[BaseException] = None

    def __iter__(self):
        if self._error is not None:
            raise RuntimeError(
                "Cannot start a new epoch: a previous iteration failed"
            ) from self._error
        if self._in_iter:
            raise RuntimeError("Loader is already being iterated")
        self._in_iter = True
        try:
            yield from self._iter_impl()
        except Exception as exc:
            self._error = exc
            raise
        finally:
            self._in_iter = False

    def _iter_impl(self):
        raise NotImplementedError


class DataLoader(LoaderBase):
    """Shuffling, batching torch loader over a petastorm_tpu Reader.

    Yields dicts ``{field: torch.Tensor | list}`` of ``batch_size`` rows.
    ``shuffling_queue_capacity`` > 0 enables the row-level random buffer with a
    ``min_after_retrieve`` decorrelation floor at half capacity (reference
    shuffling_queue_capacity/min_after_dequeue, pytorch.py:143-189).

    NGram readers yield nested ``{offset: {field: tensor}}`` window batches
    (reference collates window dicts the same way, pytorch.py:130-254);
    ``stack_timesteps=True`` readers keep the flat dict - their stacked
    fields are already ``(batch, k, ...)`` tensors.
    """

    def __init__(self, reader, batch_size: int = 1,
                 shuffling_queue_capacity: int = 0,
                 seed: Optional[int] = None,
                 collate_fn: Optional[Callable[[Dict], Dict]] = None):
        super().__init__()
        if getattr(reader, "device_decode_fields", None):
            raise PetastormTpuError(
                f"fields {reader.device_decode_fields} use"
                " decode_placement='device' (raw jpeg bytes finished on-chip"
                " by the jax loader); torch loaders need"
                " decode_placement='host'")
        if batch_size < 1:
            raise PetastormTpuError("batch_size must be >= 1")
        self.reader = reader
        self.batch_size = batch_size
        self.shuffling_queue_capacity = shuffling_queue_capacity
        self._seed = seed
        self._collate_fn = collate_fn
        #: non-stacked ngram readers emit '<offset>/<field>' columns; collate
        #: them back into {offset: {field: tensor}} like the reference's row
        #: collate does for window dicts (pytorch.py:130-254, collate :72-94)
        ngram = getattr(reader, "ngram", None)
        self._ngram_offsets = (ngram.offsets if ngram is not None
                               and not ngram.stack_timesteps else None)

    # -- engine ---------------------------------------------------------------

    def _make_buffer(self):
        if self.shuffling_queue_capacity > 0:
            capacity = max(self.shuffling_queue_capacity, self.batch_size)
            # seed-stable delivery (docs/operations.md "Reproducibility"):
            # under deterministic='seed' an unseeded buffer derives its RNG
            # from the reader's seed root, exactly like the jax loader; an
            # explicit seed wins
            from petastorm_tpu.seeding import reader_buffer_seed

            return RandomShufflingBuffer(
                capacity=capacity + self.batch_size,
                min_after_retrieve=capacity // 2,
                seed=reader_buffer_seed(self.reader,
                                        "pytorch.shuffle_buffer",
                                        self._seed))
        return NoopShufflingBuffer()

    def _transform_batch(self, batch: Dict):
        return batch

    def _iter_impl(self):
        source = self.reader.iter_batches()
        for batch in iter_batched(source, self._make_buffer(), self.batch_size):
            yield self._emit(batch)

    def _emit(self, batch: ColumnBatch) -> Dict:
        out = {name: _column_to_torch(name, col)
               for name, col in batch.columns.items()}
        if self._ngram_offsets is not None:
            from petastorm_tpu.ngram import NGRAM_KEY_SEP

            nested: Dict[int, Dict] = {off: {} for off in self._ngram_offsets}
            for key, value in out.items():
                off, _, field = key.partition(NGRAM_KEY_SEP)
                nested[int(off)][field] = value
            out = nested
        if self._collate_fn is not None:
            out = self._collate_fn(out)
        return self._transform_batch(out)

    # -- lifecycle ------------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.reader.stop()
        self.reader.join()

    def __len__(self):
        raise TypeError("DataLoader length is not known up front")


class BatchedDataLoader(DataLoader):
    """DataLoader + whole-batch ``transform_fn`` (reference pytorch.py:257-367).

    The reference needed a separate class because its row DataLoader moved
    python objects one at a time; the columnar engine here is already batched,
    so this subclass only adds the transform hook (e.g. device placement:
    ``transform_fn=lambda b: {k: v.cuda() for k, v in b.items()}``).
    """

    def __init__(self, reader, batch_size: int = 1,
                 shuffling_queue_capacity: int = 0,
                 seed: Optional[int] = None,
                 transform_fn: Optional[Callable[[Dict], Dict]] = None):
        super().__init__(reader, batch_size=batch_size,
                         shuffling_queue_capacity=shuffling_queue_capacity,
                         seed=seed)
        self._transform_fn = transform_fn

    def _transform_batch(self, batch: Dict):
        if self._transform_fn is not None:
            return self._transform_fn(batch)
        return batch
