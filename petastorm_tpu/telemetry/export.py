"""Metrics export: Prometheus text exposition + JSONL push sink.

Pull: :class:`MetricsExportServer` serves ``GET /metrics`` in Prometheus
text-exposition format (version 0.0.4) from a stdlib ``http.server`` thread
bound to **localhost only** by default - the pipeline's counters, gauges,
per-stage cumulative totals/quantiles and (when a sampler is attached)
per-interval stage rates and p50/p99, including the ``errors.*`` /
``liveness.*`` fault counters.  Wired into readers via
``make_reader(metrics_port=)`` / ``PETASTORM_TPU_METRICS_PORT=`` (``0`` =
ephemeral; the bound port is ``reader.metrics_server.port``).

Push: :func:`write_jsonl` appends sampled points to a JSONL file for
airgapped runs where nothing can scrape.

Name mapping (mechanical, stable - the golden test pins it):

* counter ``errors.skipped_rowgroups`` ->
  ``petastorm_tpu_errors_skipped_rowgroups_total``
* gauge ``pool.results_queue_depth`` ->
  ``petastorm_tpu_pool_results_queue_depth``
* stage instruments fold into labeled families:
  ``petastorm_tpu_stage_busy_seconds_total{stage="decode"}``,
  ``petastorm_tpu_stage_ops_total{stage="decode"}``,
  ``petastorm_tpu_stage_latency_seconds{stage="decode",quantile="0.99"}``
  (cumulative), plus - with a sampler -
  ``petastorm_tpu_stage_rate_per_second{stage=...}`` and
  ``petastorm_tpu_stage_interval_latency_seconds{stage=...,quantile=...}``
  over the last sampled interval.
"""

from __future__ import annotations

import json
import logging
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, List, Optional

from petastorm_tpu.telemetry.report import _hist_quantile

logger = logging.getLogger(__name__)

PREFIX = "petastorm_tpu"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
_STAGE_RE = re.compile(r"^stage\.([^.]+)\.(busy_s|count|latency_s)$")
_HOP_RE = re.compile(r"^service\.hop\.([^.]+)$")
_LABEL_RE = re.compile(r"[^a-zA-Z0-9_\-.:@]")


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _fmt(value: float) -> str:
    # integers print bare (Prometheus accepts either; bare ints are stable)
    if float(value) == int(value):
        return str(int(value))
    return repr(float(value))


def render_prometheus(snapshot: Dict,
                      sampler_point: Optional[Dict] = None) -> str:
    """Render a ``Telemetry.snapshot()`` (plus an optional sampler point for
    per-interval stage rates) as Prometheus text exposition.  Pure function
    of its inputs; ordering is deterministic so the format can be golden-
    tested."""
    lines: List[str] = []

    def family(name: str, mtype: str, help_text: str,
               samples: Iterable) -> None:
        rendered = list(samples)
        if not rendered:
            return
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        lines.extend(rendered)

    family(f"{PREFIX}_uptime_seconds", "gauge",
           "Seconds since this pipeline's telemetry registry was created.",
           [f"{PREFIX}_uptime_seconds "
            f"{_fmt(float(snapshot.get('uptime_s', 0.0)))}"])

    counters = snapshot.get("counters", {})
    stage_busy: Dict[str, float] = {}
    stage_count: Dict[str, float] = {}
    plain_counters: Dict[str, float] = {}
    for name, value in counters.items():
        m = _STAGE_RE.match(name)
        if m and m.group(2) == "busy_s":
            stage_busy[m.group(1)] = value
        elif m and m.group(2) == "count":
            stage_count[m.group(1)] = value
        else:
            plain_counters[name] = value

    for name in sorted(plain_counters):
        metric = f"{PREFIX}_{_sanitize(name)}_total"
        family(metric, "counter", f"Cumulative total of {name}.",
               [f"{metric} {_fmt(plain_counters[name])}"])

    gauges = snapshot.get("gauges", {})
    for name in sorted(gauges):
        metric = f"{PREFIX}_{_sanitize(name)}"
        family(metric, "gauge", f"Last observed value of {name}.",
               [f"{metric} {_fmt(gauges[name])}"])

    histograms = snapshot.get("histograms", {})
    stage_hists = {}
    hop_hists = {}
    for name, hist in histograms.items():
        m = _STAGE_RE.match(name)
        hm = _HOP_RE.match(name)
        if m and m.group(2) == "latency_s":
            stage_hists[m.group(1)] = hist
        elif hm:
            hop_hists[hm.group(1)] = hist
        else:
            metric = f"{PREFIX}_{_sanitize(name)}"
            family(metric, "summary", f"Distribution of {name}.",
                   [f"{metric}{{quantile=\"0.5\"}} "
                    f"{_fmt(_hist_quantile(hist, 0.5) if hist['count'] else 0)}",
                    f"{metric}{{quantile=\"0.99\"}} "
                    f"{_fmt(_hist_quantile(hist, 0.99) if hist['count'] else 0)}",
                    f"{metric}_sum {_fmt(hist['sum'])}",
                    f"{metric}_count {_fmt(hist['count'])}"])

    stages = sorted(set(stage_busy) | set(stage_count) | set(stage_hists))
    if stages:
        family(f"{PREFIX}_stage_busy_seconds_total", "counter",
               "Cumulative busy seconds per pipeline stage.",
               [f"{PREFIX}_stage_busy_seconds_total{{stage=\"{s}\"}} "
                f"{_fmt(stage_busy.get(s, 0.0))}" for s in stages])
        family(f"{PREFIX}_stage_ops_total", "counter",
               "Cumulative executions per pipeline stage.",
               [f"{PREFIX}_stage_ops_total{{stage=\"{s}\"}} "
                f"{_fmt(stage_count.get(s, 0.0))}" for s in stages])
        q_samples = []
        for s in stages:
            hist = stage_hists.get(s)
            if not hist or not hist.get("count"):
                continue
            for q in (0.5, 0.99):
                q_samples.append(
                    f"{PREFIX}_stage_latency_seconds"
                    f"{{stage=\"{s}\",quantile=\"{q}\"}} "
                    f"{_fmt(_hist_quantile(hist, q))}")
        family(f"{PREFIX}_stage_latency_seconds", "gauge",
               "Cumulative stage latency quantiles (fixed-bucket upper"
               " bounds).", q_samples)

    if hop_hists:
        # per-hop trace latency decomposition folds into one labeled family
        # (same pattern as stages) rather than N generic summaries
        hq_samples = []
        hc_samples = []
        for h in sorted(hop_hists):
            hist = hop_hists[h]
            hc_samples.append(
                f"{PREFIX}_service_hop_ops_total{{hop=\"{h}\"}} "
                f"{_fmt(hist.get('count', 0))}")
            if not hist.get("count"):
                continue
            for q in (0.5, 0.99):
                hq_samples.append(
                    f"{PREFIX}_service_hop_latency_seconds"
                    f"{{hop=\"{h}\",quantile=\"{q}\"}} "
                    f"{_fmt(_hist_quantile(hist, q))}")
        family(f"{PREFIX}_service_hop_ops_total", "counter",
               "Traced items observed per service hop.", hc_samples)
        family(f"{PREFIX}_service_hop_latency_seconds", "gauge",
               "Per-hop latency quantiles of traced service items"
               " (fixed-bucket upper bounds).", hq_samples)

    if sampler_point:
        point_stages = sorted(sampler_point.get("stages", {}))
        family(f"{PREFIX}_stage_rate_per_second", "gauge",
               "Stage executions per second over the last sampled interval.",
               [f"{PREFIX}_stage_rate_per_second{{stage=\"{s}\"}} "
                f"{_fmt(sampler_point['stages'][s]['rate_per_s'])}"
                for s in point_stages])
        iq_samples = []
        for s in point_stages:
            for q, key in ((0.5, "p50_s"), (0.99, "p99_s")):
                v = sampler_point["stages"][s][key]
                if v is None:
                    continue
                iq_samples.append(
                    f"{PREFIX}_stage_interval_latency_seconds"
                    f"{{stage=\"{s}\",quantile=\"{q}\"}} {_fmt(v)}")
        family(f"{PREFIX}_stage_interval_latency_seconds", "gauge",
               "Stage latency quantiles over the last sampled interval.",
               iq_samples)
        hop_point = sampler_point.get("hops", {})
        hiq_samples = []
        for h in sorted(hop_point):
            for q, key in ((0.5, "p50_s"), (0.99, "p99_s")):
                v = hop_point[h].get(key)
                if v is None:
                    continue
                hiq_samples.append(
                    f"{PREFIX}_service_hop_interval_latency_seconds"
                    f"{{hop=\"{h}\",quantile=\"{q}\"}} {_fmt(v)}")
        family(f"{PREFIX}_service_hop_interval_latency_seconds", "gauge",
               "Per-hop latency quantiles over the last sampled interval.",
               hiq_samples)
        family(f"{PREFIX}_sample_interval_seconds", "gauge",
               "Measured length of the last sampled interval.",
               [f"{PREFIX}_sample_interval_seconds "
                f"{_fmt(sampler_point.get('dt_s', 0.0))}"])

    return "\n".join(lines) + "\n"


def render_fleet_prometheus(fleet: Dict) -> str:
    """Render a dispatcher ``fleet_stats()`` dict as Prometheus text: the
    fleet aggregation plane's per-worker-labeled families plus fleet-merged
    histogram quantiles.  Pure function (golden-testable); appended to the
    dispatcher's ``/metrics`` body via ``MetricsExportServer(extra=...)``.

    Families::

        petastorm_tpu_fleet_worker_up{worker=...}            1
        petastorm_tpu_fleet_worker_busy{worker=...}          in-flight+queued
        petastorm_tpu_fleet_worker_capacity{worker=...}
        petastorm_tpu_fleet_worker_inflight{worker=...}      dispatcher view
        petastorm_tpu_fleet_worker_heartbeat_age_seconds{worker=...}
        petastorm_tpu_fleet_worker_counter_total{worker=...,counter=...}
        petastorm_tpu_fleet_worker_latency_seconds{worker=...,hist=...,quantile=...}
        petastorm_tpu_fleet_latency_seconds{hist=...,quantile=...}   merged
        petastorm_tpu_fleet_counter_total{counter=...}       dispatcher fold
    """
    lines: List[str] = []

    def family(name: str, mtype: str, help_text: str,
               samples: Iterable) -> None:
        rendered = list(samples)
        if not rendered:
            return
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        lines.extend(rendered)

    def lbl(v) -> str:
        return _LABEL_RE.sub("_", str(v))

    workers = fleet.get("workers", {}) or {}
    names = sorted(workers)
    family(f"{PREFIX}_fleet_worker_up", "gauge",
           "1 for every worker currently registered with the dispatcher.",
           [f"{PREFIX}_fleet_worker_up{{worker=\"{lbl(w)}\"}} 1"
            for w in names])
    for field, metric, help_text in (
            ("busy", "fleet_worker_busy",
             "Worker-reported in-flight + queued items (last heartbeat)."),
            ("capacity", "fleet_worker_capacity",
             "Configured concurrent-item capacity per worker."),
            ("inflight", "fleet_worker_inflight",
             "Dispatcher-recorded assignments in flight toward the worker."),
            ("heartbeat_age_s", "fleet_worker_heartbeat_age_seconds",
             "Seconds since the worker's last heartbeat.")):
        family(f"{PREFIX}_{metric}", "gauge", help_text,
               [f"{PREFIX}_{metric}{{worker=\"{lbl(w)}\"}} "
                f"{_fmt(float(workers[w].get(field, 0) or 0))}"
                for w in names if field in workers[w]])
    ctr_samples = []
    for w in names:
        counters = workers[w].get("counters", {}) or {}
        for c in sorted(counters):
            ctr_samples.append(
                f"{PREFIX}_fleet_worker_counter_total"
                f"{{worker=\"{lbl(w)}\",counter=\"{lbl(c)}\"}} "
                f"{_fmt(float(counters[c]))}")
    family(f"{PREFIX}_fleet_worker_counter_total", "counter",
           "Per-worker cumulative counters folded from heartbeat deltas.",
           ctr_samples)
    wq_samples = []
    for w in names:
        hists = workers[w].get("hists", {}) or {}
        for h in sorted(hists):
            for q, key in ((0.5, "p50_s"), (0.99, "p99_s")):
                v = hists[h].get(key)
                if v is None:
                    continue
                wq_samples.append(
                    f"{PREFIX}_fleet_worker_latency_seconds"
                    f"{{worker=\"{lbl(w)}\",hist=\"{lbl(h)}\","
                    f"quantile=\"{q}\"}} {_fmt(v)}")
    family(f"{PREFIX}_fleet_worker_latency_seconds", "gauge",
           "Per-worker stage/hop latency quantiles (heartbeat snapshots).",
           wq_samples)
    merged = fleet.get("merged_hists", {}) or {}
    mq_samples = []
    for h in sorted(merged):
        for q, key in ((0.5, "p50_s"), (0.99, "p99_s")):
            v = merged[h].get(key)
            if v is None:
                continue
            mq_samples.append(
                f"{PREFIX}_fleet_latency_seconds"
                f"{{hist=\"{lbl(h)}\",quantile=\"{q}\"}} {_fmt(v)}")
    family(f"{PREFIX}_fleet_latency_seconds", "gauge",
           "Fleet-merged stage/hop latency quantiles (bucket-wise merge of"
           " every worker's snapshot).", mq_samples)
    fleet_counters = fleet.get("fleet_counters", {}) or {}
    family(f"{PREFIX}_fleet_counter_total", "counter",
           "Fleet-wide cumulative counters (dispatcher heartbeat fold).",
           [f"{PREFIX}_fleet_counter_total{{counter=\"{lbl(c)}\"}} "
            f"{_fmt(float(fleet_counters[c]))}"
            for c in sorted(fleet_counters)])
    if "epoch" in fleet:
        family(f"{PREFIX}_fleet_epoch", "gauge",
               "Current dispatcher fencing epoch.",
               [f"{PREFIX}_fleet_epoch {_fmt(float(fleet['epoch']))}"])
    if not lines:
        return ""
    return "\n".join(lines) + "\n"


class MetricsExportServer:
    """Localhost-only ``/metrics`` pull endpoint on a daemon thread.

    ``port=0`` binds an ephemeral port (read it back via ``.port`` after
    ``start()``).  The handler renders a fresh snapshot per scrape - there
    is no caching, matching the one-scraper-per-host pattern; rendering is
    microseconds for a few hundred instruments.  ``stop()`` shuts the
    listener down; in-flight requests finish (daemon threads).
    """

    def __init__(self, telemetry, sampler=None, port: int = 0,
                 host: str = "127.0.0.1", extra=None):
        self.telemetry = telemetry
        self.sampler = sampler
        #: optional zero-arg callable returning extra exposition text to
        #: append per scrape (the dispatcher's fleet families); a failure
        #: there degrades the scrape to local metrics, never a 500
        self.extra = extra
        self.host = host
        self._requested_port = int(port)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._bound_port: Optional[int] = None

    @property
    def port(self) -> Optional[int]:
        """The bound port (None before ``start()``; survives ``stop()`` so
        post-mortem diagnostics still name the port that was serving)."""
        return self._bound_port

    def start(self) -> int:
        """Bind and start serving; returns the bound port.  Idempotent."""
        if self._server is not None:
            return self.port
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            server_version = "petastorm-tpu-metrics/1"

            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404, "only /metrics is served")
                    return
                try:
                    point = (outer.sampler.latest()
                             if outer.sampler is not None else None)
                    body = render_prometheus(outer.telemetry.snapshot(),
                                             sampler_point=point)
                except Exception:  # noqa: BLE001 - a scrape must not crash
                    logger.warning("metrics render failed", exc_info=True)
                    self.send_error(500, "metrics render failed")
                    return
                if outer.extra is not None:
                    try:
                        body += outer.extra() or ""
                    except Exception:  # noqa: BLE001
                        logger.warning("extra metrics render failed",
                                       exc_info=True)
                payload = body.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, fmt, *args):  # quiet: scrapes are routine
                logger.debug("metrics endpoint: " + fmt, *args)

        server = ThreadingHTTPServer((self.host, self._requested_port),
                                     _Handler)
        server.daemon_threads = True
        self._server = server
        self._bound_port = server.server_address[1]
        self._thread = threading.Thread(target=server.serve_forever,
                                        daemon=True,
                                        name="petastorm-tpu-metrics-export")
        self._thread.start()
        logger.info("metrics endpoint serving on http://%s:%d/metrics",
                    self.host, self.port)
        return self.port

    def stop(self) -> None:
        """Shut the listener down (idempotent)."""
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)


def write_jsonl(points: Iterable[Dict], path: str) -> str:
    """Append sampled points (``MetricsSampler.series()`` / ``.tail()``) to
    ``path`` as one JSON object per line - the push sink for airgapped runs
    where no scraper can reach the pull endpoint.  Returns the path."""
    with open(path, "a") as f:
        for point in points:
            f.write(json.dumps(point) + "\n")
    return path
