"""Deterministic stub worker factories for pool tests.

Reference parity: petastorm/workers_pool/tests/stub_workers.py:14-84 (coefficient
multiplier, sleeper, exception-raiser).  Module-level classes so ProcessExecutor can
pickle them for spawn.
"""

import os
import time


class MultiplierWorker:
    """process(x) -> coefficient * x."""

    def __init__(self, coefficient: int = 2):
        self.coefficient = coefficient

    def __call__(self):
        coeff = self.coefficient
        return lambda x: coeff * x


class SleepyWorker:
    def __init__(self, sleep_s: float = 0.01):
        self.sleep_s = sleep_s

    def __call__(self):
        def fn(x):
            time.sleep(self.sleep_s)
            return x
        return fn


class ExplodingWorker:
    """Raises on items equal to the trigger value."""

    def __init__(self, trigger=13):
        self.trigger = trigger

    def __call__(self):
        trigger = self.trigger

        def fn(x):
            if x == trigger:
                raise RuntimeError(f"boom on {x}")
            return x
        return fn


class PidWorker:
    """Returns the worker's process id - proves process isolation."""

    def __call__(self):
        return lambda _x: os.getpid()


class BlockingWorker:
    """Wedges on items equal to the trigger until ``release`` is set —
    drives the stall-detection diagnostics (thread pool only: the event is
    shared in-process)."""

    def __init__(self, release, trigger=1):
        self.release = release
        self.trigger = trigger

    def __call__(self):
        def fn(x):
            if getattr(x, "item", x) == self.trigger:
                self.release.wait()
            return x
        return fn


class HardCrashWorker:
    """Simulates an OOM-kill/segfault: the worker PROCESS dies without a
    traceback (os._exit bypasses exception handling entirely)."""

    def __init__(self, trigger=7):
        self.trigger = trigger

    def __call__(self):
        trigger = self.trigger

        def fn(x):
            if x == trigger:
                os._exit(17)
            return x
        return fn
