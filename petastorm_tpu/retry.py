"""Bounded retry-with-backoff for transient remote-IO failures.

TPU pods read object stores (GCS/S3) where transient 5xx/timeout errors are
routine; one such error mid-epoch must not kill a multi-hour ingest.  The
reference had per-backend resilience only (HDFS namenode failover,
hdfs/namenode.py:244-299; S3 eventual-consistency waits,
spark_dataset_converter.py:565-595); here one policy covers every filesystem
the resolver returns.

What retries: rowgroup reads in the decode workers (with the possibly
poisoned file handle dropped between attempts) and metadata opens (listing,
KV read, footer reads).  What does NOT: non-transient errors
(FileNotFoundError, PermissionError, corrupt-data ArrowInvalid, CodecError) -
those fail fast; and local filesystems by default (``io_retries='auto'``),
where a failed read is a real bug, not weather.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Callable, Optional, Union

import pyarrow.fs as pafs

from petastorm_tpu.errors import CircuitOpenError, PetastormTpuError

logger = logging.getLogger(__name__)

#: OSError subclasses that indicate a durable condition, not transient
#: weather.  CircuitOpenError is here by construction: the breaker exists to
#: STOP retries, so its fail-fast error must never itself be retried.
_NON_TRANSIENT = (FileNotFoundError, PermissionError, IsADirectoryError,
                  NotADirectoryError, FileExistsError, CircuitOpenError)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: ``initial * multiplier^attempt``, capped, jittered.

    ``circuit_threshold``/``circuit_cooldown_s`` configure the storage
    circuit breaker layered OVER the per-call retry: ``circuit_threshold``
    consecutive transient failures (across calls and workers sharing the
    breaker) open the circuit and subsequent calls fail fast with
    :class:`~petastorm_tpu.errors.CircuitOpenError` instead of compounding
    retry storms; after ``circuit_cooldown_s`` a single probe call is let
    through (half-open) and its success closes the circuit.
    ``circuit_threshold=None`` disables the breaker.
    """

    max_attempts: int = 4
    initial_backoff_s: float = 0.2
    backoff_multiplier: float = 2.0
    max_backoff_s: float = 5.0
    jitter_frac: float = 0.25
    circuit_threshold: Optional[int] = 10
    circuit_cooldown_s: float = 30.0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise PetastormTpuError("RetryPolicy.max_attempts must be >= 1")
        if self.circuit_threshold is not None and self.circuit_threshold < 1:
            raise PetastormTpuError(
                "RetryPolicy.circuit_threshold must be >= 1 or None")
        if self.circuit_cooldown_s < 0:
            raise PetastormTpuError(
                "RetryPolicy.circuit_cooldown_s must be >= 0")


class CircuitBreaker:
    """Shared consecutive-transient-failure breaker (docs/operations.md
    "Liveness & stragglers").

    closed -> (``threshold`` CONSECUTIVE transient failures) -> open ->
    (``cooldown_s`` elapses; ONE probe allowed) -> half-open ->
    probe success closes / probe failure re-opens.

    One instance is shared by every worker of a reader (thread pools share
    it directly; spawned process-pool workers each unpickle their own copy,
    so the threshold is then per-process - documented, still bounded).
    Success anywhere resets the consecutive count: the breaker reacts to a
    store that is DOWN, not to scattered weather, which the per-call retry
    layer already absorbs.  Thread-safe; picklable (lock recreated).
    """

    def __init__(self, threshold: int = 10, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if threshold < 1:
            raise PetastormTpuError("CircuitBreaker threshold must be >= 1")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None  # None = closed
        self._probing = False                    # half-open probe in flight
        self.opens = 0          # cumulative open transitions
        self.failfasts = 0      # calls rejected while open

    def __getstate__(self):
        state = dict(self.__dict__)
        del state["_lock"]
        state["_clock"] = None  # a custom clock (tests) is process-local
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        if self._clock is None:
            self._clock = time.monotonic

    @property
    def state(self) -> str:
        """``'closed'``, ``'open'``, or ``'half-open'`` (cooldown elapsed,
        probe eligible or in flight)."""
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self._probing or (self._clock() - self._opened_at
                                 >= self.cooldown_s):
                return "half-open"
            return "open"

    def before_call(self, what: str = "io") -> bool:
        """Gate one IO call: raises :class:`CircuitOpenError` while open.
        Once ``cooldown_s`` has elapsed, exactly one caller is admitted as
        the half-open probe (returns True; everyone else gets False);
        concurrent callers keep failing fast until the probe settles.  A
        probe caller whose call ends without a transient verdict (a
        non-transient error, an interrupt) MUST call :meth:`release_probe`
        or the slot would stay claimed forever."""
        with self._lock:
            if self._opened_at is None:
                return False
            elapsed = self._clock() - self._opened_at
            if elapsed >= self.cooldown_s and not self._probing:
                self._probing = True  # this caller is the probe
                return True
            self.failfasts += 1
            remaining = max(self.cooldown_s - elapsed, 0.0)
            raise CircuitOpenError(
                f"storage circuit breaker is open ({what}):"
                f" {self._consecutive_failures} consecutive transient IO"
                f" failures >= threshold {self.threshold};"
                + (" half-open probe in flight" if self._probing
                   else f" next probe in {remaining:.1f}s")
                + f" (opened {self.opens}x, {self.failfasts} calls"
                " failed fast)")

    def release_probe(self) -> None:
        """The half-open probe exited without a transient verdict (its call
        raised a NON-transient error, or was interrupted): free the probe
        slot so a later call can probe, leaving the open/cooldown state
        untouched.  Without this, an expired-credential PermissionError
        during the probe would wedge the breaker open forever."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        """A gated call succeeded: close the circuit / reset the count."""
        with self._lock:
            self._consecutive_failures = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self) -> bool:
        """A gated call failed transiently; True when this failure OPENED
        (or re-opened) the circuit - the caller records telemetry then."""
        with self._lock:
            self._consecutive_failures += 1
            if self._probing:
                # failed half-open probe: restart the cooldown clock
                self._probing = False
                self._opened_at = self._clock()
                self.opens += 1
                return True
            if (self._opened_at is None
                    and self._consecutive_failures >= self.threshold):
                self._opened_at = self._clock()
                self.opens += 1
                return True
            return False

    @property
    def is_open(self) -> bool:
        """True while calls would fail fast (cooldown not yet elapsed)."""
        with self._lock:
            return (self._opened_at is not None and not self._probing
                    and self._clock() - self._opened_at < self.cooldown_s)

    def snapshot(self) -> dict:
        """Diagnostics view: state, consecutive failures, opens, failfasts."""
        with self._lock:
            consecutive = self._consecutive_failures
            opens, failfasts = self.opens, self.failfasts
        return {"state": self.state, "consecutive_failures": consecutive,
                "opens": opens, "failfasts": failfasts}


def make_circuit_breaker(policy: Optional[RetryPolicy]
                         ) -> Optional[CircuitBreaker]:
    """One breaker per reader from its retry policy (None when retries or
    the breaker are disabled)."""
    if policy is None or policy.circuit_threshold is None:
        return None
    return CircuitBreaker(policy.circuit_threshold, policy.circuit_cooldown_s)


def is_transient(exc: BaseException) -> bool:
    """Transient = OSError family (incl. pyarrow ArrowIOError and fsspec
    backends' errors, which derive from it) minus the durable subclasses."""
    return isinstance(exc, OSError) and not isinstance(exc, _NON_TRANSIENT)


def retry_call(fn: Callable, policy: Optional[RetryPolicy], *, what: str = "io",
               on_retry: Optional[Callable[[BaseException], None]] = None,
               sleep: Callable[[float], None] = time.sleep,
               telemetry=None, breaker: Optional[CircuitBreaker] = None):
    """Run ``fn``, retrying transient failures per ``policy`` (None = no retry).

    ``on_retry(exc)`` runs before each re-attempt - the hook where callers
    drop possibly-poisoned cached handles/connections.

    ``breaker``: optional shared :class:`CircuitBreaker`.  Every attempt is
    gated through it (open circuit -> immediate
    :class:`~petastorm_tpu.errors.CircuitOpenError`, no retry loop), every
    transient failure feeds it, and a failure that trips it open short-cuts
    the remaining backoff so the outage surfaces now, not after the full
    retry budget.  Circuit opens are counted as ``liveness.circuit_opens``
    in telemetry.

    Every re-attempt is recorded in telemetry (the passed recorder, or the
    process default when ``PETASTORM_TPU_TELEMETRY=1``): an ``io.retries``
    counter plus a per-category ``io.retries.<category>`` counter keyed by
    the first token of ``what`` ("rowgroup", "dataset", ...), and a trace
    instant carrying the full ``what`` - so recurring weather shows up in
    ``petastorm-tpu-diagnose`` reports, not only in log warnings.
    """
    if policy is None and breaker is None:
        return fn()
    max_attempts = policy.max_attempts if policy is not None else 1
    backoff = policy.initial_backoff_s if policy is not None else 0.0
    for attempt in range(1, max_attempts + 1):
        probing = False
        if breaker is not None:
            probing = breaker.before_call(what)
        try:
            result = fn()
        except BaseException as exc:  # noqa: BLE001 - filtered below
            if not isinstance(exc, Exception) or not is_transient(exc):
                # no transient verdict for the breaker (non-transient error,
                # KeyboardInterrupt, ...): a claimed probe slot must be
                # released or the breaker wedges open forever
                if probing:
                    breaker.release_probe()
                raise
            if breaker is not None and breaker.record_failure():
                logger.error(
                    "Storage circuit breaker OPENED during %s: consecutive"
                    " transient IO failures reached threshold %d; failing"
                    " fast for %.0fs instead of retrying", what,
                    breaker.threshold, breaker.cooldown_s)
                _record_circuit_open(telemetry, what, exc)
            if attempt >= max_attempts:
                raise
            if breaker is not None and breaker.is_open:
                # the circuit opened under this call's failures: surface the
                # outage immediately rather than sleeping out the backoff
                # against a store the breaker just declared down.  If the
                # cooldown happens to elapse in this very instant,
                # before_call ADMITS us as the half-open probe instead of
                # raising - release the slot (we are mid-backoff, not
                # probing) so the next attempt can claim it properly
                if breaker.before_call(what):
                    breaker.release_probe()
            delay = min(backoff, policy.max_backoff_s)
            delay *= 1 + policy.jitter_frac * random.random()
            logger.warning("Transient IO failure in %s (attempt %d/%d): %s;"
                           " retrying in %.2fs", what, attempt,
                           policy.max_attempts, exc, delay)
            _record_retry(telemetry, what, exc)
            if on_retry is not None:
                try:
                    on_retry(exc)
                except Exception:  # noqa: BLE001 - cleanup is best-effort
                    logger.debug("on_retry hook failed", exc_info=True)
            sleep(delay)
            backoff *= policy.backoff_multiplier
        else:
            if breaker is not None:
                breaker.record_success()
            return result


def _record_circuit_open(telemetry, what: str, exc: BaseException) -> None:
    """Count one circuit-open transition (lazily resolved, like retries)."""
    from petastorm_tpu.telemetry import resolve as _resolve_telemetry

    tele = _resolve_telemetry(telemetry)
    if not tele.enabled:
        return
    tele.counter("liveness.circuit_opens").add(1)
    trace = getattr(tele, "trace", None)
    if trace is not None:
        trace.add("circuit-open", "fault", time.perf_counter_ns(), 0,
                  {"what": what, "error": str(exc)})


def _record_retry(telemetry, what: str, exc: BaseException) -> None:
    """Count one retry (resolved lazily: only the retry path pays for it)."""
    from petastorm_tpu.telemetry import resolve as _resolve_telemetry

    tele = _resolve_telemetry(telemetry)
    if not tele.enabled:
        return
    tele.counter("io.retries").add(1)
    category = what.split(" ", 1)[0] if what else "io"
    tele.counter(f"io.retries.{category}").add(1)
    trace = getattr(tele, "trace", None)
    if trace is not None:
        trace.add("io-retry", "fault", time.perf_counter_ns(), 0,
                  {"what": what, "error": str(exc)})


def resolve_retry_policy(io_retries: Union[None, bool, int, str, RetryPolicy],
                         filesystem: Optional[pafs.FileSystem]
                         ) -> Optional[RetryPolicy]:
    """User-facing ``io_retries`` knob -> concrete policy (or None = off).

    ``'auto'`` (the default everywhere): retries on for any non-local
    filesystem, off for LocalFileSystem.  An int sets ``max_attempts`` with
    default backoff; a RetryPolicy passes through; None/False/0 disables.
    """
    if io_retries is None or io_retries is False or io_retries == 0:
        return None
    if isinstance(io_retries, RetryPolicy):
        return io_retries
    if io_retries == "auto":
        if filesystem is not None and isinstance(filesystem, pafs.LocalFileSystem):
            return None
        return RetryPolicy()
    if isinstance(io_retries, bool):  # True
        return RetryPolicy()
    if isinstance(io_retries, int):
        return RetryPolicy(max_attempts=io_retries)
    raise PetastormTpuError(
        f"io_retries must be 'auto', None/False, an int (max attempts) or a"
        f" RetryPolicy; got {io_retries!r}")
