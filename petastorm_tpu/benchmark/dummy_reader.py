"""Loader-only microbenchmark: no parquet, no IO - just the delivery layer.

Reference parity: petastorm/benchmark/dummy_reader.py:25-85 - a synthetic
reader feeding the loaders so their overhead (shuffle buffer, collate, device
transfer) can be measured in isolation across batch sizes.
"""

from __future__ import annotations

import time
from typing import Dict, List, Sequence

import numpy as np

from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.test_util.reader_mock import ReaderMock

#: feature sizes roughly matching the reference microbench payload
#: (dummy_reader.py:30-38: one flat float feature vector + int label)
BENCH_SCHEMA = Schema("LoaderBench", [
    Field("feature", np.float32, (64,)),
    Field("label", np.int64),
])


def _measure(loader, warmup_batches: int, measure_batches: int,
             block=None) -> float:
    it = iter(loader)

    def consume(n: int) -> int:
        rows = 0
        for _ in range(n):
            batch = next(it)
            if block is not None:
                block(batch)
            first = batch[next(iter(batch))] if isinstance(batch, dict) else batch[0]
            rows += int(first.shape[0])
        return rows

    consume(warmup_batches)
    t0 = time.perf_counter()
    rows = consume(measure_batches)
    return rows / (time.perf_counter() - t0)


def loader_microbench(batch_sizes: Sequence[int] = (10, 100, 1000, 10000),
                      warmup_batches: int = 5,
                      measure_batches: int = 50,
                      shuffling_queue_capacity: int = 0,
                      kinds: Sequence[str] = ("torch", "torch_batched", "jax"),
                      ) -> List[Dict]:
    """samples/sec of each delivery loader at each batch size.

    Reference: benchmark/dummy_reader.py:47-82 (DataLoader vs BatchedDataLoader
    sweep); extended with the jax device loader, the path TPU consumers use.
    """
    results: List[Dict] = []
    for batch_size in batch_sizes:
        for kind in kinds:
            reader = ReaderMock(BENCH_SCHEMA, batch_size=batch_size,
                                num_batches=None)
            if kind == "torch":
                from petastorm_tpu.pytorch import DataLoader
                loader = DataLoader(reader, batch_size=batch_size,
                                    shuffling_queue_capacity=shuffling_queue_capacity)
                rate = _measure(loader, warmup_batches, measure_batches)
            elif kind == "torch_batched":
                from petastorm_tpu.pytorch import BatchedDataLoader
                loader = BatchedDataLoader(
                    reader, batch_size=batch_size,
                    shuffling_queue_capacity=shuffling_queue_capacity)
                rate = _measure(loader, warmup_batches, measure_batches)
            elif kind == "jax":
                import jax

                from petastorm_tpu.jax import JaxDataLoader
                with JaxDataLoader(reader, batch_size=batch_size) as loader:
                    rate = _measure(loader, warmup_batches, measure_batches,
                                    block=jax.block_until_ready)
            else:
                raise ValueError(f"unknown loader kind {kind!r}")
            reader.stop()
            results.append({"loader": kind, "batch_size": batch_size,
                            "samples_per_sec": rate})
    return results


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser(
        description="Microbenchmark delivery loaders over a synthetic reader")
    parser.add_argument("--batch-sizes", type=int, nargs="+",
                        default=[10, 100, 1000, 10000])
    parser.add_argument("--kinds", nargs="+",
                        default=["torch", "torch_batched", "jax"])
    parser.add_argument("--measure-batches", type=int, default=50)
    parser.add_argument("--shuffling-queue-capacity", type=int, default=0)
    args = parser.parse_args()
    for r in loader_microbench(batch_sizes=args.batch_sizes, kinds=args.kinds,
                               measure_batches=args.measure_batches,
                               shuffling_queue_capacity=args.shuffling_queue_capacity):
        print(f"{r['loader']:>14}  batch={r['batch_size']:<6} "
              f"{r['samples_per_sec']:>12.1f} samples/sec")


if __name__ == "__main__":
    main()
