"""Deterministic multi-corpus mixture scheduling for token pipelines.

Real LLM ingest mixes N corpora by weight, and that mixing is the least
reproducible stage of the pipeline (the reproducible-pipelines paper,
PAPERS.md) - a run is only replayable if *which corpus each batch came
from* is as deterministic as each corpus's own stream.  This module layers
the token-corpus entry point on the two pieces built for exactly that:

* every corpus reader runs ``deterministic='seed'`` delivery with a
  per-corpus seed derived from ONE mixture seed
  (``seeding.derive_seed(seed, 0, 'sequence.corpus', i)``) - corpora never
  share a permutation stream, yet the whole mixture is a pure function of
  the single seed;
* the draw sequence rides the mixer's certificate
  (:attr:`~petastorm_tpu.weighted_sampling.WeightedSamplingReader.mixture_digest`),
  so a mixed N-corpus run diffs in O(1) like a single-reader one - the
  chaos matrix certifies the packed mixed stream bit-identical across
  worker counts, executor flavors, chaos kills and the service hop.
"""

from __future__ import annotations

from typing import Optional, Sequence

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.seeding import derive_seed
from petastorm_tpu.sequence.dataset import make_sequence_reader
from petastorm_tpu.weighted_sampling import WeightedSamplingReader


def corpus_seed(seed: Optional[int], corpus_index: int) -> Optional[int]:
    """The per-corpus shuffle seed a mixture derives from its one root seed
    (``None`` stays ``None`` - unseeded corpora keep unseeded plans)."""
    if seed is None:
        return None
    return derive_seed(seed, 0, "sequence.corpus", corpus_index)


def make_mixed_sequence_reader(dataset_urls: Sequence[str],
                               weights: Optional[Sequence[float]] = None,
                               seed: Optional[int] = None,
                               tokens_field: str = "tokens",
                               **reader_kwargs) -> WeightedSamplingReader:
    """Open N token corpora and mix them by weight, deterministically.

    One ``seed`` drives everything: corpus ``i`` reads with
    ``shuffle_seed=``:func:`corpus_seed`\\ ``(seed, i)`` (arming
    ``deterministic='seed'`` delivery via the reader's ``'auto'``
    resolution) and the mixer draws from
    ``seed_stream(derive_seed(seed, 0, 'sequence.mixture'), ...)`` - so the
    mixed document stream, and therefore the packed stream, is a pure
    function of ``(seed, weights, corpora)``.  ``seed=None`` keeps every
    stage unseeded (each run differs).

    ``weights`` defaults to uniform.  All other kwargs go to every
    corpus's :func:`~petastorm_tpu.sequence.dataset.make_sequence_reader`
    verbatim (``workers_count``, ``predicate``, ``cache_type``,
    ``service_address``, ...).  An explicit ``shuffle_seed`` kwarg is
    refused: per-corpus seeds must differ or corpora would share one
    permutation stream - pass ``seed=`` instead.

    Returns the :class:`WeightedSamplingReader`; consume via
    ``iter_batches()`` + :func:`~petastorm_tpu.sequence.dataset.iter_documents`
    + the packer, or hand it to
    :class:`~petastorm_tpu.sequence.loader.PackedSequenceReader`.
    """
    if not dataset_urls:
        raise PetastormTpuError("dataset_urls must name at least one corpus")
    if "shuffle_seed" in reader_kwargs:
        raise PetastormTpuError(
            "pass seed= to make_mixed_sequence_reader, not shuffle_seed=:"
            " per-corpus seeds are derived from the one mixture seed"
            " (corpora must not share a permutation stream)")
    if weights is None:
        weights = [1.0] * len(dataset_urls)
    if len(weights) != len(dataset_urls):
        raise PetastormTpuError(
            f"{len(dataset_urls)} corpora but {len(weights)} weights")
    readers = []
    try:
        for i, url in enumerate(dataset_urls):
            readers.append(make_sequence_reader(
                url, tokens_field=tokens_field,
                shuffle_seed=corpus_seed(seed, i), **reader_kwargs))
        mixer_seed = (derive_seed(seed, 0, "sequence.mixture")
                      if seed is not None else None)
        return WeightedSamplingReader(readers, weights, seed=mixer_seed)
    except BaseException:
        for r in readers:
            r.stop()
        for r in readers:
            r.join()
        raise
