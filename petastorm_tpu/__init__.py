"""petastorm_tpu: a TPU-native Parquet tensor-ingest framework.

Capabilities of uber/petastorm (tensor-aware Parquet datasets, sharded prefetching
readers, codecs, predicates, NGram readout, framework adapters), re-architected for
JAX on TPU: columnar Arrow host pipeline, device-sharded ``jax.Array`` delivery
driven by the process mesh, and on-device (XLA/Pallas) decode/normalize ops.

Import layering: this module and everything under the core layers (schema, codecs,
etl, reader) are **jax-free** - host-side ETL never initializes the TPU.  JAX enters
only via ``petastorm_tpu.jax`` (loader), ``petastorm_tpu.ops`` (kernels) and
``petastorm_tpu.models``.
"""

from petastorm_tpu.codecs import (CompressedImageCodec, CompressedNdarrayCodec,
                                  NdarrayCodec, ScalarCodec)
from petastorm_tpu.errors import NoDataAvailableError, PetastormTpuError
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.transform import TransformSpec

__version__ = "0.1.0"

__all__ = [
    "Field", "Schema", "TransformSpec",
    "ScalarCodec", "NdarrayCodec", "CompressedNdarrayCodec", "CompressedImageCodec",
    "PetastormTpuError", "NoDataAvailableError",
    "make_reader", "make_batch_reader", "materialize_dataset",
    "make_converter",
]


def _lazy(module: str, symbol: str):
    import importlib

    try:
        mod = importlib.import_module(module)
    except ImportError as exc:  # pragma: no cover - only during partial builds
        raise NotImplementedError(
            f"{symbol} requires {module}, which is not present in this build") from exc
    return getattr(mod, symbol)


def make_reader(*args, **kwargs):
    return _lazy("petastorm_tpu.reader", "make_reader")(*args, **kwargs)


def make_batch_reader(*args, **kwargs):
    return _lazy("petastorm_tpu.reader", "make_batch_reader")(*args, **kwargs)


def materialize_dataset(*args, **kwargs):
    return _lazy("petastorm_tpu.etl.writer", "materialize_dataset")(*args, **kwargs)


def make_converter(*args, **kwargs):
    return _lazy("petastorm_tpu.converter", "make_converter")(*args, **kwargs)
