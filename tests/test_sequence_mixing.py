"""Deterministic multi-corpus mixture scheduling (ISSUE 11 tentpole c +
satellite): one seed drives corpus plans + mixture draws, the draw sequence
rides the mixture certificate, and an unseeded mixer over seeded
sub-readers warns + auto-derives under deterministic='auto'."""

import logging

import numpy as np
import pytest

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.seeding import derive_seed
from petastorm_tpu.sequence import (corpus_seed, iter_documents,
                                    make_mixed_sequence_reader,
                                    make_sequence_reader)
from petastorm_tpu.test_util.synthetic import write_token_corpus
from petastorm_tpu.weighted_sampling import WeightedSamplingReader


@pytest.fixture(scope="module")
def corpora(tmp_path_factory):
    base = tmp_path_factory.mktemp("mix_corpora")
    urls = []
    for i in range(2):
        url = str(base / f"c{i}")
        write_token_corpus(url, n_docs=60, rows_per_rg=10, mean_len=12,
                           max_len=40, seed=30 + i)
        urls.append(url)
    return urls


def _doc_stream(urls, seed, weights=None, **kwargs):
    with make_mixed_sequence_reader(urls, weights=weights,
                                    seed=seed, **kwargs) as mixer:
        docs = [d.tolist() for d in iter_documents(mixer, "tokens")]
        digest = mixer.mixture_digest
        diag = mixer.diagnostics
    return docs, digest, diag


def test_mixture_pure_function_of_seed(corpora):
    a_docs, a_dig, a_diag = _doc_stream(corpora, seed=7)
    b_docs, b_dig, _ = _doc_stream(corpora, seed=7)
    assert a_docs == b_docs
    assert a_dig == b_dig
    assert a_diag["seed"] is not None
    c_docs, c_dig, _ = _doc_stream(corpora, seed=8)
    assert c_docs != a_docs
    assert c_dig["combined"] != a_dig["combined"]
    assert c_dig["draws"] != a_dig["draws"]


def test_mixer_exposes_adapter_surface(corpora):
    """Downstream adapters (the jax loader's buffer seeding, the packer's
    telemetry) read deterministic/shuffle_seed/telemetry off their source:
    a fully-seeded mixture must expose them, or buffer RNGs silently fall
    back to unseeded."""
    from petastorm_tpu.seeding import reader_buffer_seed
    from petastorm_tpu.telemetry import Telemetry

    tele = Telemetry()
    with make_mixed_sequence_reader(corpora, seed=7,
                                    telemetry=tele) as mixer:
        assert mixer.deterministic == "seed"
        assert mixer.shuffle_seed == mixer.seed is not None
        assert mixer.telemetry is tele
        # the exact call the JaxDataLoader makes: must derive, not None
        assert reader_buffer_seed(mixer, "loader.shuffle_buffer") is not None
        list(mixer.iter_batches())
    # unseeded mixture: adapters must see 'off'/None
    with make_mixed_sequence_reader(corpora) as mixer:
        assert mixer.deterministic == "off"
        assert mixer.shuffle_seed is None
        assert reader_buffer_seed(mixer, "loader.shuffle_buffer") is None
        list(mixer.iter_batches())


def test_mixture_digest_is_o1_certificate(corpora):
    """The combined value folds the draw chain + every sub-reader's own
    StreamDigest: two runs are compared by ONE hex value each."""
    _, dig, _ = _doc_stream(corpora, seed=7)
    assert set(dig) == {"draws", "draw_count", "readers", "combined"}
    assert len(dig["readers"]) == 2
    assert all(isinstance(r, str) for r in dig["readers"])
    assert dig["draw_count"] > 0


def test_corpus_seeds_differ_per_corpus():
    assert corpus_seed(None, 0) is None
    assert corpus_seed(7, 0) != corpus_seed(7, 1)
    assert corpus_seed(7, 0) == derive_seed(7, 0, "sequence.corpus", 0)


def test_weights_skew_mixture(corpora):
    """A heavily skewed weight draws mostly from that corpus early on (the
    schedule is a property of the weights, not just the seed)."""
    docs_even, _, _ = _doc_stream(corpora, seed=3)
    docs_skew, _, _ = _doc_stream(corpora, seed=3, weights=[0.95, 0.05])
    assert docs_even != docs_skew
    # exhaustion renormalizes: every document still arrives exactly once
    with make_sequence_reader(corpora[0], shuffle_seed=1) as r0, \
            make_sequence_reader(corpora[1], shuffle_seed=2) as r1:
        total = (sum(1 for _ in iter_documents(r0, "tokens"))
                 + sum(1 for _ in iter_documents(r1, "tokens")))
    assert len(docs_skew) == total == len(docs_even)


def test_mixture_rejects_explicit_shuffle_seed(corpora):
    with pytest.raises(PetastormTpuError, match="not shuffle_seed"):
        make_mixed_sequence_reader(corpora, seed=1, shuffle_seed=2)


def test_mixture_weight_count_mismatch(corpora):
    with pytest.raises(PetastormTpuError, match="weights"):
        make_mixed_sequence_reader(corpora, weights=[1.0], seed=1)
    with pytest.raises(PetastormTpuError, match="at least one corpus"):
        make_mixed_sequence_reader([], seed=1)


# -- satellite: WeightedSamplingReader auto-seed ------------------------------

def test_unseeded_mixer_over_seeded_readers_warns_and_derives(
        corpora, caplog):
    """All sub-readers seed-deterministic + mixer seed=None: one warning,
    and under deterministic='auto' the mixer seed derives from the first
    reader's shuffle_seed - so two such constructions mix identically."""
    def build():
        readers = [make_sequence_reader(u, shuffle_seed=40 + i,
                                        deterministic="seed")
                   for i, u in enumerate(corpora)]
        return WeightedSamplingReader(readers, [0.5, 0.5])

    with caplog.at_level(logging.WARNING,
                         logger="petastorm_tpu.weighted_sampling"):
        with build() as a:
            warnings = [r for r in caplog.records
                        if "defeat stream reproducibility" in r.message]
            assert len(warnings) == 1
            assert a.seed == derive_seed(40, 0, "weighted_sampling.auto")
            a_ids = [int(x) for b in a.iter_batches()
                     for x in b.columns["doc_id"]]
            a_dig = a.mixture_digest
    with build() as b:
        b_ids = [int(x) for b2 in b.iter_batches()
                 for x in b2.columns["doc_id"]]
        b_dig = b.mixture_digest
    assert a_ids == b_ids
    assert a_dig == b_dig


def test_unseeded_mixer_deterministic_off_warns_but_stays_unseeded(
        corpora, caplog):
    readers = [make_sequence_reader(u, shuffle_seed=50 + i,
                                    deterministic="seed")
               for i, u in enumerate(corpora)]
    with caplog.at_level(logging.WARNING,
                         logger="petastorm_tpu.weighted_sampling"):
        with WeightedSamplingReader(readers, [0.5, 0.5],
                                    deterministic="off") as mixer:
            assert mixer.seed is None
            assert any("defeating stream reproducibility" in r.message
                       for r in caplog.records)
            list(mixer.iter_batches())


def test_explicit_seed_silences_warning(corpora, caplog):
    readers = [make_sequence_reader(u, shuffle_seed=60 + i,
                                    deterministic="seed")
               for i, u in enumerate(corpora)]
    with caplog.at_level(logging.WARNING,
                         logger="petastorm_tpu.weighted_sampling"):
        with WeightedSamplingReader(readers, [0.5, 0.5], seed=123) as mixer:
            assert mixer.seed == 123
            assert not caplog.records
            list(mixer.iter_batches())


def test_unseeded_readers_no_warning(corpora, caplog):
    """Unseeded sub-readers never warn: there is no reproducibility to
    defeat (and no root to derive from)."""
    readers = [make_sequence_reader(u) for u in corpora]
    with caplog.at_level(logging.WARNING,
                         logger="petastorm_tpu.weighted_sampling"):
        with WeightedSamplingReader(readers, [0.5, 0.5]) as mixer:
            assert mixer.seed is None
            assert not caplog.records
            list(mixer.iter_batches())


def test_mixer_rejects_bad_deterministic(corpora):
    readers = [make_sequence_reader(u) for u in corpora]
    try:
        with pytest.raises(PetastormTpuError, match="deterministic"):
            WeightedSamplingReader(readers, [0.5, 0.5],
                                   deterministic="seed")
    finally:
        for r in readers:
            r.stop()
        for r in readers:
            r.join()


def test_next_path_mixture_records_draws_and_exhaustion(corpora):
    """``__next__`` mixing folds draws (and exhaustion markers) too, and
    every document still arrives exactly once."""
    readers = [make_sequence_reader(u, shuffle_seed=70 + i,
                                    deterministic="seed")
               for i, u in enumerate(corpora)]
    with WeightedSamplingReader(readers, [0.5, 0.5], seed=5) as mixer:
        delivered = list(mixer)  # batched readers: one namedtuple per batch
        dig = mixer.mixture_digest
    ids = sorted(int(x) for nt in delivered for x in np.asarray(nt.doc_id))
    assert ids == sorted(list(range(60)) + list(range(60)))
    # draw_count = delivered batches + the two exhaustion discoveries
    assert dig["draw_count"] == len(delivered) + 2
