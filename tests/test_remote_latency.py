"""Remote IO under injected latency (VERDICT r4 item 4).

``memory://`` and local disk answer in microseconds; real object stores
charge 10-50 ms per request.  These tests run the production remote code
path (PyFileSystem => ``pre_buffer=True``, ``io_retries='auto'`` armed)
against ``test_util.latency_fs`` and assert the three claims:

1. coalescing: a rowgroup's column chunks arrive in FEW ranged reads -
   bounded per rowgroup, NOT one read per column;
2. latency hiding: with N workers + prefetch the injected sleep overlaps
   itself and decode, so wall time stays far under the serial sum of
   injected latency (and within a stated factor of the local read);
3. retries: ``io_retries`` composes with slow-then-FAILING calls.

Reference analog: petastorm/fs_utils.py:88-126 and the S3
eventual-consistency machinery (spark_dataset_converter.py:565-595) exist
because remote stores are slow and flaky, but the reference never tests
under injected latency.
"""

import time

import numpy as np
import pytest

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.test_util.latency_fs import latent_filesystem
from petastorm_tpu.test_util.synthetic import write_wide_dataset

N_COLS = 8
N_ROWGROUPS = 8
ROWS_PER_RG = 32


@pytest.fixture(scope="module")
def wide_ds(tmp_path_factory):
    """Many-column dataset: the shape where per-column reads would hurt
    (shared builder with bench.py's latent-vs-local config)."""
    url = str(tmp_path_factory.mktemp("latent") / "wide")
    write_wide_dataset(url, n_cols=N_COLS, n_rowgroups=N_ROWGROUPS,
                       rows_per_rg=ROWS_PER_RG)
    return url


def _read_all(url, fs, **kwargs):
    ids = []
    with make_batch_reader(url, filesystem=fs, shuffle_row_groups=False,
                           num_epochs=1, **kwargs) as r:
        for cb in r.iter_batches():
            ids.extend(np.asarray(cb.columns["id"]).astype(int).tolist())
    return ids


def test_reads_per_rowgroup_bounded(wide_ds):
    """The coalescing claim, counted: pre_buffer must merge each rowgroup's
    column chunks into a few ranged reads.  Zero latency here - this test
    is purely about CALL COUNT."""
    fs, stats = latent_filesystem(latency_s=0.0)
    ids = _read_all(wide_ds, fs, reader_pool_type="serial")
    assert sorted(ids) == list(range(N_ROWGROUPS * ROWS_PER_RG))
    s = stats.snapshot()
    # footer + metadata cost a handful of reads once per FILE; the per-
    # rowgroup marginal cost is what scales with dataset size.  8 columns
    # x 8 rowgroups = 64 column chunks: uncoalesced would be >= 64 reads
    # before any footer traffic.
    reads_per_rg = s["reads"] / N_ROWGROUPS
    assert reads_per_rg < N_COLS / 2, (
        f"{s['reads']} reads for {N_ROWGROUPS} rowgroups of {N_COLS} columns"
        f" ({reads_per_rg:.1f}/rowgroup) - column chunks are not coalesced")
    assert s["opens"] <= 4, s  # file opened once (+ metadata passes), cached


def test_latency_hidden_by_workers_and_prefetch(wide_ds):
    """With 20 ms per remote call, N workers + pre_buffer must OVERLAP the
    waits: wall time stays well under the serial sum of injected sleeps,
    and within a stated factor of the zero-latency read."""
    t0 = time.perf_counter()
    fs0, _ = latent_filesystem(latency_s=0.0)
    ids = _read_all(wide_ds, fs0, reader_pool_type="thread", workers_count=4)
    local_wall = time.perf_counter() - t0
    assert sorted(ids) == list(range(N_ROWGROUPS * ROWS_PER_RG))

    fs, stats = latent_filesystem(latency_s=0.02)
    t0 = time.perf_counter()
    ids = _read_all(wide_ds, fs, reader_pool_type="thread", workers_count=4)
    wall = time.perf_counter() - t0
    assert sorted(ids) == list(range(N_ROWGROUPS * ROWS_PER_RG))
    s = stats.snapshot()
    assert s["slept_s"] > 0.2, s  # the latency was really injected
    # paid serially, the injected sleeps alone would take slept_s; workers
    # and pre_buffer's up-front ranged reads must overlap them
    assert wall < 0.75 * s["slept_s"] + local_wall + 0.5, (
        f"wall {wall:.2f}s vs {s['slept_s']:.2f}s injected sleep"
        f" (local {local_wall:.2f}s) - remote latency is being paid"
        " serially, not hidden")
    # and the end-to-end factor vs local stays bounded (stated factor: the
    # latent read may cost up to 6x the local wall on this 1-core box; a
    # per-column-read regression would blow far past it)
    assert wall < 6.0 * local_wall + 1.0, (
        f"latent/local = {wall / max(local_wall, 1e-6):.1f}x")


def test_io_retries_compose_with_slow_failing_calls(wide_ds):
    """Slow-then-failing remote reads: the first 3 reads sleep 20 ms then
    raise OSError; io_retries='auto' (armed for non-local filesystems) must
    absorb them and deliver every row exactly once."""
    fs, stats = latent_filesystem(latency_s=0.02, fail_first_reads=3)
    ids = _read_all(wide_ds, fs, reader_pool_type="serial")
    assert sorted(ids) == list(range(N_ROWGROUPS * ROWS_PER_RG))
    s = stats.snapshot()
    assert s["failures_injected"] == 3, s


def test_io_retries_off_surfaces_failure(wide_ds):
    """io_retries=0 on the same slow-failing filesystem surfaces the
    OSError instead of silently retrying - the knob is real."""
    fs, _ = latent_filesystem(latency_s=0.0, fail_first_reads=50)
    with pytest.raises((OSError, PetastormTpuError)):
        _read_all(wide_ds, fs, reader_pool_type="serial", io_retries=0)


def test_row_reader_over_latent_fs(wide_ds):
    """The row path (make_reader) works over the latent filesystem too -
    the wrapper is a real pyarrow filesystem, not a parquet-only shim."""
    fs, stats = latent_filesystem(latency_s=0.005)
    with make_reader(wide_ds, filesystem=fs, shuffle_row_groups=False,
                     num_epochs=1, reader_pool_type="serial",
                     schema_fields=["id"]) as r:
        ids = [int(row.id) for row in r]
    assert sorted(ids) == list(range(N_ROWGROUPS * ROWS_PER_RG))
    assert stats.snapshot()["reads"] > 0
