"""ColumnBatch: the unit of data flowing through the pipeline.

The reference moves python dicts (row path, py_dict_reader_worker.py:100) or
pyarrow Tables (batch path, arrow_reader_worker.py:90) between workers and
consumer.  Here everything downstream of parquet decode is a ColumnBatch: a dict
of numpy arrays (batch-major, contiguous for fixed-shape fields) - the exact form
``jax.device_put`` wants, with zero per-row python in between.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import numpy as np


@dataclasses.dataclass
class ColumnBatch:
    columns: Dict[str, np.ndarray]
    num_rows: int
    #: absolute ventilation ordinal of the work item this batch came from
    #: (set by the decode worker; lets the Reader track the exact contiguous
    #: consumed prefix for checkpoint/resume even when a pool completes items
    #: out of ventilation order)
    ordinal: "int | None" = None

    def __post_init__(self):
        for name, col in self.columns.items():
            if len(col) != self.num_rows:
                raise ValueError(
                    f"Column {name!r} has {len(col)} rows, expected {self.num_rows}")

    def __len__(self) -> int:
        return self.num_rows

    @property
    def field_names(self) -> List[str]:
        return list(self.columns)

    def select(self, names: Sequence[str]) -> "ColumnBatch":
        return ColumnBatch({n: self.columns[n] for n in names}, self.num_rows)

    def slice_rows(self, start: int, stop: int) -> "ColumnBatch":
        stop = min(stop, self.num_rows)
        return ColumnBatch({n: c[start:stop] for n, c in self.columns.items()},
                           max(stop - start, 0))

    def mask_rows(self, mask: np.ndarray) -> "ColumnBatch":
        n = int(np.count_nonzero(mask))
        return ColumnBatch({name: col[mask] for name, col in self.columns.items()}, n)

    def row(self, i: int) -> Dict:
        return {name: col[i] for name, col in self.columns.items()}

    @staticmethod
    def concat(batches: Sequence["ColumnBatch"]) -> "ColumnBatch":
        batches = [b for b in batches if b.num_rows]
        if not batches:
            return ColumnBatch({}, 0)
        if len(batches) == 1:
            # pass through without copying: the hot ingest path (rowgroup size
            # aligned to batch size) would otherwise memcpy every batch.
            # Read-only columns (zero-copy arrow views over mmap'd files) must
            # still be copied - concat always produced writable arrays, and
            # consumers mutate batches in place (e.g. torch normalize).
            b = batches[0]
            frozen = {n for n, c in b.columns.items()
                      if isinstance(c, np.ndarray) and not c.flags.writeable}
            if not frozen:
                return b
            return ColumnBatch(
                {n: (c.copy() if n in frozen else c)
                 for n, c in b.columns.items()}, b.num_rows, ordinal=b.ordinal)
        names = batches[0].field_names
        out = {}
        for name in names:
            cols = [b.columns[name] for b in batches]
            if all(isinstance(c, np.ndarray) and c.dtype != object for c in cols):
                try:
                    out[name] = np.concatenate(cols)
                except ValueError as exc:
                    if "#" in name:
                        # derived jpeg coefficient-plane columns (device
                        # decode): rowgroups with different subsampling have
                        # different plane shapes - surface guidance, not a
                        # bare numpy shape error
                        from petastorm_tpu.errors import CodecError
                        from petastorm_tpu.native.image import \
                            _MIXED_GEOMETRY_GUIDANCE
                        raise CodecError(
                            f"column {name!r}: coefficient-plane shapes differ"
                            f" between rowgroups: {_MIXED_GEOMETRY_GUIDANCE}"
                            ) from exc
                    raise
            else:
                merged = np.empty(sum(len(c) for c in cols), dtype=object)
                i = 0
                for c in cols:
                    merged[i:i + len(c)] = c
                    i += len(c)
                out[name] = merged
        return ColumnBatch(out, sum(b.num_rows for b in batches))
