"""JaxDataLoader integration for packed token streams (ISSUE 11 tentpole
d): (tokens, segment_ids, positions, loss_mask) device arrays, bit-identical
across worker counts when seeded."""

import numpy as np
import pytest

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.sequence import (PackedSequenceReader,
                                    make_packed_sequence_loader,
                                    make_sequence_reader)
from petastorm_tpu.test_util.synthetic import write_token_corpus

jax = pytest.importorskip("jax")


@pytest.fixture(scope="module")
def corpora(tmp_path_factory):
    base = tmp_path_factory.mktemp("loader_corpora")
    urls = []
    for i in range(2):
        url = str(base / f"c{i}")
        write_token_corpus(url, n_docs=60, rows_per_rg=10, mean_len=20,
                           max_len=80, seed=60 + i)
        urls.append(url)
    return urls


def test_packed_reader_protocol(corpora):
    source = make_sequence_reader(corpora[0], shuffle_seed=3)
    with PackedSequenceReader(source, seq_len=64,
                              rows_per_batch=8) as packed:
        assert [f.name for f in packed.schema] == [
            "tokens", "segment_ids", "positions", "loss_mask"]
        assert all(f.shape == (64,) for f in packed.schema)
        assert packed.deterministic == "seed"  # passthrough from source
        assert packed.shuffle_seed == 3
        assert packed.batched_output and packed.ngram is None
        batches = list(packed.iter_batches())
        assert packed.last_row_consumed
        assert all(b.columns["tokens"].shape[1] == 64 for b in batches)
        assert all(b.columns["tokens"].dtype == np.int32 for b in batches)
        diag = packed.diagnostics
        assert diag["packing"]["rows"] == sum(b.num_rows for b in batches)
        assert diag["packing"]["fill_rate"] > 0
        with pytest.raises(PetastormTpuError, match="quiesce"):
            packed.quiesce()
        with pytest.raises(PetastormTpuError, match="quiesce"):
            packed.state_dict()


def test_loader_delivers_device_arrays(corpora):
    with make_packed_sequence_loader(corpora, batch_size=8, seq_len=64,
                                     seed=11, workers_count=2) as loader:
        batches = list(loader)
    assert batches, "no packed batches delivered"
    for b in batches:
        assert set(b) == {"tokens", "segment_ids", "positions", "loss_mask"}
        for name in b:
            assert isinstance(b[name], jax.Array)
            assert b[name].shape == (8, 64)
        toks = np.asarray(b["tokens"])
        segs = np.asarray(b["segment_ids"])
        mask = np.asarray(b["loss_mask"])
        assert ((segs > 0) == (mask > 0)).all()
        assert (toks[mask == 0] == 0).all()


def test_loader_bit_identical_across_workers(corpora):
    def run(workers):
        out = []
        with make_packed_sequence_loader(corpora, batch_size=8, seq_len=64,
                                         seed=11,
                                         workers_count=workers) as loader:
            for b in loader:
                out.append({k: np.asarray(v) for k, v in b.items()})
        return out

    a, b = run(1), run(4)
    assert len(a) == len(b)
    for x, y in zip(a, b):
        for k in x:
            assert (x[k] == y[k]).all(), k


def test_loader_shuffle_buffer_seeded_for_mixed_sources(corpora):
    """The loader's shuffle buffer over a MIXED source derives its RNG from
    the mixer's seed root (the mixer exposes deterministic/shuffle_seed):
    two runs - and two worker counts - compose identical batches even with
    a decorrelation buffer in the path."""
    def run(workers):
        with make_packed_sequence_loader(
                corpora, batch_size=4, seq_len=64, seed=9,
                workers_count=workers,
                loader_kwargs={"shuffling_queue_capacity": 32}) as loader:
            assert loader._reader.deterministic == "seed"
            return [np.asarray(b["tokens"]) for b in loader]

    a, b, c = run(2), run(2), run(4)
    assert len(a) == len(b) == len(c)
    for x, y in zip(a, b):
        assert (x == y).all()
    for x, y in zip(a, c):
        assert (x == y).all()


def test_loader_single_corpus_and_seed_sensitivity(corpora):
    def run(seed):
        with make_packed_sequence_loader(corpora[0], batch_size=4,
                                         seq_len=64, seed=seed,
                                         workers_count=2) as loader:
            return [np.asarray(b["tokens"]) for b in loader]

    a, b, c = run(5), run(5), run(6)
    assert len(a) == len(b) and all((x == y).all() for x, y in zip(a, b))
    assert any((x != y).any() for x, y in zip(a, c))


def test_loader_rejects_shuffle_seed_kwarg(corpora):
    with pytest.raises(PetastormTpuError, match="shuffle_seed"):
        make_packed_sequence_loader(corpora[0], batch_size=4, seq_len=64,
                                    shuffle_seed=3)
