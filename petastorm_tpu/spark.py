"""Spark interop: decoded-row RDD over a petastorm_tpu (or legacy) dataset.

Reference parity: petastorm/spark_utils.py:23-53 - ``dataset_as_rdd`` reads the
parquet store as a Spark DataFrame and decodes each row with the dataset schema's
codecs on the executors, yielding schema namedtuples.

pyspark is not a dependency of this package (TPU ingest does not need a JVM);
everything here gates on its presence at call time.  The Spark *writer* path is
:mod:`petastorm_tpu.converter` (accepts a pyspark DataFrame when available).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

from petastorm_tpu.etl.metadata import open_dataset
from petastorm_tpu.schema import Schema


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as exc:
        raise NotImplementedError(
            "dataset_as_rdd requires pyspark, which is not installed. The"
            " TPU-native consumers are make_reader/make_jax_loader; Spark"
            " interop is optional.") from exc


def decode_row(row: Dict[str, Any], schema: Schema) -> Dict[str, Any]:
    """Apply each field's codec to one storage-form row dict.

    Row-level analog of the columnar decode plane (petastorm_tpu/worker.py);
    exists for executors that hand us rows, like Spark (reference
    utils.py:54-87).
    """
    out = {}
    for field in schema:
        value = row.get(field.name)
        out[field.name] = None if value is None else field.codec.decode(field, value)
    return out


def as_spark_schema(schema: Schema):
    """``pyspark.sql.types.StructType`` for a Schema's STORAGE form.

    Reference parity: ``Unischema.as_spark_schema`` (unischema.py:264-281) -
    the schema handed to ``spark.createDataFrame`` when building a dataset
    from encoded rows (see :func:`dict_to_spark_row`).  Spark types derive
    from each codec's arrow storage type, so images/ndarrays map to
    BinaryType and scalars to their Spark scalar type.
    """
    _require_pyspark()
    from pyspark.sql import types as T

    fields = []
    for field in schema:
        arrow_type = field.codec.storage_type(field)
        fields.append(T.StructField(field.name, _arrow_to_spark_type(arrow_type),
                                    nullable=field.nullable))
    return T.StructType(fields)


def _arrow_to_spark_type(arrow_type):
    import pyarrow as pa
    from pyspark.sql import types as T

    scalars = {
        pa.binary(): T.BinaryType, pa.large_binary(): T.BinaryType,
        pa.string(): T.StringType, pa.large_string(): T.StringType,
        pa.bool_(): T.BooleanType,
        pa.int8(): T.ByteType, pa.int16(): T.ShortType,
        pa.int32(): T.IntegerType, pa.int64(): T.LongType,
        # Spark has no unsigned types: widen to the next signed type
        pa.uint8(): T.ShortType, pa.uint16(): T.IntegerType,
        pa.uint32(): T.LongType, pa.uint64(): T.LongType,
        pa.float16(): T.FloatType, pa.float32(): T.FloatType,
        pa.float64(): T.DoubleType,
        pa.date32(): T.DateType,
    }
    if arrow_type in scalars:
        return scalars[arrow_type]()
    if pa.types.is_timestamp(arrow_type):
        return T.TimestampType()
    if pa.types.is_decimal(arrow_type):
        return T.DecimalType(arrow_type.precision, arrow_type.scale)
    if pa.types.is_list(arrow_type) or pa.types.is_large_list(arrow_type):
        return T.ArrayType(_arrow_to_spark_type(arrow_type.value_type))
    raise NotImplementedError(
        f"No Spark type mapping for arrow storage type {arrow_type}")


def dict_to_spark_row(schema: Schema, row: Dict[str, Any]):
    """Encode one value dict through the schema's codecs into a pyspark Row.

    Reference parity: ``dict_to_spark_row`` (unischema.py:356-403) - the map
    function for building a Spark DataFrame to write through Spark::

        rows_rdd = sc.parallelize(dicts).map(
            lambda d: dict_to_spark_row(schema, d))
        df = spark.createDataFrame(rows_rdd, as_spark_schema(schema))

    Nullability is enforced (a None in a non-nullable field raises, as the
    reference does); missing nullable fields become explicit nulls.
    """
    _require_pyspark()
    from pyspark.sql import Row

    encoded = schema.encode_row(row)
    return Row(**encoded)


def dataset_as_rdd(dataset_url: str, spark_session,
                   schema_fields: Optional[Sequence] = None):
    """Decoded-row RDD of schema namedtuples for a dataset.

    :param dataset_url: dataset URL (any scheme Spark itself can read)
    :param spark_session: a ``pyspark.sql.SparkSession``
    :param schema_fields: optional field names/regexes/Field objects to subset
    """
    _require_pyspark()
    info = open_dataset(dataset_url, require_stored_schema=True)
    schema = info.stored_schema
    df = spark_session.read.parquet(dataset_url)
    if schema_fields is not None:
        schema = schema.view(schema_fields)
        df = df.select(*list(schema.fields))
    # default arguments freeze the objects Spark must ship to executors; the
    # lambda itself must not close over `info` (holds a live filesystem)
    return df.rdd.map(
        lambda row, _schema=schema: _schema.make_namedtuple(
            **decode_row(row.asDict(), _schema)))


__all__ = ["dataset_as_rdd", "decode_row", "as_spark_schema",
           "dict_to_spark_row"]
