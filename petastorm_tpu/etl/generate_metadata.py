"""``petastorm-tpu-generate-metadata``: (re)stamp dataset metadata.

Reference parity: petastorm/etl/petastorm_generate_metadata.py (161 LoC,
console script at setup.py:94) - regenerate ``_common_metadata`` (schema +
per-file rowgroup counts) for a dataset whose metadata is missing or stale,
e.g. after files were added/rewritten by an external engine.

The schema source is, in order: an explicit ``--schema-from`` dataset, the
schema JSON embedded in the data files themselves, or (with ``--infer``)
inference from the arrow schema (scalar columns only, like make_batch_reader).
"""

from __future__ import annotations

import argparse
import logging
import sys
from typing import List, Optional

logger = logging.getLogger(__name__)


def _image_dims(buf: bytes) -> Optional[tuple]:
    """(h, w, c) from a jpeg/png header, or None when unrecognized.

    Header-only parse (no pixel decode): PNG IHDR, or the first jpeg SOF
    frame marker - cheap enough to scan whole columns with.
    """
    if len(buf) < 26:
        return None
    if buf[:8] == b"\x89PNG\r\n\x1a\n":
        w = int.from_bytes(buf[16:20], "big")
        h = int.from_bytes(buf[20:24], "big")
        channels = {0: 1, 2: 3, 3: 3, 4: 2, 6: 4}.get(buf[25])
        return (h, w, channels) if channels else None
    if buf[:2] == b"\xff\xd8":  # jpeg SOI
        i = 2
        sof = {0xC0, 0xC1, 0xC2, 0xC3, 0xC5, 0xC6, 0xC7,
               0xC9, 0xCA, 0xCB, 0xCD, 0xCE, 0xCF}
        while i + 9 < len(buf):
            if buf[i] != 0xFF:
                i += 1
                continue
            marker = buf[i + 1]
            if marker == 0xFF:  # legal fill byte, not a marker
                i += 1
                continue
            if marker in sof:
                h = int.from_bytes(buf[i + 5:i + 7], "big")
                w = int.from_bytes(buf[i + 7:i + 9], "big")
                return (h, w, buf[i + 9])
            if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
                i += 2  # standalone markers have no length field
                continue
            i += 2 + int.from_bytes(buf[i + 2:i + 4], "big")
    return None


def scan_geometries(dataset_url: str,
                    storage_options: Optional[dict] = None,
                    schema=None) -> dict:
    """Scan every variable-shape image column for its distinct geometries.

    Reads only the image columns, streamed one row-group batch at a time,
    and parses encoded HEADERS (no pixel decode, no whole-column
    materialization).  This is the repair path for the dataset-level
    geometry contract (``etl.metadata.declared_geometries``) after files
    were added/rewritten by an external engine - the jax loader's
    'device-mixed' diagnostics point here when they see an undeclared
    geometry.

    ``schema``: pass the already-resolved Schema when the dataset itself has
    none stored yet (the ``--schema-from``/``--infer`` repair flows, which
    run this scan BEFORE stamping).
    """
    import pyarrow.parquet as pq

    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.etl.metadata import infer_or_load_schema, open_dataset

    info = open_dataset(dataset_url, storage_options=storage_options,
                        require_stored_schema=schema is None)
    if schema is None:
        schema = infer_or_load_schema(info)
    fields = [f.name for f in schema
              if isinstance(f.codec, CompressedImageCodec)
              and any(d is None for d in f.shape)]
    if not fields:
        return {}
    geoms: dict = {name: set() for name in fields}
    for path in info.files:
        with info.filesystem.open_input_file(path) as f:
            pf = pq.ParquetFile(f)
            present = [n for n in fields if n in pf.schema_arrow.names]
            if not present:
                continue
            for batch in pf.iter_batches(columns=present):
                for name in present:
                    for cell in batch.column(name):
                        buf = cell.as_py()
                        if buf is None:
                            continue
                        dims = _image_dims(bytes(buf))
                        if dims is not None:
                            geoms[name].add(dims)
    return {name: shapes for name, shapes in geoms.items() if shapes}


def generate_metadata(dataset_url: str,
                      schema_from: Optional[str] = None,
                      infer: bool = False,
                      rescan_geometries: bool = False,
                      storage_options: Optional[dict] = None) -> None:
    from petastorm_tpu.etl.metadata import open_dataset
    from petastorm_tpu.etl.writer import stamp_dataset_metadata

    schema = None
    if schema_from is not None:
        from petastorm_tpu.etl.metadata import infer_or_load_schema
        schema = infer_or_load_schema(
            open_dataset(schema_from, storage_options=storage_options,
                         require_stored_schema=True))
    elif infer:
        from petastorm_tpu.etl.metadata import infer_or_load_schema
        schema = infer_or_load_schema(
            open_dataset(dataset_url, storage_options=storage_options,
                         require_stored_schema=False))
    geometries = None
    if rescan_geometries:
        # keep an empty scan result as {} (not None): the rescan is
        # authoritative, so finding nothing must stamp an empty contract
        # rather than silently preserving the stale one
        geometries = scan_geometries(dataset_url,
                                     storage_options=storage_options,
                                     schema=schema)
    # schema=None -> stamp_dataset_metadata reads the schema JSON from file KV.
    # A rescan saw the WHOLE dataset, so its geometry set REPLACES the stamped
    # one (stale shapes from rewritten files must disappear, not merge).
    stamp_dataset_metadata(dataset_url, schema=schema,
                           storage_options=storage_options,
                           geometries=geometries,
                           merge_geometries=not rescan_geometries)
    logger.info("Stamped metadata for %s", dataset_url)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-generate-metadata",
        description="Regenerate _common_metadata (schema + rowgroup counts)"
                    " for a dataset")
    parser.add_argument("dataset_url")
    parser.add_argument("--schema-from", default=None,
                        help="borrow the stored schema from another dataset URL")
    parser.add_argument("--infer", action="store_true",
                        help="infer the schema from the parquet arrow schema"
                             " when no stored schema exists")
    parser.add_argument("--scan-geometries", action="store_true",
                        help="scan variable-shape image columns (header-only"
                             " parse) and stamp the distinct shapes as the"
                             " dataset-level geometry contract, REPLACING any"
                             " already-stamped shapes (the scan sees the whole"
                             " dataset, so its result is authoritative)")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    generate_metadata(args.dataset_url, schema_from=args.schema_from,
                      infer=args.infer,
                      rescan_geometries=args.scan_geometries)
    print(f"metadata stamped: {args.dataset_url}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
