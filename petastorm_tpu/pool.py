"""Executor pools: the host-side concurrency plane feeding the device pipeline.

Reference parity: petastorm/workers_pool/ (~1,100 LoC) - WorkerBase protocol
(worker_base.py:18-35), ThreadPool with bounded results queue + stop-aware puts +
exception forwarding (thread_pool.py:78-221), zmq-based ProcessPool with spawned
workers, startup barrier, orphan watchdog and slow-joiner workarounds
(process_pool.py:114-428), DummyPool doing work inside get_results
(dummy_pool.py:20-91), and ConcurrentVentilator with bounded in-flight and per-epoch
reshuffle (ventilator.py:55-166).

Design differences (TPU-first):

* **Threads are the default.** pyarrow parquet IO and decode release the GIL, so the
  reference's zmq process plumbing is usually pure overhead on a TPU host VM;
  ``ProcessExecutor`` (multiprocessing.spawn, no zmq) remains for GIL-bound python
  transforms.  Spawn (not fork) for the same reason the reference documents
  (process_pool.py:15-17: forked JVM/arrow handles break).
* **Completion-order results with explicit epoch accounting.** The consumer knows
  exactly how many items each epoch ventilates (ReadPlan is deterministic), so
  epoch-end is a counted event, not a sentinel race.
* Worker exceptions carry the formatted remote traceback and re-raise at the
  consumer (reference thread_pool.py:68-73,169-172).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import traceback
from abc import ABC, abstractmethod
from typing import Any, Callable, Iterable, Optional

from petastorm_tpu.errors import (DEFAULT_REQUEUE_ATTEMPTS,
                                  PetastormTpuError, ReaderClosedError,
                                  classify_error)
from petastorm_tpu.telemetry import resolve as _resolve_telemetry

logger = logging.getLogger(__name__)

_POLL_S = 0.05
DEFAULT_RESULTS_QUEUE_SIZE = 50  # reference: reader.py:61
_MISSING = object()


def _env_seconds(name: str, default: float) -> float:
    """Float env var with a logged fallback (shared with reader.py)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("Ignoring non-numeric %s=%r (using %.0f)",
                       name, raw, default)
        return default


class WorkerError(PetastormTpuError):
    """A worker failed; message includes the remote traceback (when the
    worker lived long enough to produce one).

    Carries the failure-classification metadata the reader's ``on_error``
    policy dispatches on: ``kind`` (``'data'`` = property of the work item,
    skip-eligible; ``'infra'`` = property of the worker, requeue-eligible),
    and - when the failure is attributable to a single work item -
    ``ordinal``, ``item`` and ``exc_type``.  Unattributable failures
    (all workers died, stall abort) keep the defaults and are never
    skippable.
    """

    def __init__(self, message: str, kind: str = "infra", ordinal=None,
                 item=None, exc_type: Optional[str] = None):
        super().__init__(message)
        self.kind = kind
        self.ordinal = ordinal
        self.item = item
        self.exc_type = exc_type


class PipelineStallError(WorkerError):
    """The reader produced no result for ``stall_abort_s`` seconds and
    aborted (``make_reader(stall_abort_s=...)`` /
    ``PETASTORM_TPU_STALL_ABORT_S``).

    Subclasses :class:`WorkerError` (kind ``'infra'``, unattributable - no
    single work item to blame) so existing handlers keep working; carries
    the full pipeline ``diagnostics`` snapshot taken at abort time, so the
    wedged state (stuck workers, queue depths, in-flight items) survives
    into the traceback instead of living only in scrolled-away warnings.
    """

    def __init__(self, message: str, diagnostics: Optional[dict] = None):
        super().__init__(message, kind="infra")
        self.diagnostics = diagnostics or {}


class VentilationCancelled(Exception):
    """An ``executor.put`` blocked on a full queue was withdrawn by its
    cancel_event (Ventilator.pause_and_join with a saturated pipeline); the
    item was NOT enqueued.  Internal control flow, never user-visible."""


class _ResizableSemaphore:
    """Counting semaphore whose bound can change while waiters are blocked.

    The executors' queue bounds live in semaphores (see ThreadedExecutor's
    queue-choice comment); runtime autotuning (petastorm_tpu.autotune) needs
    those bounds adjustable mid-flight.  ``threading.BoundedSemaphore`` bakes
    its bound in at construction, so this replaces it with the same acquire/
    release contract plus ``set_bound``:

    * accounting stays EXACT across a resize: ``in_use`` only moves via
      acquire/release, so every acquired slot must still be released and a
      release without a matching acquire still raises (the BoundedSemaphore
      overdraft guard the pools rely on to catch accounting bugs);
    * shrinking below the current ``in_use`` never strands or cancels held
      slots - new acquires simply block until releases bring ``in_use``
      under the new bound;
    * growing wakes every blocked waiter so freed capacity is used at once.
    """

    __slots__ = ("_bound", "_in_use", "_cond")

    def __init__(self, bound: int):
        if bound < 1:
            raise PetastormTpuError(f"semaphore bound must be >= 1, got {bound}")
        self._bound = int(bound)
        self._in_use = 0
        self._cond = threading.Condition()

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> bool:
        with self._cond:
            if not blocking:
                if self._in_use < self._bound:
                    self._in_use += 1
                    return True
                return False
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while self._in_use >= self._bound:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
            self._in_use += 1
            return True

    def release(self) -> None:
        with self._cond:
            if self._in_use <= 0:
                raise ValueError("semaphore released more times than acquired")
            self._in_use -= 1
            self._cond.notify()

    def set_bound(self, bound: int) -> None:
        """Change the bound; growth wakes all blocked acquirers."""
        if bound < 1:
            raise PetastormTpuError(f"semaphore bound must be >= 1, got {bound}")
        with self._cond:
            self._bound = int(bound)
            self._cond.notify_all()

    @property
    def bound(self) -> int:
        return self._bound

    @property
    def in_use(self) -> int:
        """Slots currently held (== bound means the queue is full)."""
        return self._in_use


class _Failure:
    """A worker exception crossing back to the consumer (picklable)."""

    __slots__ = ("formatted", "kind", "exc_type", "ordinal", "item")

    def __init__(self, exc: BaseException, ordinal=None, item=None):
        self.formatted = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        self.kind = classify_error(exc)
        self.exc_type = type(exc).__name__
        self.ordinal = ordinal
        self.item = item


class _Ok:
    """Success envelope tagging a result with its work-item ordinal, so the
    consumer side can settle the in-flight ledger (requeue dedup: a result
    for an ordinal no longer in flight is a duplicate and is dropped).

    ``attempt`` is the delivering item's attempt number: it lets the
    consumer attribute a hedged ordinal's first delivery to the hedge copy
    vs the original (``liveness.hedge_wins``)."""

    __slots__ = ("ordinal", "value", "attempt")

    def __init__(self, ordinal, value, attempt: int = 0):
        self.ordinal = ordinal
        self.value = value
        self.attempt = attempt

    def __getstate__(self):
        return (self.ordinal, self.value, self.attempt)

    def __setstate__(self, state):
        self.ordinal, self.value = state[0], state[1]
        self.attempt = state[2] if len(state) > 2 else 0


def _worker_error(exc: BaseException, kind: str, ordinal, item) -> WorkerError:
    """One classified WorkerError from a live exception (single place that
    encodes the message/metadata shape, shared with the _Failure path)."""
    failure = _Failure(exc, ordinal=ordinal, item=item)
    return WorkerError(f"Worker failed:\n{failure.formatted}", kind=kind,
                       ordinal=ordinal, item=item, exc_type=failure.exc_type)


#: worker factory: () -> process_fn(item) -> result.  Must be picklable for
#: ProcessExecutor (a module-level class instance holding plain-data config).
WorkerFactory = Callable[[], Callable[[Any], Any]]


class VentilatedItem:
    """A work item tagged with its absolute ventilation ordinal.

    Pools may complete items out of ventilation order; the ordinal lets the
    consumer reconstruct the exact contiguous consumed prefix (the only
    resume cursor that can guarantee no item is ever lost).  Picklable for
    the process pool.

    ``attempt`` counts infra-failure requeues of this ordinal (0 = first
    delivery); it rides the item itself so deterministic fault injection
    (test_util.chaos) can key on it across process boundaries.
    """

    __slots__ = ("ordinal", "item", "attempt")

    def __init__(self, ordinal: int, item: Any, attempt: int = 0):
        self.ordinal = ordinal
        self.item = item
        self.attempt = attempt

    def __getstate__(self):
        return (self.ordinal, self.item, self.attempt)

    def __setstate__(self, state):
        self.ordinal, self.item = state[0], state[1]
        self.attempt = state[2] if len(state) > 2 else 0


class ExecutorBase(ABC):
    """start -> (put*/get*) -> stop -> join lifecycle, mirroring the reference pool
    protocol (start/ventilate/get_results/stop/join).

    Failure handling (docs/operations.md "Failure handling"): work items
    carrying a ventilation ordinal are tracked in an in-flight ledger from
    ``put`` until their result (or attributed failure) is delivered at
    ``get``.  When a worker dies mid-item (process crash/OOM, or a simulated
    crash in tests), the ledger + worker heartbeat identify the lost item and
    it is requeued onto surviving workers up to ``max_requeue_attempts``
    times; the ledger also dedups the rare double delivery (worker died
    after queueing its result but before clearing its heartbeat).
    ``stop_on_failure=False`` (the reader's ``on_error`` skip policies) keeps
    the pool running when a failure is delivered, so the consumer can skip
    the item and keep iterating.
    """

    def __init__(self, telemetry=None, stop_on_failure: bool = True,
                 max_requeue_attempts: int = DEFAULT_REQUEUE_ATTEMPTS,
                 item_deadline_s: Optional[float] = None,
                 hedge_after_s=None):
        self._stopped = False
        self._ventilated = 0
        self._consumed = 0
        self._stop_on_failure = stop_on_failure
        self._max_requeue = max_requeue_attempts
        if item_deadline_s is not None and item_deadline_s <= 0:
            raise PetastormTpuError("item_deadline_s must be > 0 or None")
        if not (hedge_after_s is None or hedge_after_s == "auto"
                or (isinstance(hedge_after_s, (int, float))
                    and hedge_after_s > 0)):
            raise PetastormTpuError(
                "hedge_after_s must be a positive number, 'auto', or None;"
                f" got {hedge_after_s!r}")
        #: liveness knobs (docs/operations.md "Liveness & stragglers"):
        #: an in-flight item older than item_deadline_s gets its worker
        #: killed (process pool) or its slot abandoned (thread pool) and is
        #: requeued; one older than hedge_after_s is speculatively re-issued
        #: to an idle worker, first result wins
        self._item_deadline_s = item_deadline_s
        self._hedge_after = hedge_after_s
        #: ordinal -> latest in-flight VentilatedItem (items without an
        #: ordinal are not tracked: they cannot be requeued or deduped)
        self._inflight: dict = {}
        self._inflight_lock = threading.Lock()
        self._requeued_items = 0
        #: requeued items waiting for an input-queue slot (consumer-thread
        #: state: parked by _reinject, drained by _flush_pending_requeues)
        self._pending_requeue: list = []
        #: liveness ledger (consumer-thread state, like _pending_requeue):
        #: ordinal -> attempt number of its hedge copy, until first delivery
        self._hedged_attempt: dict = {}
        self._hung_workers_killed = 0
        self._hung_workers_abandoned = 0
        self._hedged_items = 0
        self._hedge_wins = 0
        #: petastorm_tpu.telemetry recorder (no-op unless enabled); executors
        #: record queue-full wait time - the signal that tells the pipeline
        #: report whether backpressure points upstream or downstream
        self._telemetry = _resolve_telemetry(telemetry)
        self._m_input_full = self._telemetry.counter("queue.input_full_wait_s")
        self._m_results_full = self._telemetry.counter(
            "queue.results_full_wait_s")
        # queue-depth gauges: stamped at put/get so the metrics sampler's
        # 1 s frames carry a live depth curve (the signal a flight record
        # needs to show "the queue drained, then the stall began")
        self._g_in_depth = self._telemetry.gauge("pool.in_queue_depth")
        self._g_out_depth = self._telemetry.gauge("pool.results_queue_depth")
        self._m_requeued = self._telemetry.counter("errors.requeued_items")
        self._m_hung_killed = self._telemetry.counter(
            "liveness.hung_workers_killed")
        self._m_hung_abandoned = self._telemetry.counter(
            "liveness.hung_workers_abandoned")
        self._m_hedged = self._telemetry.counter("liveness.hedged_items")
        self._m_hedge_wins = self._telemetry.counter("liveness.hedge_wins")

    # -- in-flight ledger (requeue + duplicate suppression) -------------------

    def _track_put(self, item: Any) -> None:
        ordinal = getattr(item, "ordinal", None)
        if ordinal is not None:
            with self._inflight_lock:
                self._inflight[ordinal] = item

    def _settle(self, ordinal) -> bool:
        """Remove ``ordinal`` from the in-flight ledger; False = the ordinal
        was already settled (this delivery is a requeue duplicate)."""
        if ordinal is None:
            return True
        with self._inflight_lock:
            return self._inflight.pop(ordinal, _MISSING) is not _MISSING

    def _try_requeue(self, ordinal, why: str) -> bool:
        """Re-ventilate the in-flight item for ``ordinal`` with its attempt
        count bumped; False when the ordinal is untracked (already
        delivered, or never had an ordinal) or its attempt budget is spent."""
        if ordinal is None:
            return False
        with self._inflight_lock:
            item = self._inflight.get(ordinal)
        if item is None:
            return False
        attempt = getattr(item, "attempt", 0)
        if attempt >= self._max_requeue:
            return False
        retry = VentilatedItem(ordinal, getattr(item, "item", item),
                               attempt + 1)
        with self._inflight_lock:
            self._inflight[ordinal] = retry
        # a crash-requeue supersedes any outstanding hedge of this ordinal:
        # the requeued copy's attempt number would otherwise satisfy the
        # 'attempt >= hedged_at' win test and overcount hedge_wins
        self._hedged_attempt.pop(ordinal, None)
        self._requeued_items += 1
        self._m_requeued.add(1)
        logger.warning("Requeueing work item %s after %s (attempt %d/%d)",
                       ordinal, why, attempt + 1, self._max_requeue)
        self._reinject(retry)
        return True

    def _deliver_failure(self, failure: "_Failure") -> bool:
        """Handle a delivered worker failure.

        Infra-kind failures with an attributable item (e.g. an in-worker
        MemoryError) get the same treatment as a worker death: the item is
        healthy, the worker wasn't - requeue it, budget permitting, and
        return True so the caller keeps polling.  Everything else settles
        the ledger and raises a classified WorkerError.
        """
        if failure.kind == "infra" and self._try_requeue(
                failure.ordinal,
                f"in-worker infra failure ({failure.exc_type})"):
            return True
        self._hedged_attempt.pop(failure.ordinal, None)
        if failure.ordinal is not None and not self._settle(failure.ordinal):
            # late failure for an ordinal that was already settled (a
            # requeued item's sibling delivery won the race): drop it like
            # a duplicate _Ok - the item already reached the consumer, so
            # aborting (raise mode) or double-counting a skip would both
            # corrupt the epoch accounting
            logger.warning("Dropping duplicate failure for already-delivered"
                           " work item %s (%s)", failure.ordinal,
                           failure.exc_type)
            return True
        if self._stop_on_failure:
            self.stop()
        raise WorkerError(f"Worker failed:\n{failure.formatted}",
                          kind=failure.kind, ordinal=failure.ordinal,
                          item=failure.item, exc_type=failure.exc_type)

    def _requeue_lost(self, ordinal, why: str,
                      exhausted_kind: str = "infra") -> None:
        """A worker died (or hung past its deadline) holding ``ordinal``:
        re-ventilate it onto surviving workers, or surface a WorkerError once
        the attempt budget is spent.

        ``exhausted_kind``: classification of the budget-exhausted error.
        Crash/OOM paths keep ``'infra'``; the item-deadline path passes
        ``'data'`` - an item that hung EVERY worker that touched it is a
        property of the item (a pathological decode, a poisoned slow row),
        and under an ``on_error`` skip policy it should quarantine like any
        other data error instead of killing the epoch.
        """
        if ordinal is None or self._try_requeue(ordinal, why):
            return
        with self._inflight_lock:
            item = self._inflight.pop(ordinal, None)
        if item is None:
            # the result was delivered before the worker died: nothing lost
            return
        self._hedged_attempt.pop(ordinal, None)
        if self._stop_on_failure:
            self.stop()
        raise WorkerError(
            f"Work item {ordinal} lost to {why}; requeue budget exhausted"
            f" ({getattr(item, 'attempt', 0)} requeue(s) of max"
            f" {self._max_requeue})"
            + (" - repeatedly hung item, quarantine-eligible"
               if exhausted_kind == "data" else " - possible crash/OOM"),
            kind=exhausted_kind, ordinal=ordinal, item=item)

    # -- liveness: straggler hedging (docs/operations.md) ---------------------

    def _hedge_threshold(self) -> Optional[float]:
        """Resolved hedge age threshold in seconds, or None (hedging off /
        'auto' lacks data).  ``'auto'`` derives the threshold from the
        observed decode latency tail: 4x the telemetry p99, floored at 0.5s,
        once at least 20 decodes have been recorded - so hedging arms itself
        against what 'slow' actually means on this dataset.  'auto' needs
        telemetry enabled in THIS process (thread/serial pools; process-pool
        workers record decode stages in their own processes)."""
        h = self._hedge_after
        if h is None:
            return None
        if h == "auto":
            if not self._telemetry.enabled:
                return None
            hist = self._telemetry.histogram("stage.decode.latency_s")
            if getattr(hist, "count", 0) < 20:
                return None
            return max(4.0 * hist.quantile(0.99), 0.5)
        return float(h)

    def _hedge(self, ordinal, why: str) -> bool:
        """Speculatively re-issue the in-flight item for ``ordinal`` (attempt
        bumped, non-blocking enqueue); the per-ordinal ledger guarantees
        whichever copy finishes second is dropped as a duplicate.  Bounded by
        the same attempt budget as requeues; False = not hedged (already
        hedged, budget spent, input queue full, or ordinal already
        delivered).  Consumer-thread context."""
        if ordinal is None or ordinal in self._hedged_attempt:
            return False
        with self._inflight_lock:
            item = self._inflight.get(ordinal)
        if item is None:
            return False
        attempt = getattr(item, "attempt", 0)
        if attempt >= self._max_requeue:
            return False
        copy = VentilatedItem(ordinal, getattr(item, "item", item),
                              attempt + 1)
        if not self._try_enqueue(copy):
            return False  # no room; re-evaluated on the next poll
        with self._inflight_lock:
            self._inflight[ordinal] = copy
        self._hedged_attempt[ordinal] = attempt + 1
        self._hedged_items += 1
        self._m_hedged.add(1)
        logger.info("Hedging work item %s after %s (speculative attempt"
                    " %d/%d; first result wins)", ordinal, why, attempt + 1,
                    self._max_requeue)
        return True

    def _note_delivery(self, ordinal, attempt: int) -> None:
        """First delivery for ``ordinal`` settled: when it was hedged, decide
        whether the hedge copy won (its attempt number delivered first)."""
        if not self._hedged_attempt:
            return
        hedged_at = self._hedged_attempt.pop(ordinal, None)
        if hedged_at is not None and attempt >= hedged_at:
            self._hedge_wins += 1
            self._m_hedge_wins.add(1)

    def _reinject(self, item: Any) -> None:
        """Re-enqueue a requeued item without ever blocking the consumer
        thread: parked when the input queue is full, drained on later
        ``_flush_pending_requeues`` calls."""
        if not self._try_enqueue(item):
            self._pending_requeue.append(item)

    def _flush_pending_requeues(self) -> None:
        while (self._pending_requeue
               and self._try_enqueue(self._pending_requeue[0])):
            self._pending_requeue.pop(0)

    def _try_enqueue(self, item: Any) -> bool:
        """Non-blocking input-queue insert (pool-specific); False = full."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support requeueing")

    def inflight_capacity(self) -> Optional[int]:
        """Upper bound on distinct work items simultaneously in flight
        through this executor, or None when unknown.  The reader's
        deterministic reorder stage uses it as a cheap gate before the
        exact :meth:`is_inflight` ledger check that tells a straggling
        ordinal (normal - keep draining) from one in nobody's ledger (a
        transport bug worth a loud warning)."""
        return None

    def is_inflight(self, ordinal) -> bool:
        """True while ``ordinal`` is tracked in the in-flight ledger (a
        result or attributed failure will still arrive for it)."""
        with self._inflight_lock:
            return ordinal in self._inflight

    @abstractmethod
    def start(self, worker_factory: WorkerFactory) -> None:
        ...

    @abstractmethod
    def put(self, item: Any, cancel_event=None) -> None:
        """Enqueue a work item; blocks on a full input queue.  When
        ``cancel_event`` is set while blocked, raises VentilationCancelled
        WITHOUT having enqueued the item (quiesce with a full pipeline)."""
        ...

    @abstractmethod
    def get(self, timeout: Optional[float] = None) -> Any:
        ...

    @abstractmethod
    def stop(self) -> None:
        ...

    @abstractmethod
    def join(self) -> None:
        ...

    @property
    def diagnostics(self) -> dict:
        return {"ventilated": self._ventilated, "consumed": self._consumed,
                "requeued_items": self._requeued_items,
                "hung_workers_killed": self._hung_workers_killed,
                "hung_workers_abandoned": self._hung_workers_abandoned,
                "hedged_items": self._hedged_items,
                "hedge_wins": self._hedge_wins,
                "stopped": self._stopped}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        self.join()


class SerialExecutor(ExecutorBase):
    """Synchronous executor: work happens inside ``get`` (reference DummyPool,
    dummy_pool.py:20-91) - for tests, profiling, and debugging.

    The input queue is bounded so a Ventilator with ``num_epochs=None`` cannot
    enqueue unboundedly ahead of the consumer.

    Stall detection: work happens synchronously inside ``get``, so the
    reader-side stall loop (which only runs between ``get`` calls) can never
    observe a work item wedged inside user code.  ONE long-lived daemon
    watchdog thread (started lazily on the first ``get``) therefore polls a
    heartbeat slot: if ``fn(item)`` runs longer than
    ``PETASTORM_TPU_STALL_WARN_S`` a WARNING names the item (once per item).
    ``PETASTORM_TPU_STALL_ABORT_S`` remains inoperative here - synchronous
    user code cannot be safely interrupted from another thread; use the
    thread or process pool when abort matters (docs/operations.md).
    """

    def __init__(self, in_queue_size: int = 32, telemetry=None,
                 stop_on_failure: bool = True,
                 max_requeue_attempts: int = DEFAULT_REQUEUE_ATTEMPTS,
                 item_deadline_s: Optional[float] = None,
                 hedge_after_s=None,
                 stall_warn_s: Optional[float] = None):
        super().__init__(telemetry=telemetry, stop_on_failure=stop_on_failure,
                         max_requeue_attempts=max_requeue_attempts,
                         item_deadline_s=item_deadline_s,
                         hedge_after_s=hedge_after_s)
        if item_deadline_s is not None or hedge_after_s is not None:
            # same limitation as stall-abort: work runs synchronously inside
            # the consumer's get(), so there is no other worker to kill,
            # abandon, or hedge onto (docs/operations.md)
            logger.warning(
                "item_deadline_s/hedge_after_s are inoperative on the serial"
                " executor (work runs inline on the consumer thread); use the"
                " thread or process pool for liveness recovery")
        self._items: "queue.Queue[Any]" = queue.Queue(maxsize=in_queue_size)
        self._fn: Optional[Callable] = None
        self._in_queue_size = in_queue_size
        # per-item watchdog threshold: explicit kwarg (the reader's
        # stall_warn_s - the serial pool is the one flavor whose mid-item
        # stalls the reader-side loop cannot observe) wins over the env var
        self._stall_warn_s = (float(stall_warn_s) if stall_warn_s is not None
                              else _env_seconds("PETASTORM_TPU_STALL_WARN_S",
                                                120.0))
        # heartbeat slot for the watchdog (single writer: the get() caller;
        # same write-order contract as the thread pool's worker_state)
        self._watch_thread: Optional[threading.Thread] = None
        self._watch_item: Any = None     # None = no item in flight
        self._watch_since = 0.0
        self._watch_gen = 0              # one warning per item, not per poll

    def start(self, worker_factory: WorkerFactory) -> None:
        self._fn = worker_factory()

    def inflight_capacity(self) -> int:
        """Serial work completes in ventilation order already; the reorder
        stage never holds more than the inline-retry window."""
        return int(self._in_queue_size) + 8

    def put(self, item: Any, cancel_event=None) -> None:
        t0 = time.perf_counter() if self._telemetry.enabled else None
        while not self._stopped:
            try:
                self._items.put(item, timeout=_POLL_S)
                self._ventilated += 1
                if t0 is not None:
                    self._m_input_full.add(time.perf_counter() - t0)
                return
            except queue.Full:
                if cancel_event is not None and cancel_event.is_set():
                    raise VentilationCancelled()
                continue
        raise ReaderClosedError("Executor is stopped")

    def _watch_loop(self) -> None:
        warned_gen = -1
        poll_s = min(max(self._stall_warn_s / 4.0, 0.05), 5.0)
        while not self._stopped:
            time.sleep(poll_s)
            item = self._watch_item
            if item is None:
                continue
            gen, elapsed = self._watch_gen, time.monotonic() - self._watch_since
            if elapsed > self._stall_warn_s and gen != warned_gen:
                warned_gen = gen
                logger.warning(
                    "Serial executor work item %s has run for %.0fs inside its"
                    " worker function (PETASTORM_TPU_STALL_WARN_S=%.0f);"
                    " pipeline state: %s", getattr(item, "ordinal", "?"),
                    elapsed, self._stall_warn_s, self.diagnostics)

    def get(self, timeout: Optional[float] = None) -> Any:
        if self._fn is None:
            raise PetastormTpuError("Executor not started")
        try:
            item = self._items.get(timeout=timeout or _POLL_S)
        except queue.Empty:
            raise queue.Empty("No ventilated items to process")
        if self._stall_warn_s > 0:
            if self._watch_thread is None:
                self._watch_thread = threading.Thread(
                    target=self._watch_loop, daemon=True,
                    name="petastorm-tpu-serial-watchdog")
                self._watch_thread.start()
            # timestamp and generation BEFORE the item (the watchdog guards
            # on item, so a non-None read sees current since/gen)
            self._watch_since = time.monotonic()
            self._watch_gen += 1
            self._watch_item = item
        current = item
        attempt = getattr(item, "attempt", 0)
        while True:
            try:
                try:
                    result = self._fn(current)
                finally:
                    self._watch_item = None
                # consumed = delivered, matching the thread/process pools:
                # a skipped/failed item must not inflate the count
                self._consumed += 1
                return result
            except BaseException as exc:  # noqa: BLE001 - classified below
                simulated = getattr(exc, "petastorm_tpu_simulated_crash",
                                    False)
                if not simulated and not isinstance(exc, Exception):
                    # KeyboardInterrupt / SystemExit / GeneratorExit are the
                    # CONSUMER's control flow (work runs inline here), never
                    # a work-item failure: propagate untouched in every mode
                    raise
                kind = "infra" if simulated else classify_error(exc)
                ordinal = getattr(current, "ordinal", None)
                if kind == "infra":
                    if attempt < self._max_requeue:
                        # serial "requeue": there is no surviving worker to
                        # move the item to, so retry it inline with the
                        # attempt count bumped (fault injection keys on it;
                        # the local counter bounds retries even for
                        # ordinal-less items)
                        attempt += 1
                        self._requeued_items += 1
                        self._m_requeued.add(1)
                        logger.warning(
                            "Serial worker infra failure on item %s (%s);"
                            " retrying inline (attempt %d/%d)", ordinal,
                            type(exc).__name__, attempt, self._max_requeue)
                        if ordinal is not None:
                            current = VentilatedItem(
                                ordinal, getattr(current, "item", current),
                                attempt)
                        self._watch_since = time.monotonic()
                        self._watch_gen += 1
                        self._watch_item = current
                        continue
                    # budget spent: a classified WorkerError in BOTH modes,
                    # matching the thread/process pools (and a raw
                    # SimulatedWorkerCrash BaseException must never escape
                    # to callers that handle `except Exception`)
                    raise _worker_error(exc, kind, ordinal, current) from exc
                if not self._stop_on_failure:
                    # skip-policy mode: deliver a classified WorkerError the
                    # reader can quarantine, without killing the executor
                    raise _worker_error(exc, kind, ordinal, current) from exc
                raise  # raise mode: propagate the original exception as-is

    def stop(self) -> None:
        self._stopped = True

    def join(self) -> None:
        pass

    @property
    def diagnostics(self) -> dict:
        return {**super().diagnostics,
                "in_queue_size": self._items.qsize()}


class ThreadedExecutor(ExecutorBase):
    """Bounded-queue thread pool (reference ThreadPool, thread_pool.py:78-221).

    pyarrow IO/decompress and cv2 decode release the GIL, so threads scale on
    multi-core TPU host VMs with zero serialization cost.
    """

    def __init__(self, workers_count: int = 3,
                 results_queue_size: int = DEFAULT_RESULTS_QUEUE_SIZE,
                 in_queue_size: Optional[int] = None,
                 profiling_enabled: bool = False,
                 telemetry=None,
                 stop_on_failure: bool = True,
                 max_requeue_attempts: int = DEFAULT_REQUEUE_ATTEMPTS,
                 item_deadline_s: Optional[float] = None,
                 hedge_after_s=None):
        super().__init__(telemetry=telemetry, stop_on_failure=stop_on_failure,
                         max_requeue_attempts=max_requeue_attempts,
                         item_deadline_s=item_deadline_s,
                         hedge_after_s=hedge_after_s)
        self._workers_count = workers_count
        # Queue choice is correctness-driven (hang post-mortem, RESULTS.md):
        # CPython's SimpleQueue.get(timeout) WEDGES under multiple
        # concurrent consumers — when a waiter wins the internal lock but a
        # sibling steals the item before it reacquires the GIL, the
        # remaining timeout is recomputed without clamping and a negative
        # value means an INFINITE lock wait (confirmed by disassembly and
        # reproduced standalone: tools/simplequeue_wedge_repro.py; it froze
        # a full suite run via this very pool).  _in_queue has N worker
        # consumers, so it uses the pure-python queue.Queue, whose
        # Condition-based timeout is correct by construction.  The output
        # side keeps the faster C SimpleQueue: it has exactly ONE consumer
        # (the reader thread), which closes the steal window.  Bounds live
        # in the semaphores either way (reference bounds ventilation at
        # workers_count + 2, reader.py:45-47,412, and treats a non-positive
        # results size as unbounded).
        self._in_queue: "queue.Queue[Any]" = queue.Queue()
        # resizable bounds (petastorm_tpu.autotune adjusts them mid-flight);
        # same exact-accounting contract as the BoundedSemaphores they
        # replaced - see _ResizableSemaphore
        self._in_size_explicit = in_queue_size is not None
        self._in_slots = _ResizableSemaphore(in_queue_size or workers_count + 2)
        self._out_queue: "queue.SimpleQueue[Any]" = queue.SimpleQueue()
        self._out_slots = _ResizableSemaphore(
            results_queue_size if results_queue_size > 0 else 2 ** 30)
        self._stop_event = threading.Event()
        self._threads = []
        self._worker_factory: Optional[WorkerFactory] = None
        # dynamic resize (docs/operations.md "Autotuning"): slots told to
        # retire at their next item boundary, and slots that have retired.
        # A retiring worker finishes its current item, moves itself from
        # _retiring to _retired and exits; retired slots are excluded from
        # fault reaping, liveness accounting and the all-dead check.
        self._retiring: set = set()
        self._retired: set = set()
        self._resize_lock = threading.Lock()
        # True once resize_workers has been called: the worker count is then
        # an explicit TARGET the pool maintains (a slot lost to a death or an
        # abandoned hang is respawned - the thread flavor of the process
        # pool's kill-and-replace).  Never-resized pools keep the static
        # degrade-then-raise semantics PR 3 documented and tests pin.
        self._target_managed = False
        # opt-in worker profiling (reference per-thread cProfile,
        # thread_pool.py:41-49,190-198).  Python 3.12 allows only ONE active
        # profiler process-wide (sys.monitoring), so profiling is SAMPLED: a
        # single designated worker thread is profiled; workers are homogeneous,
        # so its profile is representative of all of them.
        self._profiling_enabled = profiling_enabled
        self._profiles = []
        self._profiles_lock = threading.Lock()
        # per-worker heartbeat: [ordinal-or-None, monotonic-since].  Written
        # only by the owning worker (single-writer per slot, no lock needed);
        # read by diagnostics to attribute a pipeline stall to the exact
        # worker and work item (RESULTS.md hang watch item).
        self._worker_state: list = []
        # fault servicing (consumer-thread-only state): worker indexes whose
        # death has been handled
        self._reaped: set = set()
        # liveness (consumer-thread-only): index -> ordinal it was abandoned
        # on.  A thread cannot be SIGKILLed, so a worker hung past
        # item_deadline_s is ABANDONED: its slot stops counting as a live
        # worker, its item is requeued onto a sibling, and its eventual late
        # result (if the hang ever resolves) is dropped by the ledger.  The
        # entry clears itself if the thread recovers and takes a new item.
        self._abandoned: dict = {}

    def start(self, worker_factory: WorkerFactory) -> None:
        if self._threads:
            raise PetastormTpuError("Executor already started")
        # kept for dynamic grow (resize_workers spawns more slots from it)
        self._worker_factory = worker_factory
        for i in range(self._workers_count):
            fn = worker_factory()
            self._worker_state.append([None, time.monotonic()])
            t = threading.Thread(target=self._worker_loop,
                                 args=(fn, i, self._profiling_enabled and i == 0),
                                 name=f"petastorm-tpu-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def resize_workers(self, n: int) -> int:
        """Grow or shrink the live worker plane to ``n`` threads in place
        (petastorm_tpu.autotune's worker knob; also callable directly).

        Grow spawns fresh worker threads from the factory captured at
        ``start``.  Shrink RETIRES the highest-index live slots: each marked
        worker finishes its current item (no item is ever dropped), then
        exits; its daemon thread, per-slot heartbeat, and the in-flight
        ledger all settle exactly as for a normal completion, so the
        semaphore accounting and the per-ordinal ledger stay exact across
        any resize sequence.  The default input-queue bound tracks
        ``workers + 2`` (an explicit ``in_queue_size`` is left alone).
        Returns the new target count.
        """
        n = max(1, int(n))
        with self._resize_lock:
            self._target_managed = True
            if not self._threads:  # not started: just update the target
                self._workers_count = n
                if not self._in_size_explicit:
                    self._in_slots.set_bound(n + 2)
                return n
            active = self._active_slots()
            if len(active) < n:
                for _ in range(n - len(active)):
                    active.append(self._spawn_slot())
            elif len(active) > n:
                for i in sorted(active, reverse=True)[:len(active) - n]:
                    self._retiring.add(i)
            self._workers_count = n
            if not self._in_size_explicit:
                self._in_slots.set_bound(n + 2)
            return n

    def _active_slots(self) -> list:
        """Indexes of slots that are part of the live worker plane."""
        return [i for i, t in enumerate(self._threads)
                if i not in self._retired and i not in self._retiring
                and i not in self._abandoned and t.is_alive()]

    def _spawn_slot(self) -> int:
        """Start a fresh worker slot, reusing a cleanly-retired slot index
        when one is free, else appending (hold _resize_lock).  Reuse matters
        under autotune: perpetual shrink/grow explore probes would otherwise
        grow ``_threads``/``_worker_state`` without bound, and every fault
        and deadline sweep walks those lists (the process pool already
        respawns into retired slots)."""
        fn = self._worker_factory()
        for i in sorted(self._retired):
            # only slots whose thread has fully exited (a retiring worker
            # marks itself retired just before returning, so a live thread
            # here is mid-exit - it stays reusable for the next grow)
            if not self._threads[i].is_alive():
                self._retired.discard(i)
                self._worker_state[i] = [None, time.monotonic()]
                t = threading.Thread(
                    target=self._worker_loop, args=(fn, i, False),
                    name=f"petastorm-tpu-worker-{i}", daemon=True)
                t.start()
                self._threads[i] = t
                return i
        i = len(self._worker_state)
        # state slot BEFORE the thread list entry: concurrent iterators
        # index worker_state by thread index, so len(threads) <=
        # len(worker_state) must always hold
        self._worker_state.append([None, time.monotonic()])
        t = threading.Thread(target=self._worker_loop, args=(fn, i, False),
                             name=f"petastorm-tpu-worker-{i}", daemon=True)
        t.start()
        self._threads.append(t)
        return i

    def _heal_to_target(self) -> None:
        """Respawn lost slots up to the managed target.  Only once
        resize_workers has put the plane under target management: with a
        controller (or caller) owning the worker count, a slot written off
        to a death or a hung-abandonment must not silently shrink the pool
        below its target - items requeued through the attempt budget need a
        live worker to land on (a shrunk-to-one pool whose survivor hangs
        would otherwise end the epoch with an all-abandoned raise)."""
        if not self._target_managed or not self._threads:
            return
        with self._resize_lock:
            for _ in range(self._workers_count - len(self._active_slots())):
                self._spawn_slot()

    def _trim_recovered(self, index: int) -> None:
        """Retire a just-recovered abandoned slot when it overshoots the
        managed target.  Abandonment on a target-managed pool heals in a
        replacement immediately; a thread cannot be killed, so if its hang
        later resolves the plane would hold target+1 live workers - and
        repeated slow-then-recovering items would grow it monotonically.
        The recovered slot (not the replacement) is the one retired: it
        finishes any in-flight item first, so nothing is dropped."""
        if not self._target_managed:
            return
        with self._resize_lock:
            if len(self._active_slots()) > self._workers_count:
                self._retiring.add(index)

    def set_results_bound(self, n: int) -> int:
        """Resize the results-queue bound in place (autotune's queue knob);
        shrinking below the current depth just blocks producers until the
        consumer drains under the new bound.  Returns the new bound."""
        n = max(1, int(n))
        self._out_slots.set_bound(n)
        return n

    def inflight_capacity(self) -> int:
        """Upper bound on distinct work items simultaneously in flight
        across the input queue, worker slots and results plane (plus slack
        for requeues racing fresh ventilation).  The deterministic reorder
        stage (Reader) uses this to tell "waiting on a straggler" apart
        from "the expected ordinal is in nobody's ledger"."""
        workers = max(len(self._threads), int(self._workers_count))
        return (int(self._in_slots.bound) + workers
                + int(self._out_slots.bound) + workers + 8)

    def _worker_loop(self, fn: Callable, index: int = 0,
                     profile_this_worker: bool = False) -> None:
        state = self._worker_state[index]
        profile = None
        if profile_this_worker:
            import cProfile

            profile = cProfile.Profile()
        while not self._stop_event.is_set():
            if index in self._retiring:
                # retire at the item boundary: mark retired BEFORE exiting so
                # the consumer's reap sweep never mistakes this clean exit
                # for a worker death (the thread stays alive until return).
                # The two set moves are atomic under _resize_lock: a resize
                # landing between them would see this slot in NEITHER set,
                # count it active, and re-retire it - stranding the slot in
                # both sets so its next reuse instantly self-retires
                state[0] = None
                state[1] = time.monotonic()
                with self._resize_lock:
                    self._retiring.discard(index)
                    self._retired.add(index)
                break
            try:
                item = self._in_queue.get(timeout=_POLL_S)
            except queue.Empty:
                continue
            self._in_slots.release()
            # timestamp BEFORE ordinal: a concurrent diagnostics read between
            # the two writes must never pair the new item with the old
            # idle-since time (it would report the whole idle gap as "stuck")
            state[1] = time.monotonic()
            ordinal = getattr(item, "ordinal", None)
            state[0] = ordinal if ordinal is not None else "?"
            try:
                if profile is not None:
                    try:
                        result = profile.runcall(fn, item)
                    except ValueError as exc:
                        # py3.12 allows one active profiler process-wide; if
                        # someone else holds it (second profiling pool, or the
                        # app itself under cProfile), degrade to unprofiled
                        # instead of failing the read
                        if "profiling tool" not in str(exc):
                            raise
                        logger.warning("Worker profiling disabled: %s", exc)
                        profile = None
                        result = fn(item)
                else:
                    result = fn(item)
            except BaseException as exc:  # noqa: BLE001 - forwarded to consumer
                if getattr(exc, "petastorm_tpu_simulated_crash", False):
                    # chaos harness: die like a hard-killed worker - no
                    # result, heartbeat left set so get() can attribute the
                    # lost item and requeue it onto surviving workers
                    return
                result = _Failure(exc, ordinal=ordinal, item=item)
            else:
                result = _Ok(ordinal, result, getattr(item, "attempt", 0))
            self._put_result_stop_aware(result)
            state[0] = None
            state[1] = time.monotonic()
        if profile is not None:
            with self._profiles_lock:
                self._profiles.append(profile)

    def _put_result_stop_aware(self, value: Any) -> None:
        # reference _stop_aware_put (thread_pool.py:200-214): bound via the
        # slot semaphore, never block indefinitely across a stop
        t0 = time.perf_counter() if self._telemetry.enabled else None
        while not self._stop_event.is_set():
            if self._out_slots.acquire(timeout=_POLL_S):
                self._out_queue.put(value)
                if t0 is not None:
                    # time this worker spent blocked on a full results queue:
                    # sustained values mean the CONSUMER is the bottleneck
                    self._m_results_full.add(time.perf_counter() - t0)
                return

    def put(self, item: Any, cancel_event=None) -> None:
        if self._stopped:
            raise ReaderClosedError("Executor is stopped")
        t0 = time.perf_counter() if self._telemetry.enabled else None
        while not self._stop_event.is_set():
            if self._in_slots.acquire(timeout=_POLL_S):
                self._track_put(item)
                self._in_queue.put(item)
                self._ventilated += 1
                if t0 is not None:
                    # time the ventilator spent blocked on a full input queue:
                    # the worker plane is saturated (healthy backpressure)
                    self._m_input_full.add(time.perf_counter() - t0)
                    self._g_in_depth.set(self._in_queue.qsize())
                return
            if cancel_event is not None and cancel_event.is_set():
                # caller withdrew the put while the queue was full (quiesce
                # with a saturated pipeline); the item was NOT enqueued
                raise VentilationCancelled()
        raise ReaderClosedError("Executor stopped while putting")

    def _try_enqueue(self, item: Any) -> bool:
        # consumer-thread context (called from get); never block on a full
        # input queue here - the caller parks the item and retries later
        if self._in_slots.acquire(blocking=False):
            self._in_queue.put(item)
            return True
        return False

    def _service_faults(self) -> None:
        """Reap dead worker threads (requeueing their in-flight items) and
        flush parked requeues.  Runs on the consumer thread between polls -
        deliberately: every liveness mutation (requeue parking, abandonment,
        hedging) stays consumer-thread-only state, so no new locks and no
        races with a separate watchdog thread."""
        self._flush_pending_requeues()
        if self._stop_event.is_set():
            return
        for i, t in enumerate(self._threads):
            if t.is_alive() or i in self._reaped or i in self._retired:
                continue
            # a dead thread still marked _retiring never reached its retire
            # bookkeeping: it died INSIDE fn (e.g. a simulated crash), so it
            # is a genuine death, not a clean retirement
            self._retiring.discard(i)
            self._reaped.add(i)
            ordinal = self._worker_state[i][0]
            logger.warning("Worker thread %d died while on item %s", i,
                           ordinal)
            # clear the dead worker's busy slot BEFORE the (possibly
            # raising) requeue: diagnostics must not report a phantom
            # stuck worker forever (the owner is dead, so this write
            # cannot race it)
            self._worker_state[i][1] = time.monotonic()
            self._worker_state[i][0] = None
            # replace BEFORE the (possibly raising) requeue: a target-managed
            # pool must keep its worker count whether or not the item has
            # budget left
            self._heal_to_target()
            self._requeue_lost(ordinal if isinstance(ordinal, int) else None,
                               f"worker thread {i} death")
        self._check_liveness()
        considered = [(i, t) for i, t in enumerate(self._threads)
                      if i not in self._retired]
        if ((self._reaped or self._abandoned) and considered
                and all(not t.is_alive() or i in self._abandoned
                        for i, t in considered)
                and self._out_queue.empty()):
            # abandoned-as-hung slots count as gone: with every worker dead
            # or written off, queued/requeued items have no one to run them
            # - raising here is the difference between a classified error
            # and the exact indefinite wedge item_deadline_s exists to end
            if self._stop_on_failure:
                self.stop()
            raise WorkerError("All worker threads died or were abandoned as"
                              " hung; no result will arrive", kind="infra")

    def _check_liveness(self) -> None:
        """Item-deadline + hedging sweep over the worker heartbeats
        (consumer-thread context; polled while the consumer waits, which is
        exactly when a hung or straggling item matters).

        Deadline: a slot busy on the same item past ``item_deadline_s`` is
        abandoned (threads cannot be killed; the daemonic thread is excluded
        from liveness accounting and from close-time joins) and its item is
        requeued through the attempt budget - exhaustion surfaces a
        ``'data'``-kind WorkerError so a repeatedly-hanging item quarantines
        under a skip policy.  Hedging: a slot busy past the hedge threshold
        gets its item speculatively re-issued when an idle worker exists;
        the in-flight ledger keeps delivery exactly-once either way.
        """
        deadline = self._item_deadline_s
        hedge_s = (self._hedge_threshold()
                   if self._hedge_after is not None else None)
        if deadline is None and hedge_s is None:
            return
        now = time.monotonic()
        # iterate the thread list, not worker_state: a concurrent grow
        # appends the state slot first, so indexes past len(threads) may
        # exist transiently.  Retired/retiring slots are no longer part of
        # the live worker plane (idle-for-hedging or deadline sweeps).
        idle = any(s[0] is None for i, s in enumerate(self._worker_state)
                   if i < len(self._threads) and i not in self._abandoned
                   and i not in self._retired and i not in self._retiring
                   and self._threads[i].is_alive())
        for i, t in enumerate(self._threads):
            if i in self._retired:
                continue
            s = self._worker_state[i]
            ordinal = s[0]
            if ordinal is None:
                if self._abandoned.pop(i, None) is not None:
                    self._trim_recovered(i)  # recovered and went idle
                continue
            if self._abandoned.get(i) == ordinal:
                continue  # already handled this hang
            if i in self._abandoned:
                del self._abandoned[i]  # recovered onto a new item
                self._trim_recovered(i)
            if not t.is_alive():
                continue  # the reap path owns dead workers
            elapsed = max(0.0, now - s[1])
            if deadline is not None and elapsed > deadline:
                self._abandoned[i] = ordinal
                self._hung_workers_abandoned += 1
                self._m_hung_abandoned.add(1)
                logger.warning(
                    "Worker thread %d hung on item %s for %.1fs >"
                    " item_deadline_s=%.1f; abandoning the slot and"
                    " requeueing the item onto a sibling worker", i, ordinal,
                    elapsed, deadline)
                # a target-managed pool replaces the written-off slot before
                # the (possibly raising) requeue - same contract as the
                # process pool's kill-and-replace
                self._heal_to_target()
                self._requeue_lost(
                    ordinal if isinstance(ordinal, int) else None,
                    f"hung worker thread {i} (exceeded item deadline"
                    f" {deadline:.1f}s)", exhausted_kind="data")
                continue
            if (hedge_s is not None and elapsed > hedge_s and idle
                    and self._hedge(
                        ordinal if isinstance(ordinal, int) else None,
                        f"straggling {elapsed:.1f}s on worker thread {i}"
                        f" (hedge threshold {hedge_s:.1f}s)")):
                idle = False  # one speculative copy per sweep

    def get(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                result = self._out_queue.get(timeout=_POLL_S)
            except queue.Empty:
                self._service_faults()
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                continue
            # releases are bounded by successful gets, which are bounded by
            # acquired puts: a ValueError here would be a real accounting bug
            self._out_slots.release()
            if isinstance(result, _Failure):
                if self._deliver_failure(result):
                    continue  # infra failure absorbed by a requeue
            if not self._settle(result.ordinal):
                # requeue duplicate (original result surfaced after its
                # worker died): drop it - the first delivery already counted
                continue
            self._note_delivery(result.ordinal, getattr(result, "attempt", 0))
            self._consumed += 1
            if self._telemetry.enabled:
                self._g_out_depth.set(self._out_queue.qsize())
                self._g_in_depth.set(self._in_queue.qsize())
            return result.value

    def stop(self) -> None:
        self._stopped = True
        self._stop_event.set()

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for worker threads.  ``timeout`` (total, across all workers)
        bounds the wait when a worker may be wedged inside user code — e.g.
        after a stall abort: the threads are daemonic, so abandoning them
        cannot block process exit, and a warning names what was abandoned."""
        if not self._stopped:
            raise PetastormTpuError("call stop() before join()")
        if timeout is None and (self._item_deadline_s is not None
                                or self._hedge_after is not None):
            # liveness-enabled pools already accept abandoning wedged daemon
            # workers mid-epoch; an unbounded close-time join would trade the
            # hang the deadline/hedge just recovered from for a close hang
            timeout = 5.0
        deadline = None if timeout is None else time.monotonic() + timeout
        for i, t in enumerate(self._threads):
            if i in self._abandoned:
                continue  # known-hung: daemonic, never joins - skip the wait
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        alive = [t.name for t in self._threads if t.is_alive()]
        if alive:
            logger.warning(
                "Abandoning %d wedged daemon worker thread(s) %s after %.0fs;"
                " pipeline state: %s", len(alive), alive, timeout or 0,
                self.diagnostics)
        if self._profiling_enabled and self._profiles:
            stats = self.profile_stats()
            if stats is not None:
                import io as _io

                out = _io.StringIO()
                stats.stream = out
                stats.sort_stats("cumulative").print_stats(20)
                logger.info("Sampled worker profile (top 20 by cumulative):\n%s",
                            out.getvalue())

    def profile_stats(self):
        """``pstats.Stats`` of the sampled worker thread, or None when
        profiling was off / the sampled worker ran no item yet."""
        import pstats

        with self._profiles_lock:
            profiles = [p for p in self._profiles if p.getstats()]
            if not profiles:
                return None
            stats = pstats.Stats(profiles[0])
            for p in profiles[1:]:
                stats.add(p)
            return stats

    @property
    def diagnostics(self) -> dict:
        now = time.monotonic()
        # snapshot each slot's ordinal ONCE: the worker may clear it between
        # a guard and a second read, which would emit a spurious None entry
        busy = []
        for i, s in enumerate(self._worker_state):
            ordinal = s[0]
            if ordinal is not None:
                # clamp: the worker may stamp a newer time between our `now`
                # snapshot and this read
                busy.append((i, ordinal, round(max(0.0, now - s[1]), 3)))
        return {**super().diagnostics,
                "in_queue_size": self._in_queue.qsize(),
                "results_queue_size": self._out_queue.qsize(),
                "workers_count": self._workers_count,
                # resizable bounds (autotune knobs) + retired-slot count so a
                # resize trajectory is reconstructible post-mortem
                "in_queue_bound": self._in_slots.bound,
                "results_queue_bound": self._out_slots.bound,
                "workers_retired": len(self._retired),
                # [(worker index, item ordinal, seconds on it)] for workers
                # currently inside fn(item) - a stalled pipeline names the
                # exact worker and work item instead of wedging silently
                "workers_busy": busy,
                # liveness: slots written off as hung (still daemon-alive,
                # excluded from worker accounting and close-time joins)
                "workers_abandoned": sorted(self._abandoned)}


class _CrashSafeResultsChannel:
    """Bounded results transport whose writes happen synchronously in the
    worker's only thread.

    ``mp.Queue`` delivers through a per-process background *feeder* thread
    that serializes frames onto a pipe shared by every writer, under a
    shared write lock.  A worker that dies abruptly (OOM kill, chaos
    ``os._exit``) while its feeder holds that lock abandons the lock: every
    surviving worker's feeder then blocks forever, the consumer starves on
    an apparently non-empty queue (``qsize`` counts buffered puts that will
    never reach the pipe), and the epoch wedges with a live-but-mute worker
    plane.  Reproduced as the intermittent chaos-kill hang in
    tests/test_fault_tolerance.py::test_chaos_e2e_poison_kill_and_weather.

    Here ``put`` sends the frame from the worker's MAIN thread (its only
    thread) under a cross-process lock.  The abrupt-death styles this pool
    must survive land inside ``fn`` (chaos ``os._exit``, the simulated-crash
    hook) or via the liveness SIGKILL sweep, which already refuses to kill
    a delivering worker - so a death can no longer interleave with a
    half-written frame or an abandoned write lock.  Backpressure comes from
    a slot semaphore (acquired by the writer, released by the consumer
    after ``recv``), matching ``mp.Queue(maxsize)`` semantics;
    ``bound <= 0`` means unbounded, like ``mp.Queue``.

    Two deliberate residual tradeoffs.  (1) A death the pool does NOT
    control - a kernel OOM kill or external SIGKILL landing exactly inside
    ``send`` - can still orphan the write lock and leave a partial frame
    that blocks the consumer's ``recv`` past its poll timeout; the pool's
    own kill paths cannot land there, and ``mp.Queue`` wedged under a
    strictly larger set of death styles.  (2) Sends serialize under the one
    write lock, so siblings queue behind a large in-flight frame; with the
    shm transport (the default where the native module builds) frames are
    small descriptors and the lock is held microseconds.  Per-worker pipes
    would remove both by construction - the upgrade path if either bites.
    """

    def __init__(self, ctx, bound: int):
        self._rx, self._tx = ctx.Pipe(duplex=False)
        self._wlock = ctx.Lock()
        self._bound = int(bound)
        self._slots = ctx.BoundedSemaphore(self._bound) if bound > 0 else None

    def put(self, obj, stop_event, wait_cell=None) -> bool:
        """Worker-side enqueue; False = dropped (shutdown/closed channel).

        ``wait_cell``: optional ``(shared double array, slot index)`` that
        accumulates the seconds this worker spent BLOCKED on a full channel
        (slot-semaphore waits only; an uncontended acquire records nothing).
        Single-writer per slot; the parent harvests deltas into the
        ``queue.results_full_wait_s`` counter so the autotune controller's
        consumer-bound signal works across the process boundary."""
        if self._slots is not None and not self._slots.acquire(block=False):
            t0 = time.perf_counter()
            while not self._slots.acquire(timeout=_POLL_S):
                if stop_event.is_set():
                    return False
            if wait_cell is not None:
                arr, i = wait_cell
                arr[i] += time.perf_counter() - t0
        try:
            with self._wlock:
                self._tx.send(obj)
        except (OSError, ValueError):
            # consumer gone (read end closed at join); nothing to deliver to
            if self._slots is not None:
                try:
                    self._slots.release()
                except ValueError:
                    pass
            return False
        return True

    def get(self, timeout: Optional[float] = None):
        """Parent-side dequeue; raises ``queue.Empty`` on timeout (the
        ``mp.Queue.get`` contract the poll loops are written against)."""
        if not self._rx.poll(timeout):
            raise queue.Empty
        obj = self._rx.recv()
        if self._slots is not None:
            try:
                self._slots.release()
            except ValueError:
                pass
        return obj

    def qsize(self) -> int:
        if self._slots is None:
            raise NotImplementedError("unbounded channel has no depth gauge")
        # in-flight = bound - free slots (sem_getvalue; absent on macOS,
        # where this raises NotImplementedError like mp.Queue.qsize)
        return self._bound - self._slots.get_value()

    def worker_init(self) -> None:
        """Child-side setup: drop the inherited read end.  Every spawned
        worker receives a dup of ``_rx`` through the Process args; while any
        of those dups stays open, the parent's :meth:`close` cannot turn a
        blocked ``send`` into an EPIPE - the pipe would still have a
        nominal reader."""
        try:
            self._rx.close()
        except OSError:
            pass

    def close(self) -> None:
        """Parent-side teardown: closing the read end makes any sender
        still blocked in ``send`` fail with EPIPE instead of leaking
        (requires every worker to have dropped its inherited ``_rx`` dup
        via :meth:`worker_init`)."""
        for conn in (self._rx, self._tx):
            try:
                conn.close()
            except OSError:
                pass


def _process_worker_main(worker_factory, in_queue, out_queue, stop_event,
                         index=0, heartbeats=None, retire_flags=None,
                         full_waits=None):
    """Worker-process entrypoint (module-level: must be picklable for spawn).

    ``heartbeats``: optional lock-free shared double array, 3 slots per
    worker: [ordinal (-1 = idle), wall-clock since, delivering flag] — same
    stall-attribution contract as ThreadedExecutor's ``workers_busy``,
    crossing the process boundary via shared memory.  Wall clock
    (time.time), not monotonic: monotonic clocks are not comparable across
    processes on all platforms.  Reads of the (ordinal, since) PAIR can
    tear: each 8-byte slot is individually atomic but the pair is not.  The
    write order here (timestamp BEFORE ordinal) plus the double-read
    validation on the reading side (``_ProcessExecutor._read_heartbeat``:
    ordinal, timestamp, ordinal again, retry when the ordinal moved)
    guarantees a sample never pairs a new ordinal with a stale timestamp —
    a torn pair can no longer report a bogus stall (PR 1 caveat, since
    fixed).  One residual caveat alongside the wall-clock one: the 8-byte
    slot writes themselves are plain unsynchronized RawArray stores, and
    their per-slot atomicity is an x86-64 property (aligned 8-byte stores
    are single-copy atomic there).  On architectures without that guarantee
    a reader could in principle observe a HALF-WRITTEN double inside one
    slot — bounded to one garbage (ordinal, since) sample in a diagnostics
    sweep (the next sweep re-reads fresh values, and the reading side
    clamps negative ages), never control-flow corruption, since the kill
    sweep re-reads post-mortem before acting.

    ``retire_flags``: optional shared byte array, one flag per slot; a
    nonzero flag tells this worker to exit cleanly at its next item
    boundary (dynamic pool shrink, ``_ProcessExecutor.resize_workers``).
    The current item always completes and delivers first.

    ``full_waits``: optional shared double array, one cell per slot,
    accumulating the seconds this worker spent blocked on a full results
    channel (single writer per cell, same torn-store caveat as the
    heartbeats).  The parent folds deltas into the
    ``queue.results_full_wait_s`` counter on its ``get()`` path, so the
    autotune controller's consumer-bound signal crosses the process
    boundary.

    The heartbeat doubles as the crash ledger: a worker that dies mid-item
    (OOM kill, segfault) leaves its ordinal in the slot, which is how the
    parent knows exactly which work item to requeue onto surviving workers.

    The ``delivering`` slot (-1.0 = no) flips to the ordinal between
    finishing the work function and completing the result enqueue.  The
    liveness kill sweep (``_check_liveness``) refuses to SIGKILL a
    delivering worker: a kill landing inside the channel's ``send`` would
    orphan the shared write lock and deadlock every other worker's
    ``out_queue.put`` forever (``_CrashSafeResultsChannel`` keeps every
    OTHER abrupt-death style off that lock by sending from this thread).
    The ordinal slot deliberately stays set until AFTER the put, preserving
    crash attribution for a death mid-delivery (the ledger requeues it; a
    double delivery dedups).
    """
    out_queue.worker_init()  # drop the inherited read end (see channel docs)
    try:
        fn = worker_factory()
    except BaseException as exc:  # noqa: BLE001
        out_queue.put(_Failure(exc), stop_event)
        return
    if hasattr(fn, "stop_event"):  # shm encoder: abort full-arena waits on stop
        fn.stop_event = stop_event
    base = 3 * index
    while not stop_event.is_set():
        if retire_flags is not None and retire_flags[index]:
            # retire at the item boundary (pool shrink): ack with 2 BEFORE
            # exiting so the parent can promote the slot to retired without
            # having to observe the process death in a fault sweep
            retire_flags[index] = 2
            break
        try:
            item = in_queue.get(timeout=_POLL_S)
        except queue.Empty:
            continue
        if item is _ProcessExecutor._STOP_SENTINEL_VALUE:
            break
        ordinal = getattr(item, "ordinal", None)
        try:
            hb_ordinal = float(ordinal)
        except (TypeError, ValueError):
            hb_ordinal = -2.0  # busy, ordinal unknown
        if heartbeats is not None:
            # timestamp before ordinal (same reasoning as the thread pool:
            # a concurrent read must never pair a new item with an old time)
            heartbeats[base + 1] = time.time()
            heartbeats[base] = hb_ordinal
        try:
            result = _Ok(ordinal, fn(item), getattr(item, "attempt", 0))
        except BaseException as exc:  # noqa: BLE001
            if getattr(exc, "petastorm_tpu_simulated_crash", False):
                # chaos harness: die exactly like an OOM kill - no result,
                # no traceback, heartbeat left naming the in-flight item
                os._exit(17)
            result = _Failure(exc, ordinal=ordinal, item=item)
        if heartbeats is not None:
            heartbeats[base + 2] = hb_ordinal  # delivering: do not SIGKILL
        out_queue.put(result, stop_event,
                      wait_cell=(None if full_waits is None
                                 else (full_waits, index)))
        if heartbeats is not None:
            heartbeats[base] = -1.0
            heartbeats[base + 1] = time.time()
            heartbeats[base + 2] = -1.0


class _ProcessExecutor(ExecutorBase):
    """Spawned multiprocessing pool for GIL-bound worker functions.

    Replaces the reference's zmq ProcessPool (process_pool.py:114-428): spawn
    semantics and exception forwarding are kept; the zmq data plane, startup
    barrier, and slow-joiner workarounds fall away because multiprocessing queues
    provide them.  Daemon processes make the parent-death watchdog
    (process_pool.py:324-331) unnecessary.
    """

    _STOP_SENTINEL_VALUE = "__petastorm_tpu_stop__"

    #: default shared-memory arena size for the native data plane
    DEFAULT_SHM_BYTES = 256 * 2**20

    def __init__(self, workers_count: int = 3,
                 results_queue_size: int = DEFAULT_RESULTS_QUEUE_SIZE,
                 in_queue_size: Optional[int] = None,
                 use_shm: Optional[bool] = None,
                 shm_size_bytes: int = DEFAULT_SHM_BYTES,
                 telemetry=None,
                 stop_on_failure: bool = True,
                 max_requeue_attempts: int = DEFAULT_REQUEUE_ATTEMPTS,
                 item_deadline_s: Optional[float] = None,
                 hedge_after_s=None,
                 max_workers: Optional[int] = None):
        # telemetry: the PARENT process records ventilation/queue waits;
        # worker-side stage metrics recorded in the spawned processes stay
        # there (PETASTORM_TPU_TELEMETRY is inherited, so each child records
        # independently) - thread pool gives one merged report
        super().__init__(telemetry=telemetry, stop_on_failure=stop_on_failure,
                         max_requeue_attempts=max_requeue_attempts,
                         item_deadline_s=item_deadline_s,
                         hedge_after_s=hedge_after_s)
        import multiprocessing as mp

        self._ctx = mp.get_context("spawn")
        self._workers_count = workers_count
        # shared-memory slot capacity for dynamic grow (resize_workers): the
        # heartbeat/retire RawArrays cannot be extended after start, so slots
        # are pre-allocated up to this ceiling.  ``max_workers`` (autotune's
        # policy bound) sizes it explicitly; the default leaves generous
        # headroom without materializing hundreds of unused slots.
        self._slot_capacity = max(workers_count,
                                  max_workers if max_workers
                                  else min(4 * workers_count, 32))
        self._in_queue_size = in_queue_size or workers_count + 2
        self._in_queue = self._ctx.Queue(self._in_queue_size)
        # NOT an mp.Queue: its async feeder thread can wedge every surviving
        # writer when a worker dies abruptly (see _CrashSafeResultsChannel)
        self._results_queue_size = results_queue_size
        self._out_queue = _CrashSafeResultsChannel(self._ctx,
                                                   results_queue_size)
        self._stop_event = self._ctx.Event()
        self._procs = []
        self._worker_factory = None
        self._reaped: set = set()
        # dynamic resize (docs/operations.md "Autotuning"): slots flagged to
        # retire at their next item boundary, and slots whose worker has
        # exited cleanly after retirement
        self._retiring: set = set()
        self._retired: set = set()
        self._retire_flags = None
        # RLock: resize_workers calls _promote_retirements (which now locks
        # itself - the consumer's fault sweep and diagnostics promote too,
        # and an unlocked promotion racing a locked grow can exile a freshly
        # respawned worker from fault reaping)
        self._resize_lock = threading.RLock()
        # True once resize_workers has been called: the count becomes an
        # explicit target the pool maintains, so a crashed worker is
        # respawned into its slot instead of permanently shrinking the plane
        # (never-resized pools keep the PR 2 degrade-then-raise semantics)
        self._target_managed = False
        self._arena = None
        self._heartbeats = None
        # per-slot full-channel wait accumulators (seconds), harvested into
        # queue.results_full_wait_s as deltas on the parent's get() path
        self._full_waits = None
        self._full_wait_harvested = 0.0
        self._shm_size_bytes = shm_size_bytes
        if use_shm is None:  # auto: use the native transport when it builds
            from petastorm_tpu.native import is_available

            use_shm = is_available()
        self._use_shm = use_shm

    def start(self, worker_factory: WorkerFactory) -> None:
        if self._procs:
            raise PetastormTpuError("Executor already started")
        if self._use_shm:
            from petastorm_tpu.native import SharedArena
            from petastorm_tpu.native.transport import ShmResultEncoder

            self._arena = SharedArena.create(self._shm_size_bytes)
            worker_factory = ShmResultEncoder(worker_factory, self._arena.name)
        # kept for hung-worker kill-and-replace respawns (_check_liveness)
        # and dynamic grow (resize_workers)
        self._worker_factory = worker_factory
        # lock-free heartbeat slots (single-writer per triple; see
        # _process_worker_main) - powers workers_busy across processes.
        # Allocated at slot CAPACITY, not current count: RawArrays cannot
        # grow, and resize_workers spawns into the spare slots.
        self._heartbeats = self._ctx.RawArray("d", 3 * self._slot_capacity)
        self._retire_flags = self._ctx.RawArray("b", self._slot_capacity)
        # single writer per cell (the slot's worker; same torn-store caveat
        # as the heartbeats); a respawn into the slot keeps accumulating
        self._full_waits = self._ctx.RawArray("d", self._slot_capacity)
        for i in range(self._slot_capacity):
            self._heartbeats[3 * i] = -1.0
            self._heartbeats[3 * i + 2] = -1.0
        for i in range(self._workers_count):
            self._procs.append(self._spawn_worker(i))

    def _spawn_worker(self, index: int):
        """Spawn (or respawn) the worker process for slot ``index``; the
        heartbeat pair at that index is reused (single writer at a time: a
        replacement is only spawned after its predecessor is confirmed
        dead)."""
        p = self._ctx.Process(
            target=_process_worker_main,
            args=(self._worker_factory, self._in_queue, self._out_queue,
                  self._stop_event, index, self._heartbeats,
                  self._retire_flags, self._full_waits),
            name=f"petastorm-tpu-worker-{index}", daemon=True)
        p.start()
        return p

    @property
    def max_resize_workers(self) -> int:
        """Hard ceiling on ``resize_workers`` targets (shared-memory slot
        capacity, fixed at construction)."""
        return self._slot_capacity

    def _promote_retirements(self) -> None:
        """Move retiring slots whose worker ACKED the retire flag (wrote 2
        at its item boundary, _process_worker_main) to retired.  The ack is
        written before the process exits, so promotion does not depend on a
        fault sweep happening to observe the death."""
        with self._resize_lock:
            for i in list(self._retiring):
                if self._retire_flags[i] == 2:
                    self._retiring.discard(i)
                    self._retired.add(i)
                    self._heartbeats[3 * i + 1] = time.time()
                    self._heartbeats[3 * i] = -1.0
                    self._heartbeats[3 * i + 2] = -1.0

    def inflight_capacity(self) -> int:
        """Upper bound on distinct work items simultaneously in flight (see
        ThreadedExecutor.inflight_capacity; same contract for the process
        plane: input queue + worker slots + results channel + slack)."""
        workers = max(len(self._procs), int(self._workers_count))
        results = (self._results_queue_size if self._results_queue_size > 0
                   else 2 ** 30)
        return int(self._in_queue_size) + workers + int(results) + workers + 8

    def resize_workers(self, n: int) -> int:
        """Grow or shrink the worker-process plane to ``n`` in place
        (petastorm_tpu.autotune's worker knob).

        Grow reuses cleanly-retired slots first (clearing their retire
        flag), then spawns into spare pre-allocated slots, capped at
        ``max_resize_workers``.  Shrink flags the highest-index live slots
        to retire: each worker finishes and DELIVERS its current item, then
        exits at the item boundary, so the per-ordinal ledger and epoch
        accounting stay exact.  Returns the new target count (clamped to
        the slot capacity).
        """
        n = max(1, min(int(n), self._slot_capacity))
        with self._resize_lock:
            self._target_managed = True
            if not self._procs:  # not started: just update the target
                self._workers_count = n
                return n
            self._promote_retirements()  # acked slots are reusable for grow
            active = [i for i, p in enumerate(self._procs)
                      if i not in self._retired and i not in self._retiring
                      and p.is_alive()]
            if len(active) < n:
                for i in sorted(self._retired):
                    if len(active) >= n:
                        break
                    self._retire_flags[i] = 0
                    self._retired.discard(i)
                    self._reaped.discard(i)
                    self._heartbeats[3 * i + 1] = time.time()
                    self._heartbeats[3 * i] = -1.0
                    self._heartbeats[3 * i + 2] = -1.0
                    self._procs[i] = self._spawn_worker(i)
                    active.append(i)
                while len(active) < n and len(self._procs) < self._slot_capacity:
                    i = len(self._procs)
                    self._procs.append(self._spawn_worker(i))
                    active.append(i)
            elif len(active) > n:
                for i in sorted(active, reverse=True)[:len(active) - n]:
                    self._retiring.add(i)
                    self._retire_flags[i] = 1
            self._workers_count = n
            return n

    def put(self, item: Any, cancel_event=None) -> None:
        if self._stopped:
            raise ReaderClosedError("Executor is stopped")
        t0 = time.perf_counter() if self._telemetry.enabled else None
        # ledger entry BEFORE the enqueue: a fast worker's result can reach
        # the consumer's _settle before this thread runs again, and an
        # unregistered ordinal would make that legitimate delivery look like
        # a requeue duplicate (silently dropped -> lost rows)
        self._track_put(item)
        try:
            while True:
                try:
                    self._in_queue.put(item, timeout=_POLL_S)
                    self._ventilated += 1
                    if t0 is not None:
                        self._m_input_full.add(time.perf_counter() - t0)
                        try:  # mp.Queue.qsize raises on some platforms
                            self._g_in_depth.set(self._in_queue.qsize())
                        except NotImplementedError:
                            pass
                    return
                except queue.Full:
                    if self._stopped:
                        raise ReaderClosedError("Executor stopped while putting")
                    if cancel_event is not None and cancel_event.is_set():
                        raise VentilationCancelled()
        except BaseException:
            # the item never made it into the queue: retract the ledger
            # entry so it cannot be mistaken for lost in-flight work
            self._settle(getattr(item, "ordinal", None))
            raise

    def _read_heartbeat(self, index: int):
        """Torn-read-safe sample of worker ``index``'s heartbeat pair.

        The worker writes timestamp-then-ordinal; each 8-byte slot is atomic
        but the pair is not.  Reading ordinal, timestamp, ordinal-again and
        retrying while the ordinal moved guarantees the returned timestamp
        belongs to (or postdates) the returned ordinal - a torn pair can
        never pair a NEW ordinal with a STALE timestamp and report a bogus
        stall.  Returns (ordinal float, since float): -1.0 = idle, -2.0 =
        busy on an ordinal-less item.
        """
        hb = self._heartbeats
        base = 3 * index
        ordinal = hb[base]
        since = hb[base + 1]
        for _ in range(3):
            again = hb[base]
            if again == ordinal:
                break
            ordinal = again
            since = hb[base + 1]
        return ordinal, since

    def _is_delivering(self, index: int) -> bool:
        """True while worker ``index`` is between finishing its work
        function and completing the result enqueue (kill-unsafe window)."""
        return self._heartbeats[3 * index + 2] != -1.0

    def _try_enqueue(self, item: Any) -> bool:
        try:
            self._in_queue.put_nowait(item)
            return True
        except queue.Full:
            return False

    def _service_faults(self) -> None:
        """Reap dead worker processes, requeueing the item each one held
        (named by its crash-ledger heartbeat), and flush parked requeues."""
        self._flush_pending_requeues()
        if self._stopped or self._stop_event.is_set():
            return
        self._promote_retirements()
        for i, p in enumerate(self._procs):
            if p.is_alive() or i in self._reaped or i in self._retired:
                continue
            with self._resize_lock:
                retiring = i in self._retiring
                if retiring:
                    # the flagged worker exited: a clean retirement unless
                    # its heartbeat still names an in-flight item (it died
                    # INSIDE fn while retiring - a genuine crash, requeue)
                    self._retiring.discard(i)
                    self._retired.add(i)
                    hb_ordinal, _since = self._read_heartbeat(i)
                    self._heartbeats[3 * i + 1] = time.time()
                    self._heartbeats[3 * i] = -1.0
                    self._heartbeats[3 * i + 2] = -1.0
            if retiring:
                if hb_ordinal >= 0:
                    self._requeue_lost(
                        int(hb_ordinal),
                        f"worker process {i} death during retirement"
                        f" (exit code {p.exitcode})")
                continue
            self._reaped.add(i)
            ordinal = None
            if self._heartbeats is not None:
                hb_ordinal, _since = self._read_heartbeat(i)
                if hb_ordinal >= 0:
                    ordinal = int(hb_ordinal)
                elif hb_ordinal == -2.0:
                    logger.warning(
                        "Worker process %d died holding an ordinal-less work"
                        " item; it cannot be requeued", i)
            logger.warning(
                "Worker process %d (pid %s) died with exit code %s while on"
                " item %s (possible crash/OOM)", i, p.pid, p.exitcode,
                ordinal if ordinal is not None else "<none>")
            if self._heartbeats is not None:
                # clear the crash ledger BEFORE the (possibly raising)
                # requeue so diagnostics never report a phantom stuck
                # worker (the owner is dead; no write race)
                self._heartbeats[3 * i + 1] = time.time()
                self._heartbeats[3 * i] = -1.0
                self._heartbeats[3 * i + 2] = -1.0
            if self._target_managed:
                # target-managed plane (resize_workers was called): respawn
                # the slot BEFORE the (possibly raising) requeue so the pool
                # holds its target whether or not the item has budget left -
                # but never overshoot it (this death may already be absorbed
                # by a pending shrink that excluded the dead slot)
                with self._resize_lock:
                    active = [j for j, q in enumerate(self._procs)
                              if j != i and j not in self._retired
                              and j not in self._retiring and q.is_alive()]
                    if len(active) < self._workers_count:
                        self._reaped.discard(i)
                        self._procs[i] = self._spawn_worker(i)
            self._requeue_lost(
                ordinal, f"worker process {i} death (exit code {p.exitcode})")
        self._check_liveness()
        # Residual window, deliberately NOT reconciled: a SIGKILL landing in
        # the few instructions between a worker's in_queue.get and its
        # heartbeat stamp loses the item without naming it (the ledger holds
        # it, nobody delivers it).  Detecting that state from here would
        # need mp.Queue emptiness, which is advisory (the feeder thread
        # buffers) - a reconciliation attempt built on it demonstrably
        # misfired on healthy pipelines.  The stall watchdog
        # (stall_warn_s / stall_abort_s) is the designated backstop for
        # exactly this class of unattributable loss.

    def _check_liveness(self) -> None:
        """Item-deadline + hedging sweep over the shared-memory heartbeats
        (consumer-thread context, like the requeue machinery).

        Deadline: a worker whose heartbeat names the same in-flight item for
        longer than ``item_deadline_s`` is SIGKILLed - the only interruption
        that reaches a worker wedged in a blocking C call or a deadlocked
        native library - and REPLACED with a fresh spawn at the same slot;
        the item is requeued through the attempt budget, so a genuinely
        poisoned slow item eventually surfaces as a quarantine-eligible
        ``'data'`` error.  Hedging: an item past the hedge threshold is
        speculatively re-issued when an idle worker exists; the per-ordinal
        ledger dedups whichever copy loses.
        """
        deadline = self._item_deadline_s
        hedge_s = (self._hedge_threshold()
                   if self._hedge_after is not None else None)
        if ((deadline is None and hedge_s is None)
                or self._heartbeats is None or not self._procs):
            return
        now = time.time()  # heartbeats are wall-clock (cross-process)
        idle = False
        busy = []
        for i, p in enumerate(self._procs):
            if not p.is_alive() or i in self._retired:
                continue
            hb_ordinal, since = self._read_heartbeat(i)
            if hb_ordinal == -1.0:
                if i not in self._retiring:  # an exiting slot can't hedge
                    idle = True
            else:
                busy.append((i, p, hb_ordinal, max(0.0, now - since)))
        for i, p, hb_ordinal, elapsed in busy:
            ordinal = int(hb_ordinal) if hb_ordinal >= 0 else None
            if self._is_delivering(i):
                # the worker finished its work function and is mid-enqueue:
                # SIGKILLing now could orphan the results channel's shared
                # write lock (held inside the worker's synchronous send)
                # and deadlock every other worker's put forever.  The
                # result is moments away; skip this sweep.  (The consumer only runs this sweep
                # while starving, so the pipe is drained and the delivery
                # window is short - not a loophole a truly hung worker can
                # hide in: a hang wedges INSIDE fn, before the flag flips.)
                continue
            if deadline is not None and elapsed > deadline:
                logger.warning(
                    "Worker process %d (pid %s) hung on item %s for %.1fs >"
                    " item_deadline_s=%.1f; SIGKILLing and respawning", i,
                    p.pid, ordinal if ordinal is not None else "?", elapsed,
                    deadline)
                if self._is_delivering(i):
                    continue  # flipped between the first check and the kill
                p.kill()
                p.join(timeout=10)
                # re-read AFTER death: the pre-kill sample may be stale (the
                # worker can have finished that item and started another
                # before the signal landed); the post-mortem heartbeat is the
                # authoritative crash ledger
                hb_ordinal, _since = self._read_heartbeat(i)
                ordinal = int(hb_ordinal) if hb_ordinal >= 0 else None
                self._heartbeats[3 * i + 1] = time.time()
                self._heartbeats[3 * i] = -1.0
                self._heartbeats[3 * i + 2] = -1.0
                self._hung_workers_killed += 1
                self._m_hung_killed.add(1)
                with self._resize_lock:
                    if i in self._retiring:
                        # the hung worker was already flagged to retire:
                        # killing it completes the retirement; do not
                        # respawn the slot
                        self._retiring.discard(i)
                        self._retired.add(i)
                    else:
                        # replace BEFORE the (possibly raising) requeue: the
                        # pool must keep its worker count whether or not the
                        # item has budget left
                        self._procs[i] = self._spawn_worker(i)
                self._requeue_lost(
                    ordinal, f"hung worker process {i} SIGKILLed after"
                    f" exceeding item deadline {deadline:.1f}s",
                    exhausted_kind="data")
                continue
            if (hedge_s is not None and elapsed > hedge_s and idle
                    and self._hedge(
                        ordinal, f"straggling {elapsed:.1f}s on worker"
                        f" process {i} (hedge threshold {hedge_s:.1f}s)")):
                idle = False  # one speculative copy per sweep

    def get(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                result = self._out_queue.get(timeout=_POLL_S)
            except queue.Empty:
                self._service_faults()
                if deadline is not None and time.monotonic() > deadline:
                    raise
                active = [p for i, p in enumerate(self._procs)
                          if i not in self._retired]
                if active and not any(p.is_alive() for p in active):
                    if self._stop_on_failure:
                        self.stop()
                    raise WorkerError("All worker processes died (possible crash/OOM);"
                                      " no result will arrive", kind="infra")
                continue
            if isinstance(result, _Failure):
                if self._deliver_failure(result):
                    continue  # infra failure absorbed by a requeue
            ordinal, value = ((result.ordinal, result.value)
                              if isinstance(result, _Ok) else (None, result))
            settled = self._settle(ordinal)
            if self._arena is not None:
                from petastorm_tpu.native.transport import (decode_batch,
                                                            slot_column_count)

                if self._telemetry.enabled:
                    # parent-side proof of the zero-copy decode path: columns
                    # the worker decoded DIRECTLY into arena batch slots
                    # (child-process counters never reach this registry)
                    slots = slot_column_count(value)
                    if slots:
                        self._telemetry.counter("decode.batch_slots").add(slots)
                # decode duplicates too: the encoded descriptor pins arena
                # slots that only the decoded view's lifetime releases
                value = decode_batch(self._arena, value)
            if not settled:
                continue  # requeue duplicate: first delivery already counted
            self._note_delivery(ordinal, getattr(result, "attempt", 0))
            self._consumed += 1
            if self._telemetry.enabled:
                try:  # mp.Queue.qsize raises on some platforms
                    self._g_out_depth.set(self._out_queue.qsize())
                    self._g_in_depth.set(self._in_queue.qsize())
                except NotImplementedError:
                    pass
                self._harvest_full_waits()
            return value

    def _harvest_full_waits(self) -> None:
        """Fold the workers' accumulated blocked-on-full-channel seconds
        (shared ``_full_waits`` cells, written by ``_process_worker_main``'s
        ``out_queue.put``) into ``queue.results_full_wait_s`` as deltas, so
        the consumer-bound signal is visible to the sampler and the autotune
        controller despite the waits happening in child processes."""
        if self._full_waits is None:
            return
        total = sum(self._full_waits)
        delta = total - self._full_wait_harvested
        if delta > 0:
            self._full_wait_harvested = total
            self._m_results_full.add(delta)

    def stop(self) -> None:
        self._stopped = True
        self._stop_event.set()

    def join(self) -> None:
        if not self._stopped:
            raise PetastormTpuError("call stop() before join()")
        # close the results channel FIRST: a worker parked in a blocking
        # send (consumer abandoned mid-epoch with a large frame in flight)
        # gets EPIPE immediately and exits at its next stop_event check,
        # instead of burning the full 5s join timeout per worker
        self._out_queue.close()
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self._in_queue.cancel_join_thread()
        if self._arena is not None:
            # consumer-side batches may still hold zero-copy views; close()
            # defers the unmap until they are collected
            self._arena.close()

    @property
    def diagnostics(self) -> dict:
        if self._retire_flags is not None:
            self._promote_retirements()  # count acked shrinks sweep-free
        diag = {**super().diagnostics, "workers_count": self._workers_count,
                "workers_alive": sum(p.is_alive()
                                     for i, p in enumerate(self._procs)
                                     if i not in self._retired),
                "workers_retired": len(self._retired),
                "shm_transport": self._arena is not None}
        try:  # mp.Queue.qsize raises NotImplementedError on some platforms
            diag["in_queue_size"] = self._in_queue.qsize()
            diag["results_queue_size"] = self._out_queue.qsize()
        except NotImplementedError:
            pass
        if self._heartbeats is not None:
            now = time.time()
            busy = []
            for i in range(len(self._procs)):
                if i in self._retired:
                    continue
                # double-read-validated pair: a torn read can no longer pair
                # a new ordinal with a stale timestamp (bogus stall)
                ordinal, since = self._read_heartbeat(i)
                if ordinal != -1.0:  # -1 = idle; -2 = busy, ordinal unknown
                    # clamp: the worker may stamp a newer wall-clock time
                    # between our `now` snapshot and this read (and
                    # time.time() can step backwards under NTP)
                    busy.append((i, int(ordinal) if ordinal >= 0 else "?",
                                 round(max(0.0, now - since), 3)))
            diag["workers_busy"] = busy
        if self._arena is not None:
            diag["shm_free_bytes"] = self._arena.free_bytes()
        return diag


def parse_hedge_after(value: str):
    """CLI string -> ``hedge_after_s`` value: ``'auto'`` or a positive
    float.  Raises ValueError (argparse renders it as a usage error when
    used as a ``type=``) on anything else - shared by the throughput and
    diagnose CLIs."""
    if value == "auto":
        return "auto"
    try:
        parsed = float(value)
    except ValueError:
        raise ValueError(
            f"expected a number of seconds or 'auto', got {value!r}")
    if parsed <= 0:
        raise ValueError("hedge threshold must be > 0 seconds")
    return parsed


def make_executor(kind: str = "thread", workers_count: int = 3,
                  results_queue_size: int = DEFAULT_RESULTS_QUEUE_SIZE,
                  telemetry=None, stop_on_failure: bool = True,
                  max_requeue_attempts: int = DEFAULT_REQUEUE_ATTEMPTS,
                  item_deadline_s: Optional[float] = None,
                  hedge_after_s=None,
                  stall_warn_s: Optional[float] = None,
                  max_workers: Optional[int] = None) -> ExecutorBase:
    """'thread' | 'process' | 'serial' (reference: reader_pool_type, reader.py:139-150).

    ``stop_on_failure=False`` keeps the pool alive when a worker failure is
    delivered at ``get`` (the reader's ``on_error`` skip policies);
    ``max_requeue_attempts`` bounds the transparent re-ventilation of items
    lost to worker crashes.  ``item_deadline_s``/``hedge_after_s`` arm the
    liveness layer (hung-worker kill/abandon + straggler hedging; serial
    pools cannot enforce either - the work runs inline on the consumer).
    ``stall_warn_s`` reaches the serial pool's per-item watchdog (the one
    flavor whose mid-item stalls the reader-side loop cannot observe);
    thread/process pools take their stall thresholds from the reader.
    ``max_workers`` sizes the process pool's pre-allocated resize slot
    capacity (``resize_workers`` / petastorm_tpu.autotune can grow the pool
    up to it); thread pools grow without a pre-allocated ceiling.
    """
    if kind == "thread":
        return ThreadedExecutor(workers_count, results_queue_size,
                                telemetry=telemetry,
                                stop_on_failure=stop_on_failure,
                                max_requeue_attempts=max_requeue_attempts,
                                item_deadline_s=item_deadline_s,
                                hedge_after_s=hedge_after_s)
    if kind == "process":
        return _ProcessExecutor(workers_count, results_queue_size,
                                telemetry=telemetry,
                                stop_on_failure=stop_on_failure,
                                max_requeue_attempts=max_requeue_attempts,
                                item_deadline_s=item_deadline_s,
                                hedge_after_s=hedge_after_s,
                                max_workers=max_workers)
    if kind in ("serial", "dummy"):
        return SerialExecutor(telemetry=telemetry,
                              stop_on_failure=stop_on_failure,
                              max_requeue_attempts=max_requeue_attempts,
                              item_deadline_s=item_deadline_s,
                              hedge_after_s=hedge_after_s,
                              stall_warn_s=stall_warn_s)
    raise PetastormTpuError(f"Unknown executor kind {kind!r}")


class Ventilator:
    """Background thread feeding epoch work-items into an executor.

    Reference: ConcurrentVentilator (ventilator.py:55-166).  Backpressure comes
    from the executor's bounded input queue; per-epoch ordering comes from the
    deterministic ReadPlan, so this thread holds no shuffle state.
    """

    def __init__(self, executor: ExecutorBase, plan, num_epochs: Optional[int] = 1,
                 start_item: int = 0, telemetry=None,
                 release_window: Optional[int] = None,
                 release_progress=None):
        if num_epochs is not None and num_epochs < 1:
            raise PetastormTpuError("num_epochs must be >= 1 or None (infinite)")
        if start_item < 0:
            raise PetastormTpuError("start_item must be >= 0")
        self._executor = executor
        self._plan = plan
        self._num_epochs = num_epochs
        self._start_item = start_item
        # deterministic-delivery backpressure (docs/operations.md
        # "Reproducibility"): with a release window, ordinal v is not handed
        # to the executor until v < release_progress() + release_window.
        # The executor's queue bounds alone do NOT bound the reader's
        # reorder stage - a single straggling rowgroup frees its queue
        # slots to later items one by one while the reorder stage holds
        # every completed batch past it, so without this window the held
        # set could grow toward a whole epoch of decoded batches.  The
        # window must be at least the executor's in-flight capacity or it
        # would deadlock the very items the release is waiting on.
        self._release_window = release_window
        self._release_progress = release_progress
        self._telemetry = _resolve_telemetry(telemetry)
        if self._telemetry.enabled:
            # visible (as "no samples yet") in reports and --watch frames
            # even before the first item is handed to the executor
            register = getattr(self._telemetry, "register_stage", None)
            if register is not None:
                register("ventilate")
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.items_per_epoch = len(plan.epoch_items(0))
        #: absolute ordinal AFTER the last item actually handed to the
        #: executor (== items guaranteed to flow through to the consumer);
        #: exact once the thread is joined (see pause_and_join)
        self.ventilated = start_item

    @property
    def total_items(self) -> Optional[int]:
        """Items this ventilator will emit (excludes skipped resume prefix)."""
        if self._num_epochs is None:
            return None
        # plans know their own totals (ElasticResumePlan's leftover epoch is
        # shorter than its subsequent epochs)
        return max(self._plan.total_items(self._num_epochs) - self._start_item, 0)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="petastorm-tpu-ventilator",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        # resume: skip whole epochs cheaply, then a within-epoch offset
        if self.items_per_epoch > 0:
            epoch = self._start_item // self.items_per_epoch
            offset = self._start_item % self.items_per_epoch
        else:
            epoch, offset = 0, 0
        ordinal = self._start_item  # absolute position in the full item stream
        while not self._stop_event.is_set():
            if self._num_epochs is not None and epoch >= self._num_epochs:
                return
            tele = self._telemetry
            # same counter object the executor's put updates (same registry
            # name), and put runs in THIS thread - so the delta across one
            # put is exactly that put's queue-full wait
            m_blocked = tele.counter("queue.input_full_wait_s")
            for item in self._plan.epoch_items(epoch)[offset:]:
                if self._stop_event.is_set():
                    return
                if self._release_window is not None:
                    # deterministic-delivery window: never run more than one
                    # window ahead of the reader's release point (bounds the
                    # reorder stage's memory; see __init__)
                    while (ordinal >= self._release_progress()
                           + self._release_window):
                        if self._stop_event.wait(0.01):
                            return
                try:
                    if tele.enabled:
                        # ventilate busy time must EXCLUDE time blocked on a
                        # full input queue (tracked by the executor as
                        # queue.input_full_wait_s), or a consumer-bound
                        # pipeline would crown 'ventilate' the dominant stage
                        # for doing nothing but waiting
                        t0 = time.perf_counter_ns()
                        blocked0 = m_blocked.value
                        self._executor.put(VentilatedItem(ordinal, item),
                                           cancel_event=self._stop_event)
                        dur_ns = time.perf_counter_ns() - t0
                        blocked_ns = int((m_blocked.value - blocked0) * 1e9)
                        tele.record_stage("ventilate", t0,
                                          max(dur_ns - blocked_ns, 0),
                                          {"ordinal": ordinal})
                    else:
                        self._executor.put(VentilatedItem(ordinal, item),
                                           cancel_event=self._stop_event)
                except (ReaderClosedError, VentilationCancelled):
                    return
                ordinal += 1
                self.ventilated = ordinal
            offset = 0
            epoch += 1

    def stop(self) -> None:
        self._stop_event.set()

    def join(self) -> None:
        if self._thread is not None:
            self._thread.join()

    def pause_and_join(self) -> int:
        """Stop issuing new work items and wait for the thread; returns the
        exact count of items ventilated (items already handed to the executor
        still flow through to the consumer - nothing is retracted).  The
        quiesce half of drain-to-cursor checkpointing (Reader.quiesce)."""
        self._stop_event.set()
        self.join()
        return self.ventilated
