"""Sequence dataset surface (ISSUE 11 tentpole a + satellite): the
variable-length list codec round-trips directly across all three executor
flavors (incl. None cells and empty lists), make_reader refuses sequence
fields in the image-only knobs with clear guidance, and worker-side
predicate pushdown provably skips decode for filtered documents."""

import numpy as np
import pytest

from petastorm_tpu.codecs import ScalarListCodec
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.predicates import in_lambda, in_set
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.sequence import (is_sequence_field, iter_documents,
                                    make_sequence_reader, token_field)

#: rows exercising every variable-length wire form: ragged lists, empty
#: lists, None cells (nullable), plus a scalar id to key assertions by
VARLEN_ROWS = [
    {"id": 0, "tokens": [1, 2, 3]},
    {"id": 1, "tokens": []},                  # empty list
    {"id": 2, "tokens": None},                # null cell
    {"id": 3, "tokens": [7]},
    {"id": 4, "tokens": [5, 5, 5, 5, 5]},
    {"id": 5, "tokens": [9, 8]},
    {"id": 6, "tokens": []},
    {"id": 7, "tokens": [4, 4, 4]},
]


@pytest.fixture(scope="module")
def varlen_dataset(tmp_path_factory):
    url = str(tmp_path_factory.mktemp("varlen") / "ds")
    schema = Schema("VarLen", [Field("id", np.int64),
                               token_field("tokens", nullable=True)])
    write_dataset(url, schema, VARLEN_ROWS, row_group_size_rows=2)
    return url


def _expected(i):
    t = VARLEN_ROWS[i]["tokens"]
    return None if t is None else list(t)


@pytest.mark.parametrize("pool", ["thread", "process", "serial"])
def test_varlen_roundtrip_batch_reader(varlen_dataset, pool):
    """Direct ScalarListCodec roundtrip through each executor flavor: None
    cells and empty lists survive the full decode + transport path
    (process pools cross the shm/pickle boundary)."""
    got = {}
    with make_batch_reader(varlen_dataset, reader_pool_type=pool,
                           workers_count=2, shuffle_row_groups=False,
                           num_epochs=1) as reader:
        assert is_sequence_field(reader.schema["tokens"])
        for batch in reader.iter_batches():
            ids = batch.columns["id"]
            col = batch.columns["tokens"]
            for j in range(batch.num_rows):
                cell = col[j]
                got[int(ids[j])] = (None if cell is None
                                    else np.asarray(cell).tolist())
    assert got == {i: _expected(i) for i in range(len(VARLEN_ROWS))}


@pytest.mark.parametrize("pool", ["thread", "serial"])
def test_varlen_roundtrip_row_reader(varlen_dataset, pool):
    """The row path (make_reader namedtuples) round-trips the same cells."""
    got = {}
    with make_reader(varlen_dataset, reader_pool_type=pool, workers_count=2,
                     shuffle_row_groups=False, num_epochs=1) as reader:
        for row in reader:
            got[int(row.id)] = (None if row.tokens is None
                                else np.asarray(row.tokens).tolist())
    assert got == {i: _expected(i) for i in range(len(VARLEN_ROWS))}


def test_varlen_uniform_rowgroup_fast_path(tmp_path):
    """Uniform-length rowgroups take the 2-D vectorized decode path;
    iter_documents flattens both wire forms identically."""
    url = str(tmp_path / "uniform")
    schema = Schema("U", [Field("id", np.int64), token_field("tokens")])
    rows = [{"id": i, "tokens": [i] * 4} for i in range(12)]
    write_dataset(url, schema, rows, row_group_size_rows=4)
    with make_batch_reader(url, shuffle_row_groups=False,
                           num_epochs=1) as reader:
        batches = list(reader.iter_batches())
        assert any(b.columns["tokens"].dtype != object for b in batches)
    with make_sequence_reader(url, shuffle_row_groups=False,
                              deterministic="seed", num_epochs=1) as reader:
        docs = list(iter_documents(reader, "tokens"))
    assert [d.tolist() for d in docs] == [[i] * 4 for i in range(12)]
    assert all(d.dtype == np.int32 for d in docs)


def test_iter_documents_skips_null_cells(varlen_dataset):
    # deterministic='seed': plan-order delivery even unshuffled (without it
    # a loaded pool delivers in completion order and this assert is racy)
    with make_sequence_reader(varlen_dataset, shuffle_row_groups=False,
                              deterministic="seed", num_epochs=1) as reader:
        docs = [d.tolist() for d in iter_documents(reader, "tokens")]
    # None skipped; empty lists delivered (the packer skips those)
    assert docs == [e for e in (_expected(i) for i in range(8))
                    if e is not None]


def test_iter_documents_max_documents(varlen_dataset):
    with make_sequence_reader(varlen_dataset, shuffle_row_groups=False,
                              num_epochs=1) as reader:
        docs = list(iter_documents(reader, "tokens", max_documents=2))
    assert len(docs) == 2


# -- make_sequence_reader validation ------------------------------------------

def test_sequence_reader_unknown_field(varlen_dataset):
    with pytest.raises(PetastormTpuError, match="not in the dataset schema"):
        make_sequence_reader(varlen_dataset, tokens_field="nope")


def test_sequence_reader_non_sequence_field(varlen_dataset):
    with pytest.raises(PetastormTpuError,
                       match="not a variable-length sequence column"):
        make_sequence_reader(varlen_dataset, tokens_field="id")


def test_token_field_shape_and_codec():
    f = token_field("t", dtype=np.int64, nullable=True)
    assert f.shape == (None,) and isinstance(f.codec, ScalarListCodec)
    assert f.dtype == np.dtype(np.int64) and f.nullable
    assert is_sequence_field(f)
    assert not is_sequence_field(Field("x", np.int64))


# -- satellite: clear make_reader errors for sequence fields ------------------

def test_decode_roi_on_sequence_field_clear_error(varlen_dataset):
    with pytest.raises(PetastormTpuError,
                       match="variable-length sequence field"):
        make_batch_reader(varlen_dataset,
                          decode_roi={"tokens": (0, 0, 4, 4)})


def test_decode_placement_on_sequence_field_clear_error(varlen_dataset):
    # via make_reader: the row factory shares the validation path
    with pytest.raises(PetastormTpuError,
                       match="variable-length sequence field"):
        make_reader(varlen_dataset,
                    decode_placement={"tokens": "device"})


# -- worker-side predicate pushdown (acceptance criterion) --------------------

@pytest.fixture(scope="module")
def labeled_corpus(tmp_path_factory):
    from petastorm_tpu.test_util.synthetic import write_token_corpus

    url = str(tmp_path_factory.mktemp("labeled") / "corpus")
    write_token_corpus(url, n_docs=120, rows_per_rg=10, mean_len=16,
                       max_len=64, seed=9)
    return url


def test_predicate_pushdown_skips_decode_for_filtered_rows(labeled_corpus):
    """Filtered documents never cost token decode: the predicate column
    decodes first, the mask filters the arrow table, and only survivors
    reach the token column's decode.  Observable proof:
    ``sequence.rows_filtered`` counts the drops while
    ``worker.rows_decoded`` counts ONLY the survivors."""
    from petastorm_tpu.telemetry import Telemetry

    with make_batch_reader(labeled_corpus, shuffle_row_groups=False,
                           num_epochs=1) as reader:
        all_labels = [str(x) for b in reader.iter_batches()
                      for x in b.columns["lang"]]
    kept_expected = sum(1 for x in all_labels if x == "l0")
    assert 0 < kept_expected < len(all_labels)

    tele = Telemetry()
    with make_batch_reader(labeled_corpus, shuffle_row_groups=False,
                           predicate=in_set({"l0"}, "lang"),
                           telemetry=tele, num_epochs=1) as reader:
        kept = sum(b.num_rows for b in reader.iter_batches())
    assert kept == kept_expected
    snap = tele.snapshot()["counters"]
    assert snap["sequence.rows_filtered"] == len(all_labels) - kept_expected
    # the decode counter delta: only survivors were decoded
    assert snap["worker.rows_decoded"] == kept_expected


def test_predicate_on_doc_length_column(labeled_corpus):
    """The n_tokens scalar makes length filtering a pushdown predicate -
    short docs are dropped before their token lists decode."""
    with make_batch_reader(
            labeled_corpus, shuffle_row_groups=False, num_epochs=1,
            predicate=in_lambda(
                ["n_tokens"], lambda cols: cols["n_tokens"] >= 16,
                vectorized=True)) as reader:
        for batch in reader.iter_batches():
            lens = [len(t) for t in batch.columns["tokens"]]
            assert all(n >= 16 for n in lens)
            assert (np.asarray(batch.columns["n_tokens"]) ==
                    np.asarray(lens)).all()
