"""Continuous metrics sampling: a time-series ring buffer over a Telemetry.

The PR-1 registry answers "what happened" (cumulative counters); a production
ingest serving long epochs needs "what is happening NOW" and "what was
happening right before it died" (tf.data's input-pipeline analyzer samples
continuously for exactly this reason - PAPERS.md, arxiv 2101.12127 section 4).
This module adds both:

* :class:`MetricsSampler` - a background daemon thread that snapshots the
  registry every ``interval_s`` (default 1 s) into a bounded ring of
  time-series points: counter deltas become per-second **rates**, gauges keep
  their **last value**, and stage latency histograms yield **per-interval
  p50/p99** (quantiles of only the executions that landed in that interval,
  not the run-so-far blur).  One snapshot per second over a few hundred
  instruments is microseconds of work - cheap enough to leave on in
  production.
* the **flight recorder** (:func:`flight_record` / :func:`dump_flight_record`)
  - on a terminal pipeline failure the last ``window_s`` of sampled series
  plus the tail of the trace buffer are serialized, so the crash artifact
  carries the throughput/queue-depth/stall curves leading INTO the failure,
  not just final counters.  The reader wires this to ``PipelineStallError``,
  terminal ``WorkerError``, ``ErrorBudgetExceededError`` and circuit-open
  aborts (``make_reader(flight_record_path=)`` /
  ``PETASTORM_TPU_FLIGHT_RECORD=``).

Sample-point schema (plain JSON-serializable dicts)::

    {"t": <registry uptime_s>,      # sample time on the report's wall clock
     "wall_time": <time.time()>,    # absolute, for cross-process alignment
     "dt_s": <measured interval>,
     "counters": {name: total},     # raw cumulative totals
     "rates": {name: (total - prev)/dt},         # per-second
     "gauges": {name: last_value},
     "stages": {name: {"count": total, "rate_per_s": ..., "busy_frac": ...,
                       "p50_s": ..., "p99_s": ...}}}   # p50/p99 None when the
                                                       # interval saw no op
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

#: default sampling interval; overridable per reader
#: (``make_reader(sample_interval_s=)``) or process-wide via
#: ``PETASTORM_TPU_SAMPLE_INTERVAL_S``
DEFAULT_INTERVAL_S = 1.0

#: default ring capacity: 10 minutes of 1 s points
DEFAULT_MAX_POINTS = 600

#: default flight-recorder window (seconds of sampled series kept)
DEFAULT_FLIGHT_WINDOW_S = 60.0

#: default trace-tail length carried by a flight record
DEFAULT_TRACE_TAIL = 200


def _delta_hist_quantile(prev: Optional[Dict], cur: Dict, q: float
                         ) -> Optional[float]:
    """Quantile of the observations recorded BETWEEN two histogram snapshots
    (fixed buckets make snapshots subtractable); None when the interval saw
    none."""
    counts = cur["counts"]
    if prev is not None:
        counts = [c - p for c, p in zip(counts, prev["counts"])]
    total = sum(counts)
    if total <= 0:
        return None
    buckets = cur["buckets"]
    rank = q * total
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            return buckets[min(i, len(buckets) - 1)]
    return buckets[-1]


class MetricsSampler:
    """Background thread sampling a Telemetry registry into a bounded ring.

    Thread-safe throughout: the sampling thread appends, any thread may read
    (``series``/``latest``/``tail``) or force an immediate sample
    (``sample_now`` - used by the flight recorder to flush the trailing
    partial interval up to the failure moment).  A sampler over a disabled
    (Null) recorder is inert: ``start()`` is a no-op and every read returns
    empty.
    """

    def __init__(self, telemetry, interval_s: float = DEFAULT_INTERVAL_S,
                 max_points: int = DEFAULT_MAX_POINTS):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s!r}")
        if max_points < 1:
            raise ValueError(f"max_points must be >= 1, got {max_points!r}")
        self.telemetry = telemetry
        self.interval_s = float(interval_s)
        self._points: "collections.deque" = collections.deque(
            maxlen=max_points)
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev: Optional[Dict] = None       # previous snapshot
        self._prev_wall = 0.0

    @property
    def enabled(self) -> bool:
        """False over a Null recorder (nothing to sample)."""
        return bool(getattr(self.telemetry, "enabled", False))

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        """Start the sampling thread (idempotent; no-op when disabled).  The
        baseline snapshot is taken here, so the first point covers the first
        full interval."""
        if not self.enabled or self._thread is not None:
            return
        self._prev = self.telemetry.snapshot()
        self._prev_wall = time.time()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="petastorm-tpu-metrics-sampler")
        self._thread.start()

    def stop(self) -> None:
        """Stop the sampling thread (idempotent; bounded join)."""
        self._stop_event.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(2.0, 2 * self.interval_s))
            self._thread = None

    def _run(self) -> None:
        while not self._stop_event.wait(self.interval_s):
            try:
                self.sample_now()
            except Exception:  # noqa: BLE001 - observability must not crash
                logger.warning("metrics sampler tick failed", exc_info=True)

    # -- sampling -------------------------------------------------------------

    def sample_now(self) -> Optional[Dict]:
        """Take one sample immediately and append it to the ring; returns the
        point (None when disabled, not yet started, or the elapsed interval
        is too small to yield meaningful rates)."""
        if not self.enabled:
            return None
        with self._lock:
            prev, prev_wall = self._prev, self._prev_wall
            if prev is None:    # start() not called: establish the baseline
                self._prev = self.telemetry.snapshot()
                self._prev_wall = time.time()
                return None
            cur = self.telemetry.snapshot()
            wall = time.time()
            dt = wall - prev_wall
            if dt < 1e-3:       # sample_now raced the timer tick: skip
                return None
            point = self._build_point(prev, cur, dt, wall)
            self._prev, self._prev_wall = cur, wall
            self._points.append(point)
        return point

    @staticmethod
    def _build_point(prev: Dict, cur: Dict, dt: float, wall: float) -> Dict:
        prev_counters = prev.get("counters", {})
        counters = cur.get("counters", {})
        rates = {n: max(v - prev_counters.get(n, 0.0), 0.0) / dt
                 for n, v in counters.items()}
        prev_hists = prev.get("histograms", {})
        stages: Dict[str, Dict] = {}
        hops: Dict[str, Dict] = {}
        for n, hist in cur.get("histograms", {}).items():
            if n.startswith("service.hop."):
                # per-hop latency decomposition of traced service items:
                # same per-interval quantile treatment as stages, its own
                # section (hops are legs of one item, not pipeline stages)
                hops[n[len("service.hop."):]] = {
                    "count": int(hist.get("count", 0)),
                    "p50_s": _delta_hist_quantile(prev_hists.get(n), hist,
                                                  0.5),
                    "p99_s": _delta_hist_quantile(prev_hists.get(n), hist,
                                                  0.99),
                }
                continue
            if not (n.startswith("stage.") and n.endswith(".latency_s")):
                continue
            stage = n.split(".", 2)[1]
            stages[stage] = {
                "count": int(counters.get(f"stage.{stage}.count", 0)),
                "rate_per_s": rates.get(f"stage.{stage}.count", 0.0),
                "busy_frac": rates.get(f"stage.{stage}.busy_s", 0.0),
                "p50_s": _delta_hist_quantile(prev_hists.get(n), hist, 0.5),
                "p99_s": _delta_hist_quantile(prev_hists.get(n), hist, 0.99),
            }
        # counters already registered as stages render via ``stages``; keep
        # the raw maps complete anyway (flight-record analysis wants totals)
        point = {"t": float(cur.get("uptime_s", 0.0)),
                 "wall_time": wall,
                 "dt_s": dt,
                 "counters": dict(counters),
                 "rates": rates,
                 "gauges": dict(cur.get("gauges", {})),
                 "stages": stages}
        if hops:
            point["hops"] = hops
        return point

    # -- reads ----------------------------------------------------------------

    def series(self) -> List[Dict]:
        """All buffered points, oldest first (a copy)."""
        with self._lock:
            return list(self._points)

    def latest(self) -> Optional[Dict]:
        """The most recent point, or None."""
        with self._lock:
            return self._points[-1] if self._points else None

    def tail(self, seconds: float) -> List[Dict]:
        """Points from the last ``seconds`` of the series (by sample time)."""
        with self._lock:
            points = list(self._points)
        if not points:
            return []
        cutoff = points[-1]["t"] - float(seconds)
        return [p for p in points if p["t"] >= cutoff]

    def __len__(self) -> int:
        with self._lock:
            return len(self._points)


# -- flight recorder ----------------------------------------------------------

def flight_record(sampler: MetricsSampler, reason: str = "",
                  window_s: float = DEFAULT_FLIGHT_WINDOW_S,
                  trace_tail: int = DEFAULT_TRACE_TAIL,
                  fleet_events: Optional[List[Dict]] = None) -> Dict:
    """Capture the last ``window_s`` of sampled series plus the trace tail.

    Called at the moment of a terminal pipeline failure (the reader wires
    this into its stall-abort / worker-error / budget-exhaustion paths); a
    final ``sample_now()`` flushes the partial interval so the series reaches
    the failure moment.  ``fleet_events`` (optional) carries the dispatcher's
    structured event tail fetched at failure time, so one artifact holds the
    local curves AND the fleet's last minute of promotions / requeues /
    autoscale decisions.  Returns a JSON-serializable record::

        {"reason", "wall_time", "window_s", "interval_s",
         "points": [<sample points>...],
         "final": <full Telemetry.snapshot()>,
         "trace_tail": [<last spans, TraceBuffer.tail schema>...],
         "fleet_events": [<dispatcher event dicts>...]}   # may be empty
    """
    sampler.sample_now()
    tele = sampler.telemetry
    trace = getattr(tele, "trace", None)
    return {
        "reason": reason,
        "wall_time": time.time(),
        "window_s": float(window_s),
        "interval_s": sampler.interval_s,
        "points": sampler.tail(window_s),
        "final": tele.snapshot(),
        "trace_tail": trace.tail(trace_tail) if trace is not None else [],
        "fleet_events": list(fleet_events or []),
    }


def dump_flight_record(record: Dict, path: str) -> str:
    """Append ``record`` to ``path`` as JSONL; returns the path.

    One header line (``kind='flight_recorder'``: reason, window, interval),
    one ``kind='point'`` line per sampled point, one ``kind='final_snapshot'``
    line, one ``kind='trace_event'`` line per trace span, then one
    ``kind='fleet_event'`` line per dispatcher event (when the record carries
    a fleet tail).  Append mode:
    a long-lived job that crashes repeatedly accumulates one record per
    incident in the same artifact (header ``wall_time`` separates them).
    """
    with open(path, "a") as f:
        header = {k: record[k] for k in ("reason", "wall_time", "window_s",
                                         "interval_s")}
        header["kind"] = "flight_recorder"
        header["points"] = len(record["points"])
        f.write(json.dumps(header) + "\n")
        for point in record["points"]:
            f.write(json.dumps({"kind": "point", **point}) + "\n")
        f.write(json.dumps({"kind": "final_snapshot",
                            "snapshot": record["final"]}) + "\n")
        for event in record.get("trace_tail", []):
            f.write(json.dumps({"kind": "trace_event", **event}) + "\n")
        for event in record.get("fleet_events", []):
            # nested: dispatcher events carry their OWN "kind" field (the
            # event type), which must not collide with the line discriminator
            f.write(json.dumps({"kind": "fleet_event", "event": event})
                    + "\n")
    return path


def load_flight_records(path: str) -> List[Dict]:
    """Parse a :func:`dump_flight_record` JSONL back into record dicts
    (``points``/``final``/``trace_tail`` re-nested), newest last - the
    post-mortem half of the flight-recorder round trip."""
    records: List[Dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            kind = obj.pop("kind", None)
            if kind == "flight_recorder":
                obj.pop("points", None)
                records.append({**obj, "points": [], "final": {},
                                "trace_tail": [], "fleet_events": []})
            elif not records:
                continue        # tolerate a truncated/foreign prefix
            elif kind == "point":
                records[-1]["points"].append(obj)
            elif kind == "final_snapshot":
                records[-1]["final"] = obj.get("snapshot", {})
            elif kind == "trace_event":
                records[-1]["trace_tail"].append(obj)
            elif kind == "fleet_event":
                records[-1]["fleet_events"].append(obj.get("event", obj))
    return records
