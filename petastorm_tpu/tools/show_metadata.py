"""Operator CLI: inspect a dataset's schema, rowgroups, indexes and KV keys.

Reference parity: the ``petastorm-generate-metadata``-adjacent inspection tool
``metadata_util`` (reference petastorm/etl/metadata_util.py:15-70: -\\-schema
prints unischema fields, -\\-index prints rowgroup indexes).  TPU-build
differences: one ``show`` surface prints everything an operator debugging a
dataset needs (schema incl. codecs/shapes, rowgroup count + row-count
distribution, hive partition keys, stored rowgroup indexes, raw KV keys), and
``--json`` emits the same as one machine-readable document.

Usage::

    petastorm-tpu-metadata show file:///path/to/dataset
    petastorm-tpu-metadata show --schema-only hdfs://ns/ds
    petastorm-tpu-metadata show --rowgroups --json gs://bucket/ds
"""

from __future__ import annotations

import argparse
import json
import posixpath
import sys
from typing import List, Optional

from petastorm_tpu.etl.indexing import get_row_group_indexes
from petastorm_tpu.etl.metadata import (DatasetInfo, infer_or_load_schema,
                                        open_dataset)


def _schema_rows(info: DatasetInfo) -> List[dict]:
    schema = infer_or_load_schema(info)
    rows = []
    for field in schema:
        rows.append({
            "name": field.name,
            "dtype": str(field.dtype),
            "shape": list(field.shape),
            "codec": type(field.codec).__name__,
            "nullable": field.nullable,
        })
    return rows


def _rowgroup_summary(info: DatasetInfo) -> dict:
    sizes = sorted(rg.num_rows for rg in info.row_groups)
    n = len(sizes)
    return {
        "num_files": len(info.files),
        "num_row_groups": n,
        "total_rows": sum(sizes),
        "rows_per_group_min": sizes[0] if n else 0,
        "rows_per_group_median": sizes[n // 2] if n else 0,
        "rows_per_group_max": sizes[-1] if n else 0,
    }


def _per_file_rowgroups(info: DatasetInfo) -> List[dict]:
    per_file: dict = {}
    for rg in info.row_groups:
        per_file.setdefault(rg.path, []).append(rg.num_rows)
    return [{"file": posixpath.relpath(path, info.root_path),
             "row_groups": counts, "rows": sum(counts)}
            for path, counts in sorted(per_file.items())]


def _indexes(info: DatasetInfo) -> List[dict]:
    try:
        stored = get_row_group_indexes(info)
    except Exception as exc:  # noqa: BLE001 - inspection must not die on one key
        return [{"error": f"could not load stored indexes: {exc}"}]
    out = []
    for name, indexer in stored.items():
        values = indexer.indexed_values()
        out.append({
            "name": name,
            "type": type(indexer).__name__,
            "fields": list(indexer.column_names),
            "num_indexed_values": len(values),
            "sample_values": [str(v) for v in values[:8]],
        })
    return out


_ALL_SECTIONS = ("rowgroups", "files", "indexes")


def describe(url: str, storage_options: Optional[dict] = None,
             sections=_ALL_SECTIONS) -> dict:
    """Everything ``show`` prints, as one JSON-ready document.

    ``sections`` limits the expensive parts: loading stored rowgroup indexes
    materializes every indexed value, which --schema-only must not pay for.
    """
    info = open_dataset(url, storage_options=storage_options)
    doc = {
        "url": url,
        "root": info.root_path,
        "schema_source": ("stored" if info.stored_schema is not None
                          else "inferred-from-arrow"),
        "schema": _schema_rows(info),
        "partition_keys": info.partition_keys,
        "kv_metadata_keys": sorted(k.decode("utf-8", "replace")
                                   for k in info.kv_metadata),
    }
    if "rowgroups" in sections:
        doc["rowgroups"] = _rowgroup_summary(info)
    if "files" in sections:
        doc["files"] = _per_file_rowgroups(info)
    if "indexes" in sections:
        doc["indexes"] = _indexes(info)
    return doc


def _print_human(doc: dict, show_rowgroups: bool, schema_only: bool) -> None:
    print(f"Dataset: {doc['url']}")
    print(f"  schema source: {doc['schema_source']}")
    print("\nSchema:")
    widths = (max((len(r["name"]) for r in doc["schema"]), default=4),
              max((len(r["dtype"]) for r in doc["schema"]), default=5))
    for r in doc["schema"]:
        shape = "x".join("?" if d is None else str(d) for d in r["shape"]) or "scalar"
        null = " nullable" if r["nullable"] else ""
        print(f"  {r['name']:<{widths[0]}}  {r['dtype']:<{widths[1]}}  "
              f"{shape:<12} {r['codec']}{null}")
    if schema_only:
        return
    if doc["partition_keys"]:
        print(f"\nPartition keys: {', '.join(doc['partition_keys'])}")
    rg = doc["rowgroups"]
    print(f"\nRowgroups: {rg['num_row_groups']} across {rg['num_files']} files,"
          f" {rg['total_rows']} rows total")
    print(f"  rows/group min={rg['rows_per_group_min']}"
          f" median={rg['rows_per_group_median']}"
          f" max={rg['rows_per_group_max']}")
    if show_rowgroups:
        print("\nPer-file rowgroups:")
        for f in doc["files"]:
            print(f"  {f['file']}: {len(f['row_groups'])} groups,"
                  f" {f['rows']} rows {f['row_groups']}")
    if doc["indexes"]:
        print("\nStored rowgroup indexes:")
        for ix in doc["indexes"]:
            if "error" in ix:
                print(f"  {ix['error']}")
                continue
            print(f"  {ix['name']} ({ix['type']} on"
                  f" {', '.join(ix['fields'])}):"
                  f" {ix['num_indexed_values']} indexed values"
                  f" (sample: {', '.join(ix['sample_values'][:4])})")
    print("\nKV metadata keys:")
    for k in doc["kv_metadata_keys"]:
        print(f"  {k}")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-metadata",
        description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="command", required=True)
    show = sub.add_parser("show", help="print dataset metadata")
    show.add_argument("url", help="dataset URL (file://, gs://, s3://, hdfs://)")
    show.add_argument("--schema-only", action="store_true",
                      help="print only the schema table")
    show.add_argument("--rowgroups", action="store_true",
                      help="also print per-file rowgroup row counts")
    show.add_argument("--json", action="store_true", dest="as_json",
                      help="emit one machine-readable JSON document")
    args = parser.parse_args(argv)

    if args.schema_only:
        sections = ()
    elif args.rowgroups:
        sections = _ALL_SECTIONS
    else:
        sections = ("rowgroups", "indexes")
    doc = describe(args.url, sections=sections)
    if args.as_json:
        if args.schema_only:
            doc = {"url": doc["url"], "schema_source": doc["schema_source"],
                   "schema": doc["schema"]}
        json.dump(doc, sys.stdout, indent=2)
        print()
    else:
        _print_human(doc, show_rowgroups=args.rowgroups,
                     schema_only=args.schema_only)
    return 0


if __name__ == "__main__":
    sys.exit(main())
