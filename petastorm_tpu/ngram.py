"""NGram: sliding-window temporal readout (multi-timestep sequences per sample).

Reference parity: petastorm/ngram.py (339 LoC) - ``NGram(fields={offset: [fields]},
delta_threshold, timestamp_field, timestamp_overlap)`` (ngram.py:102-125), windows
formed within one rowgroup only (doc ngram.py:85-91), consecutive-timestamp delta
threshold (ngram.py:179-193), optional non-overlap dedup (ngram.py:225-270),
per-timestep schema views with regex resolution (ngram.py:195-223,303-326).

Design differences (TPU-first):

* **Columnar window formation**: rows are sorted and window-start indices computed
  with vectorized numpy over the timestamp column; per-(offset, field) outputs are
  gathered with one fancy-index per column - no per-row python (the reference
  builds python dicts per timestep, ngram.py:225-270).
* **Sequence-axis output**: ``stack_timesteps=True`` (default off for reference
  parity) emits fields that appear at every offset as one ``(n_windows, k, ...)``
  array - the layout a sequence/context-parallel consumer shards over its 'seq'
  mesh axis via the jax loader's PartitionSpec (SURVEY.md section 5 long-context
  note).  Stacked readers are columnar-only: consume via ``iter_batches``/the
  jax loader, not the row-path iterator.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.schema import Schema

#: separator in flattened ngram column names: "<offset>/<field>"
NGRAM_KEY_SEP = "/"


class NGram:
    """Sliding-window spec: ``{offset: [fields]}`` read per window, windows
    anchored where consecutive ``timestamp_field`` values stay within
    ``delta_threshold``.  Pass to ``make_reader(ngram=...)``;
    ``stack_timesteps=True`` yields columnar (window, T, ...) arrays for the
    device-feed path instead of per-offset namedtuples."""

    def __init__(self,
                 fields: Dict[int, Sequence],
                 delta_threshold: Union[int, float],
                 timestamp_field: str,
                 timestamp_overlap: bool = True,
                 stack_timesteps: bool = False):
        if not fields:
            raise PetastormTpuError("NGram fields must be a non-empty {offset: [fields]}")
        offsets = sorted(fields)
        if offsets != list(range(offsets[0], offsets[0] + len(offsets))):
            raise PetastormTpuError(f"NGram offsets must be consecutive, got {offsets}")
        self._fields = {k: list(v) for k, v in fields.items()}
        self._offsets = offsets
        self.length = len(offsets)
        self.delta_threshold = delta_threshold
        if hasattr(timestamp_field, "name"):  # accept a Field (reference accepts both)
            timestamp_field = timestamp_field.name
        self.timestamp_field = timestamp_field
        self.timestamp_overlap = timestamp_overlap
        self.stack_timesteps = stack_timesteps

    @property
    def offsets(self) -> List[int]:
        """Sorted timestep offsets this window spec covers."""
        return list(self._offsets)

    def __eq__(self, other):
        if not isinstance(other, NGram):
            return NotImplemented
        return (self._fields == other._fields
                and self.delta_threshold == other.delta_threshold
                and self.timestamp_field == other.timestamp_field
                and self.timestamp_overlap == other.timestamp_overlap
                and self.stack_timesteps == other.stack_timesteps)

    def __hash__(self):
        return hash((tuple(sorted((k, tuple(v)) for k, v in self._fields.items())),
                     self.delta_threshold, self.timestamp_field,
                     self.timestamp_overlap, self.stack_timesteps))

    def resolve_schema(self, schema: Schema) -> Dict[int, Schema]:
        """Per-offset schema views with regex/Field resolution (ngram.py:303-326)."""
        out = {}
        for off in self._offsets:
            out[off] = schema.view(self._fields[off])
        return out

    def required_fields(self, schema: Schema) -> List[str]:
        """Union of all per-offset fields plus the timestamp field."""
        names: List[str] = []
        for off in self._offsets:
            for n in schema.resolve_fields(self._fields[off]):
                if n not in names:
                    names.append(n)
        if self.timestamp_field not in names:
            names.append(self.timestamp_field)
        return names

    @staticmethod
    def _stackable(field) -> bool:
        """Static test for whether a field's decoded columns can stack into one
        (n, k, ...) array.  Must be decidable from the schema alone so
        ``output_schema`` and ``form_windows`` always agree: fixed shape,
        non-object dtype, and non-nullable (a null cell turns the decoded
        column into an object array at runtime)."""
        return (field.is_fixed_shape and field.dtype != np.dtype(object)
                and not field.nullable)

    # -- window formation -----------------------------------------------------

    def window_starts(self, timestamps: np.ndarray,
                      anchor_range: Optional[tuple] = None) -> np.ndarray:
        """Valid window start indices over timestamp-sorted rows.

        A window of ``length`` rows starting at i is valid iff every consecutive
        timestamp delta within it is <= delta_threshold (ngram.py:179-193).
        ``anchor_range=(lo, hi)`` keeps only starts in [lo, hi) - used for
        row-drop partitions (reference lookahead borrowing,
        py_dict_reader_worker.py:254-274).  With ``timestamp_overlap=False``,
        selected windows share no rows (greedy left-to-right, ngram.py:225-270).
        """
        n = len(timestamps)
        k = self.length
        if n < k:
            return np.empty(0, dtype=np.int64)
        deltas = np.diff(np.asarray(timestamps))
        if np.any(deltas < 0):
            raise PetastormTpuError(
                f"NGram requires rows sorted by {self.timestamp_field!r}")
        ok = deltas <= self.delta_threshold
        if k == 1:
            starts = np.arange(n, dtype=np.int64)
        else:
            # all k-1 consecutive deltas inside the window must be ok
            win_ok = np.lib.stride_tricks.sliding_window_view(ok, k - 1).all(axis=1)
            starts = np.nonzero(win_ok)[0].astype(np.int64)
        if not self.timestamp_overlap and len(starts):
            # greedy dedup BEFORE anchor filtering, so the selected set is a
            # global property of the rows and row-drop partitions (which each
            # see a different anchor range) never pick overlapping windows
            keep = []
            next_free = -1
            for s in starts:
                if s >= next_free:
                    keep.append(s)
                    next_free = s + k
            starts = np.asarray(keep, dtype=np.int64)
        if anchor_range is not None:
            lo, hi = anchor_range
            starts = starts[(starts >= lo) & (starts < hi)]
        return starts

    def form_windows(self, schema: Schema, batch: ColumnBatch,
                     anchor_range: Optional[tuple] = None) -> ColumnBatch:
        """Sorted batch -> flattened ngram ColumnBatch ('<offset>/<field>' keys)."""
        ts = batch.columns[self.timestamp_field]
        order = np.argsort(np.asarray(ts), kind="stable")
        if not np.array_equal(order, np.arange(len(order))):
            batch = ColumnBatch({n: c[order] for n, c in batch.columns.items()},
                                batch.num_rows)
            ts = batch.columns[self.timestamp_field]
        starts = self.window_starts(ts, anchor_range)
        base = self._offsets[0]
        out: Dict[str, np.ndarray] = {}
        per_offset_fields = {off: schema.resolve_fields(self._fields[off])
                             for off in self._offsets}
        for off in self._offsets:
            idx = starts + (off - base)
            for name in per_offset_fields[off]:
                out[f"{off}{NGRAM_KEY_SEP}{name}"] = batch.columns[name][idx]
        if self.stack_timesteps:
            # fields present at EVERY offset collapse to one (n, k, ...) array -
            # the layout a context-parallel consumer shards on its 'seq' axis.
            # The stackability test is the schema-static one, so the emitted
            # columns always match ``output_schema``.
            common = [n for n in per_offset_fields[self._offsets[0]]
                      if all(n in per_offset_fields[o] for o in self._offsets)
                      and self._stackable(schema[n])]
            for name in common:
                parts = [out.pop(f"{o}{NGRAM_KEY_SEP}{name}") for o in self._offsets]
                out[name] = np.stack(parts, axis=1)
        return ColumnBatch(out, len(starts))

    def output_schema(self, schema: Schema) -> Schema:
        """Schema of the columnar batches ``form_windows`` emits.

        Non-stacked: one ``'<offset>/<field>'`` entry per (offset, field).
        Stacked: fields present at every offset become ``(length,) + shape``
        entries under their plain name (only when statically stackable: fixed
        shape, non-object dtype - mirroring the runtime check in
        ``form_windows``); the rest keep flat keys.
        """
        from petastorm_tpu.schema import Field

        per_offset = {off: schema.resolve_fields(self._fields[off])
                      for off in self._offsets}
        out = []
        stacked = set()
        if self.stack_timesteps:
            for name in per_offset[self._offsets[0]]:
                f = schema[name]
                if (all(name in per_offset[o] for o in self._offsets)
                        and self._stackable(f)):
                    out.append(Field(name, f.dtype, (self.length,) + f.shape,
                                     nullable=f.nullable))
                    stacked.add(name)
        for off in self._offsets:
            for name in per_offset[off]:
                if name in stacked:
                    continue
                f = schema[name]
                out.append(Field(f"{off}{NGRAM_KEY_SEP}{name}", f.dtype,
                                 f.shape, f.codec, f.nullable))
        return Schema(f"{schema.name}_ngram", out)

    def make_namedtuple_types(self, schema: Schema):
        """offset -> namedtuple type for window rows (what row-path iteration yields per timestep)."""
        views = self.resolve_schema(schema)
        return {off: view.make_namedtuple_type() for off, view in views.items()}

    def row(self, views, types, ngram_batch: ColumnBatch, i: int) -> Dict:
        """One window as {offset: namedtuple} (reference row-path shape)."""
        out = {}
        for off, view in views.items():
            vals = {f.name: ngram_batch.columns[f"{off}{NGRAM_KEY_SEP}{f.name}"][i]
                    for f in view}
            out[off] = types[off](**vals)
        return out
