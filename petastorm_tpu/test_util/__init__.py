"""Test utilities: synthetic datasets, mock readers, shuffle-quality analysis.

Reference parity: petastorm/test_util/ (reader_mock.py, shuffling_analysis.py) and
the synthetic TestSchema generator in petastorm/tests/test_common.py:40-102.
"""
