"""Spark DataFrame -> cached parquet -> TPU/torch feed via the converter.

Reference parity: examples/spark_dataset_converter/ (pytorch_converter_example
.py + tensorflow_converter_example.py): make a converter from a DataFrame,
feed one framework loop per output flavor, clean the cache up.

This environment has no JVM/pyspark, so by default the example runs against
the pinned mock (petastorm_tpu.test_util.mock_pyspark) - the SAME duck-typed
surface the test suite verifies the converter against.  With a real pyspark
installed it builds a local SparkSession instead; the converter code path is
identical either way (it only sees the pyspark module surface).
"""

import argparse
import contextlib
import tempfile
import warnings


def _pyspark_available() -> bool:
    try:
        import pyspark  # noqa: F401

        return True
    except ImportError:
        return False


def build_dataframe(n: int):
    """(dataframe, cleanup_fn) - a real local-SparkSession DataFrame (pyspark
    importable: either installed, or the mock entered by main())."""
    from pyspark.ml.linalg import Vectors
    from pyspark.sql import SparkSession

    spark = (SparkSession.builder.master("local[2]")
             .appName("petastorm-tpu-converter-example").getOrCreate())
    df = spark.createDataFrame(
        [(i, float(i) / n, Vectors.dense([i, i + 0.5, i + 0.25]))
         for i in range(n)],
        ["id", "x", "vec"])
    print(f"real SparkSession (local[2]), {n} rows")
    return df, spark.stop


def main(cache_dir: str = None, rows: int = 32) -> None:
    import jax
    import numpy as np

    from petastorm_tpu.converter import make_converter

    cache_dir = cache_dir or tempfile.mkdtemp(prefix="pst_converter_cache_")
    if _pyspark_available():
        mock_ctx = contextlib.nullcontext()
        df, cleanup = build_dataframe(rows)
    else:
        # the pinned mock installs into sys.modules only INSIDE this context
        # (and is removed after), so running the example cannot poison later
        # imports in the same process - e.g. guards that expect pyspark absent
        from petastorm_tpu.test_util.mock_pyspark import (
            installed_mock_pyspark, mock_spark_dataframe)

        print(f"pyspark not installed - using the pinned mock"
              f" (petastorm_tpu.test_util.mock_pyspark), {rows} rows")
        mock_ctx = installed_mock_pyspark()
        df, cleanup = mock_spark_dataframe(rows), (lambda: None)
    with mock_ctx:
        with warnings.catch_warnings():
            # VectorUDT columns convert to float32 arrays with a one-time warning
            warnings.simplefilter("ignore", UserWarning)
            conv = make_converter(df, cache_dir_url=cache_dir)
        try:
            print(f"converted: {len(conv)} rows in {len(conv.file_urls)}"
                  " parquet file(s) (executor-side materialization)")

            # jax feed: device batches through the TPU loader
            total = 0
            with conv.make_jax_loader(
                    batch_size=8,
                    # array<float> columns land as variable-shape fields; XLA
                    # needs static shapes, so declare the pad target (here the
                    # vectors are all length 3 already - no actual padding)
                    pad_shapes={"vec": (3,)},
                    reader_kwargs={"num_epochs": 1, "workers_count": 1,
                                   "shuffle_row_groups": False}) as loader:
                for batch in loader:
                    total += int(batch["id"].shape[0])
                    assert isinstance(batch["vec"], jax.Array)
                    assert batch["vec"].dtype == np.float32  # VectorUDT -> f32
            print(f"jax loader delivered {total} rows"
                  f" (vec is a float32 device array)")

            # torch feed: the reference example's shape
            import torch

            seen = 0
            with conv.make_torch_dataloader(
                    batch_size=8,
                    reader_kwargs={"num_epochs": 1, "workers_count": 1,
                                   "shuffle_row_groups": False}) as dl:
                for batch in dl:
                    seen += batch["id"].shape[0]
                    assert isinstance(batch["vec"], torch.Tensor)
            print(f"torch DataLoader delivered {seen} rows")

            # row-path readback: values survived the trip exactly
            with conv.make_reader(reader_pool_type="serial", num_epochs=1,
                                  shuffle_row_groups=False) as r:
                row5 = [row for row in r if row.id == 5][0]
            np.testing.assert_allclose(np.asarray(row5.vec), [5.0, 5.5, 5.25])
            print("row 5 vec == [5.0, 5.5, 5.25] - roundtrip exact")

            # converting the SAME dataframe again reuses the cache (fingerprint)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", UserWarning)
                again = make_converter(df, cache_dir_url=cache_dir)
            assert again.cache_url == conv.cache_url
            print("second make_converter() hit the fingerprint cache"
                  " (no re-materialization)")
        finally:
            conv.delete()
            cleanup()
    print("done (cache deleted)")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--cache-dir", default=None)
    parser.add_argument("--rows", type=int, default=32)
    args = parser.parse_args()
    main(args.cache_dir, args.rows)
