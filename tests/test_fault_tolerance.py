"""Fault-tolerant ingest: ``on_error`` policies, rowgroup quarantine,
work-item requeue, and the chaos-injection harness (ISSUE 2 tentpole).

The production contract under test: a multi-hour pod epoch with one poisoned
rowgroup, a hard-killed worker and transient IO weather must complete under
``on_error='skip'`` yielding exactly the rows of the healthy rowgroups - no
duplicates, no hang - with the damage accounted (quarantine ledger, requeue
and retry counters), while the default ``on_error='raise'`` keeps today's
fail-fast behavior bit-for-bit.

Reference gap: petastorm's pools forward any worker failure as a fatal error
(workers_pool/thread_pool.py:169-172) and its zmq process pool would wait
forever on a crashed worker; tf.data service (PAPERS.md) treats
skip-and-account fault tolerance as a prerequisite for production serving.
"""

import os
import queue
import time

import numpy as np
import pytest

from petastorm_tpu.errors import (CodecError, ErrorBudgetExceededError,
                                  ErrorPolicy, PetastormTpuError,
                                  classify_error, resolve_error_policy)
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.pool import (ThreadedExecutor, VentilatedItem, Ventilator,
                                WorkerError)
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.telemetry import Telemetry
from petastorm_tpu.test_util.chaos import (ChaosSpec, ChaosWorker,
                                           SimulatedWorkerCrash)
from petastorm_tpu.test_util.stub_workers import SleepyWorker

SCHEMA = Schema("Faulty", [Field("x", np.int64)])
N_ROWS = 40
RG_ROWS = 4  # 10 rowgroups of 4 rows


def _write(tmp_path, one_rowgroup_per_file=False):
    url = str(tmp_path / "ds")
    write_dataset(url, SCHEMA, [{"x": i} for i in range(N_ROWS)],
                  row_group_size_rows=RG_ROWS,
                  rows_per_file=RG_ROWS if one_rowgroup_per_file else None)
    return url


def _rows_of_rowgroups(ordinals):
    out = set()
    for o in ordinals:
        out |= set(range(o * RG_ROWS, (o + 1) * RG_ROWS))
    return out


# -- policy / classification units --------------------------------------------

def test_resolve_error_policy():
    assert resolve_error_policy("raise") is None
    assert resolve_error_policy(None) is None
    assert resolve_error_policy("skip") == ErrorPolicy()
    custom = ErrorPolicy(max_skipped_rowgroups=3)
    assert resolve_error_policy(custom) is custom
    with pytest.raises(PetastormTpuError):
        resolve_error_policy("ignore")
    with pytest.raises(PetastormTpuError):
        ErrorPolicy(max_skipped_rowgroups=-1)
    with pytest.raises(PetastormTpuError):
        ErrorPolicy(max_skipped_fraction=1.5)
    with pytest.raises(PetastormTpuError):
        ErrorPolicy(max_requeue_attempts=-1)


def test_classify_error():
    assert classify_error(CodecError("bad pixels")) == "data"
    assert classify_error(ValueError("transform blew up")) == "data"
    assert classify_error(OSError("exhausted retries")) == "data"
    assert classify_error(MemoryError()) == "infra"


def test_chaos_spec_parse_and_determinism():
    spec = ChaosSpec.parse(
        "decode_fail_rate=0.5,kill_ordinals=3;7,seed=2,fail_first_reads=4,"
        "slow_s=0.01,kill_on_retry=true")
    assert spec.decode_fail_rate == 0.5
    assert spec.kill_ordinals == (3, 7)
    assert spec.seed == 2 and spec.fail_first_reads == 4
    assert spec.kill_on_retry
    # decisions are pure functions of (seed, kind, ordinal)
    picks = [spec.should_fail_decode(i) for i in range(100)]
    assert picks == [spec.should_fail_decode(i) for i in range(100)]
    assert 20 < sum(picks) < 80  # the rate is honored, roughly
    # a different seed picks a different set
    other = ChaosSpec(seed=3, decode_fail_rate=0.5)
    assert picks != [other.should_fail_decode(i) for i in range(100)]
    # kill gate: requeued attempts do not re-trigger by default
    assert spec.should_kill(3, attempt=0)
    assert spec.should_kill(3, attempt=1)  # kill_on_retry=true in the spec
    assert not ChaosSpec(kill_ordinals=(3,)).should_kill(3, attempt=1)
    with pytest.raises(PetastormTpuError):
        ChaosSpec.parse("unknown_key=1")
    with pytest.raises(PetastormTpuError):
        ChaosSpec(decode_fail_rate=2.0)


# -- pool-level requeue semantics ---------------------------------------------

def _collect(executor, n, timeout=30):
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n:
        remaining = deadline - time.monotonic()
        assert remaining > 0, f"timed out with {len(out)}/{n} results"
        try:
            out.append(executor.get(timeout=min(remaining, 0.5)))
        except queue.Empty:
            continue
    return out


def test_thread_pool_requeues_item_of_crashed_worker():
    """A worker thread that hard-dies mid-item loses nothing: the in-flight
    ledger + heartbeat name the lost item and a surviving worker redoes it."""
    chaos = ChaosSpec(kill_ordinals=(2,))
    with ThreadedExecutor(workers_count=2) as ex:
        ex.start(ChaosWorker(SleepyWorker(0), chaos))
        for i in range(6):
            ex.put(VentilatedItem(i, i))
        results = _collect(ex, 6)
        diag = ex.diagnostics
    got = sorted(v.item for v in results)
    assert got == list(range(6))  # ordinal 2 delivered exactly once
    assert diag["requeued_items"] == 1


def test_thread_pool_requeue_budget_exhausts_to_worker_error():
    """kill_on_retry chaos re-kills every attempt: once the budget is spent
    the consumer gets a classified infra WorkerError, not a hang."""
    chaos = ChaosSpec(kill_ordinals=(0,), kill_on_retry=True)
    ex = ThreadedExecutor(workers_count=4, max_requeue_attempts=2)
    try:
        ex.start(ChaosWorker(SleepyWorker(0), chaos))
        ex.put(VentilatedItem(0, 0))
        with pytest.raises(WorkerError) as ei:
            _collect(ex, 1, timeout=30)
        err = ei.value
        assert err.kind == "infra"
        assert "requeue budget exhausted" in str(err) or "died" in str(err)
    finally:
        ex.stop()
        ex.join(timeout=5)


def test_serial_pool_inline_infra_retry():
    """The serial flavor's degenerate requeue: an infra-classified failure
    retries inline with the attempt count bumped (chaos keys on it)."""
    chaos = ChaosSpec(kill_ordinals=(1,))
    from petastorm_tpu.pool import SerialExecutor

    with SerialExecutor() as ex:
        ex.start(ChaosWorker(SleepyWorker(0), chaos))
        ex.put(VentilatedItem(0, 0))
        ex.put(VentilatedItem(1, 1))
        a = ex.get(timeout=5)
        b = ex.get(timeout=5)
        assert ex.diagnostics["requeued_items"] == 1
    assert sorted(v.ordinal for v in (a, b)) == [0, 1]


class _OomOnFirstAttempt:
    """Raises MemoryError on the trigger ordinal's first attempt only."""

    def __init__(self, trigger):
        self.trigger = trigger

    def __call__(self):
        def fn(item):
            if (getattr(item, "ordinal", None) == self.trigger
                    and getattr(item, "attempt", 0) == 0):
                raise MemoryError("simulated in-worker OOM")
            return item
        return fn


def test_thread_pool_requeues_in_worker_memory_error():
    """A delivered infra-kind failure (in-worker MemoryError) is requeued
    like a worker death, not surfaced - the item is healthy."""
    with ThreadedExecutor(workers_count=2) as ex:
        ex.start(_OomOnFirstAttempt(trigger=3))
        for i in range(6):
            ex.put(VentilatedItem(i, i))
        results = _collect(ex, 6)
        diag = ex.diagnostics
    assert sorted(v.ordinal for v in results) == list(range(6))
    assert diag["requeued_items"] == 1


class _AlwaysOom:
    def __call__(self):
        def fn(_item):
            raise MemoryError("persistent OOM")
        return fn


def test_serial_pool_ordinal_less_infra_retry_is_bounded():
    """Inline infra retries are bounded by a local attempt counter even for
    items without an ordinal (no unbounded spin on a persistent failure)."""
    from petastorm_tpu.pool import SerialExecutor

    with SerialExecutor(max_requeue_attempts=2) as ex:
        ex.start(_AlwaysOom())
        ex.put("no-ordinal-item")
        # budget spent -> a classified infra WorkerError (matching the
        # thread/process pools), not an unbounded retry spin
        with pytest.raises(WorkerError, match="MemoryError") as ei:
            ex.get(timeout=5)
        assert ei.value.kind == "infra"
        assert ex.diagnostics["requeued_items"] == 2


def test_serial_skip_mode_never_swallows_keyboard_interrupt():
    """Serial work runs inline on the consumer thread: Ctrl-C during decode
    is the CONSUMER's control flow and must propagate untouched even under
    a skip policy, never be quarantined as a 'data' error."""
    from petastorm_tpu.pool import SerialExecutor

    class _Interrupts:
        def __call__(self):
            def fn(_item):
                raise KeyboardInterrupt()
            return fn

    with SerialExecutor(stop_on_failure=False) as ex:
        ex.start(_Interrupts())
        ex.put(VentilatedItem(0, 0))
        with pytest.raises(KeyboardInterrupt):
            ex.get(timeout=5)


def test_infinite_reader_fraction_budget_uses_running_denominator(tmp_path):
    """num_epochs=None has no expected total: the fraction budget evaluates
    against items consumed so far (floored at one epoch), so a steady
    per-epoch corruption rate must NOT trip the budget cumulatively."""
    url = _write(tmp_path)
    # rowgroup 1 is poisoned every epoch: ordinals 1, 11, 21, ...
    chaos = ChaosSpec(decode_fail_ordinals=tuple(range(1, 100, 10)))
    policy = ErrorPolicy(max_skipped_fraction=0.2)  # actual rate is 0.1
    batches = 0
    with make_batch_reader(url, reader_pool_type="serial",
                           shuffle_row_groups=False, num_epochs=None,
                           chaos=chaos, on_error=policy) as r:
        for _ in r.iter_batches():
            batches += 1
            if batches >= 27:  # three epochs' worth of healthy batches
                break
        assert r.diagnostics["skipped_rowgroups"] == 3


def test_simulated_crash_is_baseexception():
    # ordinary `except Exception` user code must not swallow a chaos kill
    assert not issubclass(SimulatedWorkerCrash, Exception)
    assert issubclass(SimulatedWorkerCrash, BaseException)


# -- reader-level skip / quarantine -------------------------------------------

def test_default_raise_mode_unchanged(tmp_path):
    """on_error='raise' (default): first data error kills the read, as today."""
    url = _write(tmp_path)
    chaos = ChaosSpec(decode_fail_ordinals=(3,))
    with pytest.raises(WorkerError, match="chaos: injected decode failure"):
        with make_batch_reader(url, reader_pool_type="thread",
                               workers_count=2, shuffle_row_groups=False,
                               chaos=chaos) as r:
            list(r.iter_batches())


def test_on_error_rejects_unknown_value(tmp_path):
    url = _write(tmp_path)
    with pytest.raises(PetastormTpuError, match="on_error"):
        make_batch_reader(url, on_error="ignore")


@pytest.mark.parametrize("pool", ["serial", "thread"])
def test_skip_quarantines_and_completes(tmp_path, pool):
    url = _write(tmp_path)
    chaos = ChaosSpec(decode_fail_ordinals=(3, 7))
    tele = Telemetry()
    with make_batch_reader(url, reader_pool_type=pool, workers_count=2,
                           shuffle_row_groups=False, chaos=chaos,
                           on_error="skip", telemetry=tele) as r:
        rows = [x for b in r.iter_batches() for x in b.columns["x"]]
        diag = r.diagnostics
        state = r.state_dict()
    assert sorted(rows) == sorted(set(range(N_ROWS))
                                  - _rows_of_rowgroups([3, 7]))
    assert diag["skipped_rowgroups"] == 2
    quarantined = {(e["ordinal"], e["kind"]) for e
                   in diag["quarantined_rowgroups"]}
    assert quarantined == {(3, "data"), (7, "data")}
    for e in diag["quarantined_rowgroups"]:
        assert e["path"] and e["row_group"] is not None
        assert e["exc_type"] == "CodecError"
    assert tele.snapshot()["counters"]["errors.skipped_rowgroups"] == 2
    # skipped items count toward the cursor: the epoch ended exactly
    assert state["position"] == 10 and state["ordinal_exact"]


def test_corrupted_rowgroup_file_skipped(tmp_path):
    """REAL on-disk corruption (not injected exceptions): garbage bytes in
    one parquet file surface as a data error and quarantine that rowgroup.

    Serial pool: decode runs inside get(), so corrupting after construction
    cannot race a worker thread reading the file early."""
    url = _write(tmp_path, one_rowgroup_per_file=True)
    files = sorted(f for f in os.listdir(url) if f.endswith(".parquet"))
    assert len(files) == N_ROWS // RG_ROWS
    victim = os.path.join(url, files[2])
    size = os.path.getsize(victim)
    with make_batch_reader(url, reader_pool_type="serial",
                           shuffle_row_groups=False, on_error="skip") as r:
        with open(victim, "wb") as f:  # after construction: workers open lazily
            f.write(b"\x13" * size)
        rows = [x for b in r.iter_batches() for x in b.columns["x"]]
        diag = r.diagnostics
    assert sorted(rows) == sorted(set(range(N_ROWS)) - _rows_of_rowgroups([2]))
    assert diag["skipped_rowgroups"] == 1
    assert diag["quarantined_rowgroups"][0]["path"].endswith(files[2])
    assert diag["quarantined_rowgroups"][0]["kind"] == "data"


def test_skip_row_reader_multi_epoch(tmp_path):
    """Row-path reader, two epochs: the poisoned rowgroup is skipped in each
    epoch independently and the row multiset is exact both times."""
    url = _write(tmp_path)
    chaos = ChaosSpec(decode_fail_ordinals=(1, 11))  # same rowgroup, per epoch
    with make_reader(url, reader_pool_type="thread", workers_count=2,
                     shuffle_row_groups=False, num_epochs=2, chaos=chaos,
                     on_error="skip") as r:
        rows = [row.x for row in r]
        diag = r.diagnostics
    expect = sorted(set(range(N_ROWS)) - _rows_of_rowgroups([1])) * 2
    assert sorted(rows) == sorted(expect)
    assert diag["skipped_rowgroups"] == 2


def test_error_budget_count_exceeded(tmp_path):
    url = _write(tmp_path)
    chaos = ChaosSpec(decode_fail_ordinals=(1, 4, 6))
    policy = ErrorPolicy(max_skipped_rowgroups=2)
    with pytest.raises(ErrorBudgetExceededError, match="max_skipped_rowgroups"):
        with make_batch_reader(url, reader_pool_type="serial",
                               shuffle_row_groups=False, chaos=chaos,
                               on_error=policy) as r:
            list(r.iter_batches())


def test_error_budget_fraction_exceeded(tmp_path):
    url = _write(tmp_path)
    chaos = ChaosSpec(decode_fail_ordinals=(1, 4, 6))
    policy = ErrorPolicy(max_skipped_fraction=0.25)  # 3/10 > 0.25
    with pytest.raises(ErrorBudgetExceededError, match="max_skipped_fraction"):
        with make_batch_reader(url, reader_pool_type="serial",
                               shuffle_row_groups=False, chaos=chaos,
                               on_error=policy) as r:
            list(r.iter_batches())


def test_error_budget_within_limits_completes(tmp_path):
    url = _write(tmp_path)
    chaos = ChaosSpec(decode_fail_ordinals=(1,))
    policy = ErrorPolicy(max_skipped_rowgroups=2, max_skipped_fraction=0.25)
    with make_batch_reader(url, reader_pool_type="serial",
                           shuffle_row_groups=False, chaos=chaos,
                           on_error=policy) as r:
        rows = [x for b in r.iter_batches() for x in b.columns["x"]]
    assert len(rows) == N_ROWS - RG_ROWS


# -- the headline chaos e2e ---------------------------------------------------

@pytest.mark.parametrize("pool", ["thread", "process"])
def test_chaos_e2e_poison_kill_and_weather(tmp_path, pool):
    """Acceptance scenario: one poisoned rowgroup + one hard-killed worker
    + transient IO failures; ``on_error='skip'`` completes the epoch with
    exactly the healthy rowgroups' rows (no duplicates, no hang) and the
    damage visible in diagnostics and telemetry.

    The kill is real on the process pool (os._exit inside the spawned
    worker, like an OOM kill) and simulated-but-equivalent on the thread
    pool; it fires only on the first attempt, so the requeued item lands on
    a surviving worker and is delivered exactly once.
    """
    url = _write(tmp_path)
    chaos = ChaosSpec(decode_fail_ordinals=(4,),   # the poisoned rowgroup
                      kill_ordinals=(6,),          # one hard worker kill
                      fail_first_reads=2)          # transient IO weather
    tele = Telemetry()
    t0 = time.monotonic()
    with make_batch_reader(url, reader_pool_type=pool, workers_count=2,
                           shuffle_row_groups=False, chaos=chaos,
                           on_error="skip", telemetry=tele) as r:
        rows = [x for b in r.iter_batches() for x in b.columns["x"]]
        diag = r.diagnostics
        state = r.state_dict()
    assert time.monotonic() - t0 < 120, "chaos epoch took implausibly long"
    # exactly the healthy rowgroups' rows: no loss beyond the quarantined
    # rowgroup, no duplicates from the requeue
    assert sorted(rows) == sorted(set(range(N_ROWS)) - _rows_of_rowgroups([4]))
    assert diag["skipped_rowgroups"] == 1
    assert diag["quarantined_rowgroups"][0]["ordinal"] == 4
    assert diag["requeued_items"] == 1
    counters = tele.snapshot()["counters"]
    assert counters["errors.skipped_rowgroups"] == 1
    assert counters["errors.requeued_items"] == 1
    if pool == "thread":
        # parent-side recorder sees the worker-plane retries in-process;
        # spawned workers record into their own (documented) recorders
        assert counters.get("io.retries", 0) >= 1
    assert state["position"] == 10 and state["ordinal_exact"]


def test_crash_safe_results_channel_semantics():
    """The process pool's results transport: bounded put/get with the
    ``queue.Empty`` timeout contract, slot accounting across get, and a
    closed channel turning sends into clean drops instead of hangs."""
    import multiprocessing as mp
    import queue as stdlib_queue

    from petastorm_tpu.pool import _CrashSafeResultsChannel

    ctx = mp.get_context("spawn")
    stop = ctx.Event()
    ch = _CrashSafeResultsChannel(ctx, bound=2)
    assert ch.put("a", stop) and ch.put("b", stop)
    assert ch.qsize() == 2
    # full at bound: the writer parks on the slot semaphore until the
    # consumer drains or stop is raised - here stop turns it into a drop
    stop.set()
    assert not ch.put("c", stop)
    stop.clear()
    assert ch.get(timeout=1) == "a"
    assert ch.qsize() == 1
    assert ch.get(timeout=1) == "b"
    with pytest.raises(stdlib_queue.Empty):
        ch.get(timeout=0.05)
    ch.close()
    assert not ch.put("d", stop)  # closed channel: dropped, not wedged


def test_chaos_kill_storm_never_wedges_results_plane(tmp_path):
    """Regression (pre-existing flaky hang, fixed by
    ``_CrashSafeResultsChannel``): with mp.Queue results, a worker dying by
    ``os._exit`` moments after buffering a result could be killed while its
    queue FEEDER thread held the shared pipe write lock - the abandoned
    lock then wedged every surviving worker's put and the epoch hung with
    an idle, live worker plane (observed ~1-in-4 sessions under load).
    Worker puts are now synchronous in the worker's only thread, so every
    kill in this storm lands outside the write lock by construction; the
    epoch must complete with exact accounting every time."""
    url = _write(tmp_path)
    chaos = ChaosSpec(kill_ordinals=(2, 5, 8))  # one dice roll per kill
    t0 = time.monotonic()
    # 4 workers: each kill permanently retires one (a never-resized pool
    # keeps the degrade-then-raise contract), so one survivor remains to
    # drain the requeues
    with make_batch_reader(url, reader_pool_type="process", workers_count=4,
                           shuffle_row_groups=False, chaos=chaos,
                           on_error="skip") as r:
        rows = [x for b in r.iter_batches() for x in b.columns["x"]]
        diag = r.diagnostics
    assert time.monotonic() - t0 < 90, "kill storm wedged the results plane"
    assert sorted(rows) == list(range(N_ROWS))  # all requeues delivered
    assert diag["requeued_items"] == 3


def test_all_process_workers_die_surfaces_not_hangs(tmp_path):
    """Satellite: every process worker killed mid-read -> the consumer gets
    the WorkerError with the crash/OOM hint (pool "all died" path), never a
    silent hang until stall-abort."""
    url = _write(tmp_path)
    # every ordinal kills, on every attempt: the pool must cascade to death
    chaos = ChaosSpec(kill_rate=1.0, kill_on_retry=True)
    t0 = time.monotonic()
    with pytest.raises(WorkerError, match="crash/OOM"):
        with make_batch_reader(url, reader_pool_type="process",
                               workers_count=2, shuffle_row_groups=False,
                               chaos=chaos) as r:
            list(r.iter_batches())
    assert time.monotonic() - t0 < 120


def test_ventilator_backpressure_with_requeue():
    """Requeue re-injection must respect the bounded input queue (parked
    and flushed, never deadlocked) even while the ventilator is pushing."""
    from petastorm_tpu.etl.metadata import RowGroupRef
    from petastorm_tpu.plan import ReadPlan

    chaos = ChaosSpec(kill_ordinals=(5,))
    rgs = [RowGroupRef(f"/f{i}", 0, 5, i) for i in range(30)]
    plan = ReadPlan(rgs, shuffle_row_groups=False)
    ex = ThreadedExecutor(workers_count=2, in_queue_size=2,
                          results_queue_size=2)
    with ex:
        ex.start(ChaosWorker(SleepyWorker(0), chaos))
        vent = Ventilator(ex, plan, num_epochs=1)
        vent.start()
        results = _collect(ex, 30, timeout=60)
        vent.join()
    assert sorted(v.ordinal for v in results) == list(range(30))
    assert ex.diagnostics["requeued_items"] == 1
