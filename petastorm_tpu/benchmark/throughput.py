"""Reader/loader throughput measurement.

Reference parity: petastorm/benchmark/throughput.py - warmup then measured
cycles (throughput.py:113-174), samples/sec + RSS + CPU% metrics
(throughput.py:39,84-88), and an isolated fresh-process mode for clean RSS
numbers (throughput.py:69-91, which re-execs itself).

TPU-first additions: ``jax_loader_throughput`` measures the actual device feed
path (host parquet -> ColumnBatch -> sharded ``jax.Array``), which is the
number that matters for keeping a TPU busy; samples/sec alone (the reference's
only metric) ignores transfer overlap.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import List, Optional, Sequence

WorkerPoolType = ("thread", "process", "serial")


@dataclasses.dataclass
class BenchmarkResult:
    """What one measurement run produced.

    Reference: the three reported metrics at benchmark/throughput.py:84-88.
    """
    samples_per_sec: float
    wall_s: float
    samples: int
    rss_mb: float
    cpu_percent: float
    #: percent of wall time the consumer was blocked waiting for the next
    #: batch - the device-idle metric for the feed (SURVEY.md section 7 step
    #: 10): ~0 means the host pipeline keeps the chip busy
    input_stall_percent: "float | None" = None
    #: mean prefetch-queue depth sampled at each batch (capacity = healthy)
    prefetch_depth_avg: "float | None" = None
    #: telemetry snapshot (petastorm_tpu.telemetry.Telemetry.snapshot()) when
    #: the run was telemetered - stage busy seconds, queue waits, counters;
    #: feed it to telemetry.render_pipeline_report() for the bottleneck view
    metrics: "dict | None" = None
    #: static planner verdict (petastorm_tpu.planner.PlanVerdict.to_dict())
    #: when the run was autotuned - planned knobs with per-knob provenance
    planner: "dict | None" = None

    def to_json(self) -> str:
        d = {k: v for k, v in dataclasses.asdict(self).items() if v is not None}
        return json.dumps(d)


def _rss_mb() -> float:
    """Resident set size of this process, in MB (linux /proc)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    import resource
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


class _CpuClock:
    """CPU%% over a wall interval = (user+sys delta) / wall delta * 100."""

    def start(self) -> None:
        t = os.times()
        self._cpu0, self._wall0 = t.user + t.system, time.perf_counter()

    def stop(self) -> float:
        t = os.times()
        wall = time.perf_counter() - self._wall0
        return 100.0 * (t.user + t.system - self._cpu0) / max(wall, 1e-9)


def reader_throughput(dataset_url: str,
                      field_regex: Optional[Sequence[str]] = None,
                      warmup_cycles: int = 200,
                      measure_cycles: int = 1000,
                      pool_type: str = "thread",
                      workers_count: int = 3,
                      read_method: str = "row",
                      shuffle_row_groups: bool = True,
                      transform_spec=None,
                      storage_options: Optional[dict] = None,
                      telemetry=None, chaos=None,
                      on_error="raise",
                      item_deadline_s: Optional[float] = None,
                      hedge_after_s=None,
                      metrics_port: Optional[int] = None,
                      flight_record_path: Optional[str] = None,
                      autotune=False,
                      cache_type: str = "null",
                      cache_location: Optional[str] = None,
                      cache_size_limit: Optional[int] = None) -> BenchmarkResult:
    """Measure raw reader throughput in samples/sec.

    ``read_method='row'`` counts one sample per ``next()`` (make_reader);
    ``'batch'`` iterates make_batch_reader and counts rows per columnar batch.
    ``telemetry``: optional petastorm_tpu.telemetry recorder; when enabled its
    snapshot rides back on ``BenchmarkResult.metrics``.
    ``chaos``/``on_error``: measure throughput under injected faults
    (test_util.chaos) - degradation becomes a number, not an anecdote.
    ``autotune``: run the closed-loop knob tuner during the measurement
    (petastorm_tpu.autotune; True or an AutotunePolicy).
    Reference: ``reader_throughput`` (benchmark/throughput.py:113-174).
    """
    from petastorm_tpu.reader import make_batch_reader, make_reader
    from petastorm_tpu.telemetry import resolve as _resolve_telemetry

    if read_method not in ("row", "batch"):
        raise ValueError(f"read_method must be 'row' or 'batch', got {read_method!r}")
    factory = make_reader if read_method == "row" else make_batch_reader
    tele = _resolve_telemetry(telemetry)
    clock = _CpuClock()
    with factory(dataset_url, schema_fields=list(field_regex) if field_regex else None,
                 reader_pool_type=pool_type, workers_count=workers_count,
                 shuffle_row_groups=shuffle_row_groups, num_epochs=None,
                 transform_spec=transform_spec,
                 storage_options=storage_options, telemetry=tele,
                 chaos=chaos, on_error=on_error,
                 item_deadline_s=item_deadline_s,
                 hedge_after_s=hedge_after_s,
                 metrics_port=metrics_port,
                 flight_record_path=flight_record_path,
                 cache_type=cache_type, cache_location=cache_location,
                 cache_size_limit=cache_size_limit,
                 autotune=autotune or None) as reader:
        if reader.metrics_server is not None:
            # stderr so --json stdout stays one parseable line; without this
            # an ephemeral --metrics-port 0 endpoint would be unreachable
            # (the bound port lives only on the reader)
            print("metrics endpoint: http://127.0.0.1:"
                  f"{reader.metrics_server.port}/metrics", file=sys.stderr)
        it = iter(reader)

        def consume(cycles: int) -> int:
            n = 0
            for _ in range(cycles):
                item = next(it)
                n += len(item[0]) if read_method == "batch" else 1
            return n

        consume(warmup_cycles)
        clock.start()
        t0 = time.perf_counter()
        samples = consume(measure_cycles)
        wall = time.perf_counter() - t0
        cpu = clock.stop()
        planner = (reader.planner.to_dict()
                   if reader.planner is not None else None)
    return BenchmarkResult(samples_per_sec=samples / wall, wall_s=wall,
                           samples=samples, rss_mb=_rss_mb(), cpu_percent=cpu,
                           metrics=tele.snapshot() if tele.enabled else None,
                           planner=planner)


def jax_loader_throughput(dataset_url: str,
                          batch_size: int = 32,
                          warmup_batches: int = 8,
                          measure_batches: int = 64,
                          pool_type: str = "thread",
                          workers_count: int = 3,
                          field_regex: Optional[Sequence[str]] = None,
                          shuffle_row_groups: bool = True,
                          storage_options: Optional[dict] = None,
                          simulated_step_s: float = 0.0,
                          device_decode_fields: Sequence[str] = (),
                          prefetch: Optional[int] = None,
                          telemetry=None, chaos=None,
                          on_error="raise",
                          item_deadline_s: Optional[float] = None,
                          hedge_after_s=None,
                          metrics_port: Optional[int] = None,
                          flight_record_path: Optional[str] = None,
                          autotune=False,
                          cache_type: str = "null",
                          cache_location: Optional[str] = None,
                          cache_size_limit: Optional[int] = None) -> BenchmarkResult:
    """Measure the device feed path: batches landing as committed ``jax.Array``.

    Blocks on every batch (``block_until_ready``) so the number reflects
    host decode + transfer, i.e. the ceiling on how fast this loader can feed
    a training step.

    ``simulated_step_s`` emulates a training step between batches; with it,
    ``input_stall_percent`` answers the operational question "would this feed
    keep a chip with an N-ms step busy?" - the device-idle% north-star metric
    (BASELINE.md).  At 0 the feed runs flat out and the stall percent is by
    construction ~100 (every moment is waiting).
    """
    import jax

    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.telemetry import resolve as _resolve_telemetry

    tele = _resolve_telemetry(telemetry)
    clock = _CpuClock()
    reader = make_batch_reader(
        dataset_url, schema_fields=list(field_regex) if field_regex else None,
        reader_pool_type=pool_type, workers_count=workers_count,
        shuffle_row_groups=shuffle_row_groups,
        num_epochs=None, storage_options=storage_options,
        decode_placement=({f: "device" for f in device_decode_fields}
                          if device_decode_fields else None),
        telemetry=tele, chaos=chaos, on_error=on_error,
        item_deadline_s=item_deadline_s, hedge_after_s=hedge_after_s,
        metrics_port=metrics_port, flight_record_path=flight_record_path,
        cache_type=cache_type, cache_location=cache_location,
        cache_size_limit=cache_size_limit,
        autotune=autotune or None)
    if reader.metrics_server is not None:
        # same stderr contract as reader_throughput: the ephemeral bound
        # port must be reachable by the user
        print("metrics endpoint: http://127.0.0.1:"
              f"{reader.metrics_server.port}/metrics", file=sys.stderr)
    try:
        loader = JaxDataLoader(reader, batch_size=batch_size, prefetch=prefetch)
    except Exception:
        # the reader's executor threads would poll forever otherwise
        reader.stop()
        reader.join()
        raise
    wait_s = 0.0
    depth_sum = 0
    depth_n = 0
    with loader:
        it = iter(loader)

        def consume(n_batches: int) -> int:
            nonlocal wait_s, depth_sum, depth_n
            n = 0
            for _ in range(n_batches):
                t1 = time.perf_counter()
                batch = next(it)
                jax.block_until_ready(batch)
                wait_s += time.perf_counter() - t1
                depth_sum += loader.diagnostics["prefetch_depth"]
                depth_n += 1
                first = next(iter(batch.values()))
                n += int(first.shape[0])
                if simulated_step_s:
                    time.sleep(simulated_step_s)
            return n

        consume(warmup_batches)
        wait_s, depth_sum, depth_n = 0.0, 0, 0
        clock.start()
        t0 = time.perf_counter()
        samples = consume(measure_batches)
        wall = time.perf_counter() - t0
        cpu = clock.stop()
        planner = (reader.planner.to_dict()
                   if reader.planner is not None else None)
    return BenchmarkResult(samples_per_sec=samples / wall, wall_s=wall,
                           samples=samples, rss_mb=_rss_mb(), cpu_percent=cpu,
                           input_stall_percent=100.0 * wait_s / wall,
                           prefetch_depth_avg=depth_sum / max(depth_n, 1),
                           metrics=tele.snapshot() if tele.enabled else None,
                           planner=planner)


def run_isolated(cli_args: List[str]) -> BenchmarkResult:
    """Run the benchmark CLI in a fresh interpreter and parse its JSON line.

    Reference: throughput.py:69-91 re-execs for an RSS untainted by the parent
    (dataset-generation, test fixtures, jax runtime...).
    """
    out = subprocess.run(
        [sys.executable, "-m", "petastorm_tpu.benchmark.cli", "--json", *cli_args],
        capture_output=True, text=True, check=True)
    line = out.stdout.strip().splitlines()[-1]
    return BenchmarkResult(**json.loads(line))
