"""Converter tests (reference: tests/test_spark_dataset_converter.py, JVM-free)."""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest
import torch

from petastorm_tpu.converter import (CACHE_DIR_ENV_VAR, _registered_converters,
                                     make_converter)
from petastorm_tpu.errors import PetastormTpuError


def _df(n=64):
    return pd.DataFrame({
        "id": np.arange(n, dtype=np.int64),
        "x": np.linspace(0, 1, n).astype(np.float64),
        "label": (np.arange(n) % 3).astype(np.int32),
    })


def test_requires_cache_dir(monkeypatch):
    monkeypatch.delenv(CACHE_DIR_ENV_VAR, raising=False)
    with pytest.raises(PetastormTpuError, match="cache"):
        make_converter(_df())


def test_materialize_and_read_back(tmp_path):
    conv = make_converter(_df(), cache_dir_url=str(tmp_path / "cache"))
    try:
        assert len(conv) == 64
        with conv.make_reader(reader_pool_type="serial",
                              shuffle_row_groups=False, num_epochs=1) as r:
            rows = list(r)
        assert len(rows) == 64
        assert [row.id for row in rows] == list(range(64))
    finally:
        conv.delete()
    assert not os.path.exists(conv.cache_url)


def test_float64_downcast_default_and_opt_out(tmp_path):
    conv32 = make_converter(_df(), cache_dir_url=str(tmp_path / "c32"))
    conv64 = make_converter(_df(), cache_dir_url=str(tmp_path / "c64"),
                            dtype=None)
    try:
        assert conv32.schema["x"].dtype == np.float32
        assert conv64.schema["x"].dtype == np.float64
    finally:
        conv32.delete(), conv64.delete()


def test_dedup_by_content(tmp_path):
    cache = str(tmp_path / "cache")
    a = make_converter(_df(), cache_dir_url=cache)
    b = make_converter(_df(), cache_dir_url=cache)        # same content
    c = make_converter(_df(32), cache_dir_url=cache)      # different content
    d = make_converter(_df(), cache_dir_url=cache, row_group_size_mb=1)
    try:
        assert a is b  # shared handle: delete() on one cannot orphan the other
        assert a.cache_url != c.cache_url
        assert a.cache_url != d.cache_url  # params are part of the fingerprint
    finally:
        for conv in (a, b, c, d):
            conv.delete()
    # a fresh conversion after delete() re-materializes rather than reusing a
    # dead handle
    e = make_converter(_df(), cache_dir_url=cache)
    try:
        assert e is not a
        with e.make_reader(num_epochs=1) as r:
            assert len(list(r)) == 64
    finally:
        e.delete()


def test_env_var_cache_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(CACHE_DIR_ENV_VAR, str(tmp_path / "envcache"))
    conv = make_converter(_df())
    try:
        assert str(tmp_path / "envcache") in conv.cache_url
    finally:
        conv.delete()


def test_arrow_table_input(tmp_path):
    table = pa.table({"id": np.arange(10, dtype=np.int64),
                      "y": np.ones(10, np.float32)})
    conv = make_converter(table, cache_dir_url=str(tmp_path / "cache"))
    try:
        with conv.make_reader(num_epochs=1) as r:
            assert len(list(r)) == 10
    finally:
        conv.delete()


def test_unsupported_input_rejected(tmp_path):
    with pytest.raises(PetastormTpuError, match="Unsupported input"):
        make_converter([1, 2, 3], cache_dir_url=str(tmp_path / "cache"))


def test_make_torch_dataloader(tmp_path):
    conv = make_converter(_df(), cache_dir_url=str(tmp_path / "cache"))
    try:
        with conv.make_torch_dataloader(
                batch_size=16,
                reader_kwargs={"num_epochs": 1}) as loader:
            batches = list(loader)
        assert sum(len(b["id"]) for b in batches) == 64
        assert isinstance(batches[0]["x"], torch.Tensor)
    finally:
        conv.delete()


def test_make_jax_loader(tmp_path):
    import jax

    conv = make_converter(_df(), cache_dir_url=str(tmp_path / "cache"))
    try:
        with conv.make_jax_loader(
                batch_size=16,
                reader_kwargs={"num_epochs": 1}) as loader:
            batch = next(iter(loader))
        assert isinstance(batch["x"], jax.Array)
        assert batch["x"].shape == (16,)
    finally:
        conv.delete()


def test_rank_mismatch_warns(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_RANK", "1")
    monkeypatch.setenv("HOROVOD_SIZE", "4")
    conv = make_converter(_df(2000), cache_dir_url=str(tmp_path / "cache"),
                          row_group_size_mb=0.001)
    try:
        with pytest.warns(UserWarning, match="disagrees"):
            with conv.make_reader(cur_shard=0, shard_count=4,
                                  num_epochs=1) as r:
                next(iter(r))
        with pytest.warns(UserWarning, match="ALL the data"):
            with conv.make_reader(num_epochs=1) as r:
                next(iter(r))
    finally:
        conv.delete()


def test_atexit_registration(tmp_path):
    conv = make_converter(_df(), cache_dir_url=str(tmp_path / "cache"))
    assert conv in _registered_converters
    conv.delete()
    assert conv not in _registered_converters
    keep = make_converter(_df(), cache_dir_url=str(tmp_path / "cache2"),
                          delete_at_exit=False)
    assert keep not in _registered_converters
    # delete() on a non-owning converter must not remove the files
    keep.delete()
    assert os.path.exists(keep.cache_url)


def test_make_tf_dataset(tmp_path):
    conv = make_converter(_df(), cache_dir_url=str(tmp_path / "cache"))
    try:
        cm = conv.make_tf_dataset(
            reader_kwargs={"num_epochs": 1, "reader_pool_type": "serial",
                           "shuffle_row_groups": False})
        with cm as dataset:
            ids = [int(item.id) for item in dataset.as_numpy_iterator()]
        assert ids == list(range(64))
        assert cm._reader._stopped  # reader released on exit
    finally:
        conv.delete()


def test_slices_get_distinct_fingerprints(tmp_path):
    """Zero-copy slices share buffers; the fingerprint must still distinguish
    them (regression: slice(0,50) and slice(50,50) collided, returning the
    wrong cached dataset)."""
    t = pa.table({"x": np.arange(100, dtype=np.int64)})
    c1 = make_converter(t.slice(0, 50), str(tmp_path), dtype=None)
    c2 = make_converter(t.slice(50, 50), str(tmp_path), dtype=None)
    c3 = make_converter(t, str(tmp_path), dtype=None)
    assert len({c1.cache_url, c2.cache_url, c3.cache_url}) == 3
    with c2.make_reader(shuffle_row_groups=False) as r:
        assert sorted(row.x for row in r) == list(range(50, 100))


def test_arrow_path_clears_debris_dir(tmp_path):
    """A pre-existing directory with NO parquet at the cache target (crashed
    writer, foreign files) must be moved aside and re-materialized - neither
    adopted as data nor allowed to fail the publish rename."""
    from petastorm_tpu.converter import _fingerprint

    t = pa.table({"x": np.arange(40, dtype=np.int64)})
    tag = _fingerprint(t, {"codec": "snappy", "rg_mb": 128.0, "v": 2})
    debris = tmp_path / f"converted-{tag}"
    debris.mkdir()
    (debris / "stray.txt").write_text("junk")

    conv = make_converter(t, str(tmp_path), row_group_size_mb=128.0)
    try:
        assert conv.file_urls and all(
            u.endswith(".parquet") for u in conv.file_urls)
        with conv.make_reader(reader_pool_type="serial",
                              shuffle_row_groups=False) as r:
            assert sorted(row.x for row in r) == list(range(40))
        assert not (debris / "stray.txt").exists()
    finally:
        conv.delete()


def test_dedup_persistence_wins(tmp_path):
    """A later delete_at_exit=False on the same content un-registers cleanup."""
    conv1 = make_converter(_df(), str(tmp_path))
    assert conv1 in _registered_converters
    conv2 = make_converter(_df(), str(tmp_path), delete_at_exit=False)
    assert conv2 is conv1
    assert conv1 not in _registered_converters
    assert not conv1._owns_cache
    # asking to delete again warns but keeps the persistent choice
    with pytest.warns(UserWarning, match="delete_at_exit=False"):
        make_converter(_df(), str(tmp_path), delete_at_exit=True)
    assert conv1 not in _registered_converters


def test_explicit_snappy_reuses_default_cache(tmp_path):
    c1 = make_converter(_df(), str(tmp_path))
    c2 = make_converter(_df(), str(tmp_path), compression_codec="snappy")
    assert c2 is c1


def test_loader_factory_failure_does_not_leak_reader(tmp_path):
    import threading

    conv = make_converter(_df(), str(tmp_path))
    before = threading.active_count()
    with pytest.raises(Exception):
        conv.make_jax_loader(batch_size=0)
    deadline = 50
    while threading.active_count() > before and deadline:
        import time
        time.sleep(0.1)
        deadline -= 1
    assert threading.active_count() <= before


# -- Spark DataFrame input (mocked pyspark, shared with the example) ----------
#
# The pinned mock lives in petastorm_tpu.test_util.mock_pyspark (also used by
# examples/spark_converter/); toPandas() raises so the converter's Spark path
# must materialize on the "executors" (df.write.parquet), never collect to
# the driver (reference spark_dataset_converter.py:546-562).

from petastorm_tpu.test_util.mock_pyspark import (  # noqa: E402
    MockSparkDataFrame as _FakeSparkDataFrame,
    build_mock_pyspark_modules,
    mock_spark_dataframe as _spark_frame,
)


def _install_fake_pyspark(monkeypatch):
    import sys

    for name, mod in build_mock_pyspark_modules().items():
        monkeypatch.setitem(sys.modules, name, mod)


def test_spark_df_materializes_on_executors(tmp_path, monkeypatch):
    _install_fake_pyspark(monkeypatch)
    with pytest.warns(UserWarning, match="MLlib vector"):
        conv = make_converter(_spark_frame(), cache_dir_url=str(tmp_path))
    try:
        assert len(conv) == 32
        assert len(conv.file_urls) == 2  # one per "executor" part file
        with conv.make_reader(reader_pool_type="serial", num_epochs=1,
                              shuffle_row_groups=False) as r:
            rows = list(r)
        assert [row.id for row in rows] == list(range(32))
        # VectorUDT -> float32 array (default dtype='float32'), values intact
        v5 = np.asarray(rows[5].vec, dtype=np.float32)
        np.testing.assert_allclose(v5, [5.0, 5.5, 5.25])
        # DoubleType scalar downcast to float32 by dtype='float32'
        assert conv.schema["x"].dtype == np.float32
    finally:
        conv.delete()


def test_spark_write_call_sequence_pinned(tmp_path, monkeypatch):
    """The executor-side materialization must issue EXACTLY the pinned
    DataFrameWriter chain (mode -> compression option -> block-size option ->
    parquet into a .tmp dir) - the strongest drift tripwire available without
    a real pyspark in this environment (docs/operations.md)."""
    _install_fake_pyspark(monkeypatch)
    import warnings as _w

    _FakeSparkDataFrame.write_calls.clear()
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        conv = make_converter(_spark_frame(), cache_dir_url=str(tmp_path),
                              row_group_size_mb=32.0)
    try:
        # attribute probes (hasattr duck-typing) touch .write without calling
        # it; exactly ONE chain may actually write
        chains = [c for c in _FakeSparkDataFrame.write_calls if c]
        assert len(chains) == 1
        (calls,) = chains
        assert calls[0] == ("mode", "overwrite")
        assert calls[1] == ("option", "compression", "snappy")
        assert calls[2] == ("option", "parquet.block.size", int(32.0 * 2**20))
        kind, url = calls[3]
        assert kind == "parquet" and "/.tmp-" in url  # tmp dir, atomic publish
        assert len(calls) == 4
    finally:
        _FakeSparkDataFrame.write_calls.clear()
        conv.delete()


def test_spark_df_crashed_write_not_adopted(tmp_path, monkeypatch):
    """A cache dir with part files but no completeness marker (_SUCCESS /
    _common_metadata) is a crashed write: it must be re-materialized, never
    silently reused as a complete dataset."""
    import warnings as _w

    import pyarrow.parquet as pq

    _install_fake_pyspark(monkeypatch)
    df = _spark_frame()
    # predict the cache dir, then plant a partial (marker-less) write there
    from petastorm_tpu.converter import _spark_fingerprint, _spark_prepare_df

    with _w.catch_warnings():
        _w.simplefilter("ignore")
        prepared = _spark_prepare_df(df, "float32")
    tag = _spark_fingerprint(prepared, {"codec": "snappy", "rg_mb": 32.0,
                                        "v": 2, "engine": "spark"})
    stale = tmp_path / f"converted-{tag}"
    stale.mkdir()
    pq.write_table(pa.table({"id": [999]}), str(stale / "part-00000.parquet"))

    with _w.catch_warnings():
        _w.simplefilter("ignore")
        conv = make_converter(_spark_frame(), cache_dir_url=str(tmp_path),
                              row_group_size_mb=32.0)
    try:
        assert len(conv) == 32  # fresh materialization, not the stale row
        with conv.make_reader(reader_pool_type="serial", num_epochs=1) as r:
            ids = sorted(row.id for row in r)
        assert ids == list(range(32))
    finally:
        conv.delete()


def test_spark_df_plan_dedup_and_no_collection(tmp_path, monkeypatch):
    _install_fake_pyspark(monkeypatch)
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("ignore")
        a = make_converter(_spark_frame(), cache_dir_url=str(tmp_path))
        b = make_converter(_spark_frame(), cache_dir_url=str(tmp_path))
        c = make_converter(_spark_frame(16), cache_dir_url=str(tmp_path))
    try:
        assert b is a          # same analyzed plan -> same cache entry
        assert c is not a      # different plan -> different entry
    finally:
        a.delete(), c.delete()
