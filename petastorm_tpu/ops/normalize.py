"""Fused on-chip uint8 -> float image normalization.

The op computes ``(u8/255 - mean[c]) / std[c]`` per channel, emitting bfloat16 by
default (MXU-native).  Shipping uint8 to the device and normalizing there cuts
host->device bytes 4x vs normalizing on host in float32 - on TPU the transfer is
usually the ingest bottleneck (HBM/PCIe bound), so this is the single highest-value
"decode on device" op (BASELINE.json north star).

Two implementations:

* ``_normalize_pallas``: a Pallas TPU kernel over (8, lane)-tiled blocks of the
  flattened (N, H*W*C) image, with per-position scale/bias vectors materialized
  once (channel pattern tiled across the row).  VPU-bound elementwise work with
  explicit VMEM blocking (see /opt/skills/guides/pallas_guide.md tiling table).
* ``_normalize_xla``: plain jnp fallback (XLA fuses this into one kernel too) -
  used on non-TPU backends and for shapes that violate the tiling constraints.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_LANE = 128
_SUBLANE = 8


def _choose_block(n: int, length: int) -> Optional[Tuple[int, int]]:
    """(rows, cols) VMEM block for an (n, length) array, or None if untileable."""
    if length % _LANE != 0 or n % _SUBLANE != 0:
        return None
    bl = next((c for c in (8 * _LANE, 4 * _LANE, 2 * _LANE, _LANE)
               if length % c == 0), None)
    if bl is None:
        return None
    bn = next((r for r in (64, 32, 16, _SUBLANE) if n % r == 0), None)
    return (bn, bl) if bn else None


def _normalize_kernel(x_ref, scale_ref, bias_ref, o_ref):
    # Mosaic has no direct u8->f32 cast; widen through int32 first
    x = x_ref[:].astype(jnp.int32).astype(jnp.float32)
    o_ref[:] = (x * scale_ref[:] + bias_ref[:]).astype(o_ref.dtype)


def _normalize_pallas(flat_u8: jax.Array, scale_vec: jax.Array, bias_vec: jax.Array,
                      block: Tuple[int, int], out_dtype) -> jax.Array:
    from jax.experimental import pallas as pl

    n, length = flat_u8.shape
    bn, bl = block
    grid = (n // bn, length // bl)
    return pl.pallas_call(
        _normalize_kernel,
        out_shape=jax.ShapeDtypeStruct((n, length), out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, bl), lambda i, j: (i, j)),
            pl.BlockSpec((1, bl), lambda i, j: (0, j)),
            pl.BlockSpec((1, bl), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bn, bl), lambda i, j: (i, j)),
    )(flat_u8, scale_vec, bias_vec)


@functools.partial(jax.jit, static_argnames=("out_dtype", "use_pallas"))
def _normalize_impl(images: jax.Array, scale_vec: jax.Array, bias_vec: jax.Array,
                    out_dtype: jnp.dtype, use_pallas: bool) -> jax.Array:
    shape = images.shape
    flat = images.reshape(shape[0], -1)
    if use_pallas:
        block = _choose_block(*flat.shape)
        out = _normalize_pallas(flat, scale_vec, bias_vec, block, out_dtype)
    else:
        out = (flat.astype(jnp.float32) * scale_vec + bias_vec).astype(out_dtype)
    return out.reshape(shape)


def normalize_images(images: jax.Array,
                     mean: Sequence[float] = (0.485, 0.456, 0.406),
                     std: Sequence[float] = (0.229, 0.224, 0.225),
                     out_dtype=jnp.bfloat16) -> jax.Array:
    """``(images/255 - mean) / std`` fused on device; images are NHWC uint8.

    mean/std are per-channel in [0,1] units (torchvision convention).  Uses the
    Pallas kernel when the flattened shape satisfies TPU tiling; XLA elementwise
    otherwise (identical math).
    """
    if images.dtype != jnp.uint8:
        raise TypeError(f"normalize_images expects uint8, got {images.dtype}")
    if images.ndim < 2:
        raise TypeError("normalize_images expects at least (N, ...) images")
    c = images.shape[-1]
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if mean.size == 1:
        mean = np.full(c, float(mean), np.float32)
    if std.size == 1:
        std = np.full(c, float(std), np.float32)
    if mean.size != c or std.size != c:
        raise ValueError(f"mean/std size {mean.size}/{std.size} != channels {c}")

    length = int(np.prod(images.shape[1:]))
    # per-position scale/bias row: channel pattern tiled across H*W
    scale_np = np.tile(1.0 / (255.0 * std), length // c).astype(np.float32)[None, :]
    bias_np = np.tile(-mean / std, length // c).astype(np.float32)[None, :]

    # trace-safe platform check: inside jit the array is abstract, so key off
    # the backend jit compiles for ('axon' is the tunneled TPU PJRT plugin)
    on_tpu = jax.default_backend() in ("tpu", "axon")
    use_pallas = on_tpu and _choose_block(images.shape[0], length) is not None
    return _normalize_impl(images, jnp.asarray(scale_np), jnp.asarray(bias_np),
                           out_dtype, use_pallas)
