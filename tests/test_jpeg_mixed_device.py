"""decode_placement='device-mixed': on-chip decode of MIXED jpeg geometries.

Lifts the uniform-geometry restriction of the 'device' fast path (VERDICT
round-2 item 3): rows are grouped by (H, W, subsampling), each geometry
bucket decodes on-chip with its planes padded to the full batch size - so
XLA compiles the decode exactly once per geometry, never per data-dependent
group size - then every image is padded/cropped to one static target.

Reference analog: the host decode handles any geometry per cell
(petastorm/codecs.py:92-118); this gets the same generality on the device
path.
"""

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from petastorm_tpu.errors import PetastormTpuError  # noqa: E402
from petastorm_tpu.native import image as native_image  # noqa: E402

if not native_image.available():
    pytest.skip("native image library unavailable", allow_module_level=True)

from petastorm_tpu.codecs import CompressedImageCodec  # noqa: E402
from petastorm_tpu.etl.writer import write_dataset  # noqa: E402
from petastorm_tpu.jax import JaxDataLoader  # noqa: E402
from petastorm_tpu.reader import make_batch_reader  # noqa: E402
from petastorm_tpu.schema import Field, Schema  # noqa: E402

from tests.test_jpeg_hybrid import _cv2_decode, _encode, _smooth_rgb  # noqa: E402

#: three geometries, interleaved so single rowgroups mix them
GEOMETRIES = [(64, 96), (48, 64), (32, 32)]
TARGET = (64, 96, 3)
N_ROWS = 24


@pytest.fixture(scope="module")
def mixed_ds(tmp_path_factory):
    schema = Schema("MixedGeo", [
        Field("idx", np.int64),
        Field("image", np.uint8, (None, None, 3),
              CompressedImageCodec("jpeg", quality=92)),
    ])
    rows = []
    for i in range(N_ROWS):
        h, w = GEOMETRIES[i % len(GEOMETRIES)]
        rows.append({"idx": i, "image": _smooth_rgb(h, w, seed=i)})
    url = str(tmp_path_factory.mktemp("mixed_geo") / "ds")
    write_dataset(url, schema, rows, row_group_size_rows=6)
    return url


def test_mixed_decode_matches_host_decode(mixed_ds, monkeypatch):
    """Every geometry decodes on-device to within the hybrid-decode pixel
    contract of its host decode, padded to the static target - and the
    on-chip decode sees a BOUNDED set of shapes (one per geometry)."""
    import petastorm_tpu.ops.jpeg as ops_jpeg

    signatures = set()
    real = ops_jpeg.decode_coefficients

    def recording(planes, qtabs, image_size, sampling, **kw):
        signatures.add((tuple(p.shape for p in planes), image_size, sampling))
        return real(planes, qtabs, image_size=image_size, sampling=sampling, **kw)

    monkeypatch.setattr(ops_jpeg, "decode_coefficients", recording)

    with make_batch_reader(mixed_ds, shuffle_row_groups=False, num_epochs=2,
                           decode_placement={"image": "device-mixed"}) as r:
        assert r.device_decode_mixed == frozenset({"image"})
        with JaxDataLoader(r, batch_size=8, fields=["idx", "image"],
                           pad_shapes={"image": TARGET}) as loader:
            got = {}
            for b in loader:
                imgs = np.asarray(b["image"])
                assert imgs.shape == (8,) + TARGET and imgs.dtype == np.uint8
                for k, i in enumerate(np.asarray(b["idx"])):
                    got.setdefault(int(i), []).append(imgs[k])
            diag = loader.diagnostics
    assert sorted(got) == list(range(N_ROWS))
    assert all(len(v) == 2 for v in got.values())  # both epochs delivered

    # bounded compiles: one decode signature per geometry, across 2 epochs
    # and 6 batches (data-dependent group sizes are padded away)
    assert len(signatures) == len(GEOMETRIES)
    assert diag["mixed_decode_geometries"] == {"image": len(GEOMETRIES)}

    for i in range(N_ROWS):
        h, w = GEOMETRIES[i % len(GEOMETRIES)]
        ref = _cv2_decode(_encode(_smooth_rgb(h, w, seed=i), quality=92))
        dev = got[i][0]
        diff = np.abs(ref.astype(int) - dev[:h, :w].astype(int))
        assert diff.max() <= 6 and diff.mean() < 1.0, f"idx {i} ({h}x{w})"
        # the pad region is exactly zero
        assert dev[h:].sum() == 0 and dev[:, w:].sum() == 0


def test_mixed_subsampling_within_one_size(tmp_path):
    """Same pixel size but different chroma subsampling = different
    coefficient geometry; both must decode in one dataset."""
    s444 = getattr(cv2, "IMWRITE_JPEG_SAMPLING_FACTOR_444", None)
    if s444 is None:
        pytest.skip("cv2 build lacks sampling-factor control")
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    from petastorm_tpu.etl.writer import stamp_dataset_metadata

    schema = Schema("MixedSamp", [
        Field("idx", np.int64),
        Field("image", np.uint8, (32, 32, 3), CompressedImageCodec("jpeg"))])
    bufs = [_encode(_smooth_rgb(32, 32, seed=i),
                    sampling=(s444 if i % 2 else None)) for i in range(8)]
    url = str(tmp_path / "ds")
    os.makedirs(url)
    table = pa.Table.from_pylist(
        [{"idx": i, "image": b} for i, b in enumerate(bufs)],
        schema=schema.as_arrow_schema())
    pq.write_table(table, os.path.join(url, "part-00000.parquet"),
                   row_group_size=4)
    stamp_dataset_metadata(url, schema)

    with make_batch_reader(url, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device-mixed"}) as r:
        # fixed schema shape: the target comes from the schema, no pad_shapes
        with JaxDataLoader(r, batch_size=4, fields=["idx", "image"]) as loader:
            batches = list(loader)
            diag = loader.diagnostics
    assert diag["mixed_decode_geometries"] == {"image": 2}
    by_idx = {int(i): np.asarray(b["image"])[k]
              for b in batches for k, i in enumerate(np.asarray(b["idx"]))}
    for i in range(8):
        ref = _cv2_decode(bufs[i])
        diff = np.abs(ref.astype(int) - by_idx[i].astype(int))
        assert diff.max() <= 6 and diff.mean() < 1.0


def test_mixed_requires_static_target(mixed_ds):
    with make_batch_reader(mixed_ds, num_epochs=1,
                           decode_placement={"image": "device-mixed"}) as r:
        with pytest.raises(PetastormTpuError, match="ONE pad_shapes target"):
            JaxDataLoader(r, batch_size=8, fields=["idx", "image"])
        with pytest.raises(PetastormTpuError, match="ONE pad_shapes target"):
            JaxDataLoader(r, batch_size=8, fields=["idx", "image"],
                          pad_shapes={"image": [(32, 32, 3), (64, 96, 3)]})


def test_declared_geometries_stamped_at_write(mixed_ds):
    """write_dataset stamps the dataset-level geometry contract for
    variable-shape image fields; the reader exposes it."""
    with make_batch_reader(mixed_ds, num_epochs=1) as r:
        declared = r.declared_geometries
    assert set(declared) == {"image"}
    assert sorted(declared["image"]) == sorted(
        (h, w, 3) for h, w in GEOMETRIES)


def test_mixed_on_mesh_decodes_and_bounds_compiles(mixed_ds, monkeypatch):
    """VERDICT r3 item 2: 'device-mixed' works across a mesh.  The decode is
    host-local (geometry buckets may differ per host), delivery scatters the
    decoded rows over the mesh as a global array, and the compile count stays
    bounded by the stamped dataset-level geometry contract."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    import petastorm_tpu.ops.jpeg as ops_jpeg

    signatures = set()
    real = ops_jpeg.decode_coefficients

    def recording(planes, qtabs, image_size, sampling, **kw):
        signatures.add((tuple(p.shape for p in planes), image_size, sampling))
        return real(planes, qtabs, image_size=image_size, sampling=sampling, **kw)

    monkeypatch.setattr(ops_jpeg, "decode_coefficients", recording)

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    with make_batch_reader(mixed_ds, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device-mixed"}) as r:
        with JaxDataLoader(r, batch_size=8, mesh=mesh,
                           shardings={"idx": P("data"), "image": P("data")},
                           fields=["idx", "image"],
                           pad_shapes={"image": TARGET}) as loader:
            got = {}
            for b in loader:
                assert b["image"].shape == (8,) + TARGET
                assert b["image"].sharding.spec == P("data")
                assert len(b["image"].sharding.device_set) == 8
                imgs = np.asarray(b["image"])
                for k, i in enumerate(np.asarray(b["idx"])):
                    got[int(i)] = imgs[k]
            diag = loader.diagnostics
    assert sorted(got) == list(range(N_ROWS))
    assert len(signatures) == len(GEOMETRIES)  # bounded compiles on the mesh
    assert diag["mixed_decode_geometries"] == {"image": len(GEOMETRIES)}
    assert diag["declared_geometries"] == {"image": len(GEOMETRIES)}
    for i in range(N_ROWS):
        h, w = GEOMETRIES[i % len(GEOMETRIES)]
        ref = _cv2_decode(_encode(_smooth_rgb(h, w, seed=i), quality=92))
        diff = np.abs(ref.astype(int) - got[i][:h, :w].astype(int))
        assert diff.max() <= 6 and diff.mean() < 1.0, f"idx {i} ({h}x{w})"


def test_mixed_on_mesh_partial_tail_padded(mixed_ds):
    """drop_last=False on a mesh: the partial final mixed batch zero-pads to
    the static shape and carries '_valid_rows' + a zero valid mask tail."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    with make_batch_reader(mixed_ds, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device-mixed"}) as r:
        with JaxDataLoader(r, batch_size=16, mesh=mesh, drop_last=False,
                           shardings={"idx": P("data"), "image": P("data")},
                           fields=["idx", "image"],
                           pad_shapes={"image": TARGET},
                           valid_mask_field="mask") as loader:
            batches = list(loader)
    assert len(batches) == 2  # 24 rows = 16 + 8(+8 pad)
    tail = batches[-1]
    assert tail["_valid_rows"] == 8
    assert np.asarray(tail["mask"]).tolist() == [1.0] * 8 + [0.0] * 8
    assert np.asarray(tail["image"])[8:].sum() == 0  # pad rows all zero


def test_mixed_on_mesh_trailing_axes_rejected(mixed_ds):
    """Only the batch axis may shard a mixed field (the decode is host-local;
    image axes cannot span hosts)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    with make_batch_reader(mixed_ds, num_epochs=1,
                           decode_placement={"image": "device-mixed"}) as r:
        with pytest.raises(PetastormTpuError, match="only the batch axis"):
            JaxDataLoader(r, batch_size=8, mesh=mesh,
                          shardings={"idx": P("data"),
                                     "image": P("data", "model")},
                          fields=["idx", "image"],
                          pad_shapes={"image": TARGET})


def test_mixed_on_mesh_replicated_single_host_works(mixed_ds):
    """A batch-replicated spec is feasible on a single host (the host holds
    the whole batch); delivery replicates the decoded rows to every device."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    with make_batch_reader(mixed_ds, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device-mixed"}) as r:
        with JaxDataLoader(r, batch_size=8, mesh=mesh,
                           shardings={"idx": P("data"), "image": P()},
                           fields=["idx", "image"],
                           pad_shapes={"image": TARGET}) as loader:
            b = next(iter(loader))
    assert b["image"].shape == (8,) + TARGET
    assert b["image"].sharding.is_fully_replicated
    # replicated delivery carries the same pixels as the sharded path
    i0 = int(np.asarray(b["idx"])[0])
    h, w = GEOMETRIES[i0 % len(GEOMETRIES)]
    ref = _cv2_decode(_encode(_smooth_rgb(h, w, seed=i0), quality=92))
    assert np.abs(ref.astype(int)
                  - np.asarray(b["image"])[0, :h, :w].astype(int)).max() <= 6


def test_mixed_scatter_layout_rejected_across_processes(mixed_ds):
    """When the batch spans processes (local rows < global batch), a
    batch-replicated spec must fail AT CONSTRUCTION with the contract error,
    not an opaque shape error from make_array_from_single_device_arrays.
    Single-process tests cannot make jax report multiple processes, so the
    multi-host geometry is modelled by the one quantity the check consumes:
    ``_local_rows`` < ``_global_batch``."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    with make_batch_reader(mixed_ds, shuffle_row_groups=False, num_epochs=1,
                           decode_placement={"image": "device-mixed"}) as r:
        with JaxDataLoader(r, batch_size=8, mesh=mesh,
                           shardings={"idx": P("data"), "image": P()},
                           fields=["idx", "image"],
                           pad_shapes={"image": TARGET}) as loader:
            loader._local_rows = 4  # this host owns half the global batch
            with pytest.raises(PetastormTpuError,
                               match="batch axis to be sharded"):
                loader._validate_mixed_scatter_layout("image")
            # a sharded spec whose shards cover more rows than the host owns
            # trips the coverage check with the mesh/spec in the message
            loader._specs = {"idx": P("data"), "image": P("data")}
            with pytest.raises(PetastormTpuError, match="host owns 4"):
                loader._validate_mixed_scatter_layout("image")


def test_uniform_device_path_still_guides_to_mixed(mixed_ds):
    """The uniform 'device' path on a mixed dataset keeps failing loudly,
    now pointing at 'device-mixed'."""
    with pytest.raises(PetastormTpuError, match="device-mixed"):
        make_batch_reader(mixed_ds, num_epochs=1,
                          decode_placement={"image": "device"})
