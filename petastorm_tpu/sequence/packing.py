"""Deterministic sequence packing: variable-length documents -> fixed
``(batch, seq_len)`` token blocks.

The dominant production workload is token streams (ROADMAP item 4), and the
input-pipeline papers (tf.data, PAPERS.md) put packing/filtering *inside*
the input pipeline as first-class transformations.  This module is the
packing half: a streaming first-fit bin packer that turns a plan-ordered
stream of variable-length token documents into dense fixed-shape blocks
carrying document-boundary segment IDs, per-document positions and a
loss mask - the exact quadruple a packed-attention training step consumes.

Determinism is the design constraint, not an afterthought: the packer is a
pure function of the *document stream order* (no clocks, no RNG, no
arrival-time coupling), so under ``deterministic='seed'`` reader delivery
the packed stream is bit-identical across worker counts, executor flavors,
chaos kills and the service hop - and the chaos matrix certifies it
(tests/test_determinism_matrix.py token-dataset cells, via
:func:`packed_stream_digest`).

Two delivery modes:

* **packed** (:class:`SequencePacker` / :func:`iter_packed_blocks`): dense
  ``(batch, seq_len)`` blocks; fill-rate typically >= 0.85 on real-corpus
  length distributions (gated in tools/bench_compare.py).
* **ragged** (:func:`iter_ragged_batches`): flat token buffer + offsets for
  consumers that pack on-device (e.g. a jit-compiled packer kernel).

docs/operations.md "Token pipelines" is the operator-facing runbook.
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, Iterable, Iterator, List, Optional

import numpy as np

from petastorm_tpu.errors import PetastormTpuError

#: long-document policies (documents longer than ``seq_len``)
LONG_DOC_POLICIES = ("split", "truncate", "error")


class _Bin:
    """One open packing bin: preallocated output row being filled."""

    __slots__ = ("tokens", "segment_ids", "positions", "loss_mask", "used",
                 "segments")

    def __init__(self, seq_len: int, tokens_dtype, mask_dtype, pad_token):
        self.tokens = np.full(seq_len, pad_token, dtype=tokens_dtype)
        self.segment_ids = np.zeros(seq_len, dtype=np.int32)
        self.positions = np.zeros(seq_len, dtype=np.int32)
        self.loss_mask = np.zeros(seq_len, dtype=mask_dtype)
        self.used = 0
        self.segments = 0

    def place(self, doc: np.ndarray) -> None:
        n = len(doc)
        lo, hi = self.used, self.used + n
        self.tokens[lo:hi] = doc
        self.segments += 1
        self.segment_ids[lo:hi] = self.segments
        self.positions[lo:hi] = np.arange(n, dtype=np.int32)
        self.loss_mask[lo:hi] = 1
        self.used = hi

    def row(self) -> Dict[str, np.ndarray]:
        return {"tokens": self.tokens, "segment_ids": self.segment_ids,
                "positions": self.positions, "loss_mask": self.loss_mask}


class SequencePacker:
    """Streaming first-fit-shrinking bin packer over a document stream.

    Feed variable-length 1-D token arrays in stream order; completed rows
    come back as ``{'tokens', 'segment_ids', 'positions', 'loss_mask'}``
    dicts of ``(seq_len,)`` arrays.  ``segment_ids`` are 1-based per packed
    document (0 = padding), ``positions`` restart at 0 per document, and
    ``loss_mask`` is 1 on real tokens, 0 on padding - the standard packed
    segment-attention contract.

    The algorithm (exactly this, because the packed stream must be a pure
    function of document order - the chaos matrix certifies it):

    * up to ``open_bins`` partially-filled bins are kept, in creation order;
    * each document goes to the FIRST open bin it fits (first-fit); a bin
      that fills exactly closes and is emitted immediately;
    * when nothing fits and the open set is full, the bin with the LEAST
      remaining capacity (oldest on ties) closes and is emitted - bins
      shrink until they cannot absorb the incoming document, then the most-
      shrunk one ships - and a fresh bin takes the document;
    * ``finish()`` emits the remaining bins in creation order.

    Documents longer than ``seq_len`` follow ``long_docs``: ``'split'``
    (default; chunks of ``seq_len``, each chunk packs as its own segment),
    ``'truncate'`` (keep the first ``seq_len`` tokens) or ``'error'``.
    Empty (and None) documents are skipped and counted.

    ``stats()`` reports fill-rate and token/document accounting; with a
    ``telemetry`` recorder the same numbers ride the ``sequence.*`` series
    (docs/operations.md "Token pipelines").
    """

    def __init__(self, seq_len: int, open_bins: int = 8,
                 long_docs: str = "split", tokens_dtype=np.int32,
                 mask_dtype=np.float32, pad_token: int = 0,
                 telemetry=None):
        if seq_len < 1:
            raise PetastormTpuError("seq_len must be >= 1")
        if open_bins < 1:
            raise PetastormTpuError("open_bins must be >= 1")
        if long_docs not in LONG_DOC_POLICIES:
            raise PetastormTpuError(
                f"long_docs must be one of {LONG_DOC_POLICIES}; got"
                f" {long_docs!r}")
        self.seq_len = int(seq_len)
        self._open_limit = int(open_bins)
        self._long_docs = long_docs
        self._tokens_dtype = np.dtype(tokens_dtype)
        self._mask_dtype = np.dtype(mask_dtype)
        self._pad_token = pad_token
        self._bins: List[_Bin] = []
        self._finished = False
        # accounting (stats() / sequence.* telemetry)
        self._docs = 0
        self._docs_split = 0
        self._docs_truncated = 0
        self._docs_empty = 0
        self._tokens = 0
        self._rows = 0
        from petastorm_tpu.telemetry import resolve as _resolve_telemetry

        self._telemetry = _resolve_telemetry(telemetry)
        tele = self._telemetry
        self._m_docs = tele.counter("sequence.docs_packed")
        self._m_tokens = tele.counter("sequence.tokens_packed")
        self._m_pad = tele.counter("sequence.pad_tokens")
        self._m_rows = tele.counter("sequence.rows_emitted")
        self._m_split = tele.counter("sequence.docs_split")
        self._g_fill = tele.gauge("sequence.fill_rate")

    def _emit(self, idx: int) -> Dict[str, np.ndarray]:
        b = self._bins.pop(idx)
        self._rows += 1
        if self._telemetry.enabled:
            self._m_rows.add(1)
            self._m_pad.add(self.seq_len - b.used)
            self._g_fill.set(self.fill_rate)
        return b.row()

    def feed(self, doc) -> List[Dict[str, np.ndarray]]:
        """Pack one document; returns the rows this document completed
        (usually none or one - more when a long document splits)."""
        if self._finished:
            raise PetastormTpuError("SequencePacker.feed after finish()")
        if doc is None:
            self._docs_empty += 1
            return []
        doc = np.asarray(doc)
        if doc.ndim != 1:
            raise PetastormTpuError(
                f"documents must be 1-D token arrays; got shape {doc.shape}")
        if len(doc) == 0:
            self._docs_empty += 1
            return []
        self._docs += 1
        chunks: Iterable[np.ndarray]
        kept = len(doc)  # tokens this doc will actually emit
        if len(doc) <= self.seq_len:
            chunks = (doc,)
        elif self._long_docs == "split":
            self._docs_split += 1
            if self._telemetry.enabled:
                self._m_split.add(1)
            chunks = (doc[i:i + self.seq_len]
                      for i in range(0, len(doc), self.seq_len))
        elif self._long_docs == "truncate":
            self._docs_truncated += 1
            kept = self.seq_len
            chunks = (doc[:self.seq_len],)
        else:
            raise PetastormTpuError(
                f"document of {len(doc)} tokens exceeds seq_len"
                f" {self.seq_len} (long_docs='error')")
        self._tokens += kept
        if self._telemetry.enabled:
            # counted once, post-policy: Counters are monotonic (a negative
            # truncation correction would read as a reset to rate())
            self._m_docs.add(1)
            self._m_tokens.add(kept)
        out: List[Dict[str, np.ndarray]] = []
        for chunk in chunks:
            n = len(chunk)
            placed = False
            for i, b in enumerate(self._bins):
                if self.seq_len - b.used >= n:
                    b.place(chunk)
                    if b.used == self.seq_len:
                        out.append(self._emit(i))
                    placed = True
                    break
            if placed:
                continue
            if len(self._bins) >= self._open_limit:
                # evict the most-shrunk bin (least remaining; oldest on ties)
                out.append(self._emit(
                    min(range(len(self._bins)),
                        key=lambda i: self.seq_len - self._bins[i].used)))
            b = _Bin(self.seq_len, self._tokens_dtype, self._mask_dtype,
                     self._pad_token)
            b.place(chunk)
            self._bins.append(b)
            if b.used == self.seq_len:
                out.append(self._emit(len(self._bins) - 1))
        return out

    def finish(self) -> List[Dict[str, np.ndarray]]:
        """Close and emit the remaining open bins, in creation order."""
        self._finished = True
        out = []
        while self._bins:
            out.append(self._emit(0))
        return out

    @property
    def fill_rate(self) -> float:
        """Real tokens / emitted slots (0.0 before the first emitted row).
        Open-bin tokens are excluded until their bin emits."""
        slots = self._rows * self.seq_len
        if not slots:
            return 0.0
        pending = sum(b.used for b in self._bins)
        return (self._tokens - pending) / slots

    def stats(self) -> Dict:
        """Packing accounting: documents, tokens, emitted rows, fill rate."""
        return {"docs": self._docs,
                "docs_split": self._docs_split,
                "docs_truncated": self._docs_truncated,
                "docs_empty": self._docs_empty,
                "tokens": self._tokens,
                "rows": self._rows,
                "seq_len": self.seq_len,
                "fill_rate": round(self.fill_rate, 4)}


def iter_packed_rows(docs: Iterable, seq_len: int,
                     packer: Optional[SequencePacker] = None,
                     finish: bool = True,
                     **packer_kwargs) -> Iterator[Dict[str, np.ndarray]]:
    """Pack a document iterable; yields completed rows in emission order.

    Pass an existing ``packer`` to read its accounting afterwards; to keep
    packing the SAME packer across several calls, pass ``finish=False`` on
    all but the last (``finish()`` closes the open bins and refuses further
    feeding).  An existing packer must agree with ``seq_len`` and takes no
    ``packer_kwargs`` - a silent mismatch would hand a jit consumer
    wrong-shaped blocks."""
    if packer is not None:
        if packer.seq_len != seq_len:
            raise PetastormTpuError(
                f"packer.seq_len {packer.seq_len} != seq_len {seq_len}:"
                " the packer's width wins silently otherwise")
        if packer_kwargs:
            raise PetastormTpuError(
                f"packer_kwargs {sorted(packer_kwargs)} are ignored when an"
                " existing packer is passed; configure the packer instead")
    p = packer if packer is not None else SequencePacker(seq_len,
                                                         **packer_kwargs)
    for doc in docs:
        yield from p.feed(doc)
    if finish:
        yield from p.finish()


def iter_packed_blocks(docs: Iterable, seq_len: int, batch_size: int,
                       packer: Optional[SequencePacker] = None,
                       drop_last: bool = False,
                       **packer_kwargs) -> Iterator[Dict[str, np.ndarray]]:
    """Pack documents into dense ``(batch, seq_len)`` blocks.

    Yields dicts of stacked ``tokens`` / ``segment_ids`` / ``positions`` /
    ``loss_mask`` arrays of shape ``(batch_size, seq_len)``.  The final
    block may have fewer rows (``drop_last=True`` drops it instead - the
    fixed-shape contract a jit consumer wants).
    """
    if batch_size < 1:
        raise PetastormTpuError("batch_size must be >= 1")
    pending: List[Dict[str, np.ndarray]] = []
    for row in iter_packed_rows(docs, seq_len, packer=packer,
                                **packer_kwargs):
        pending.append(row)
        if len(pending) == batch_size:
            yield {k: np.stack([r[k] for r in pending]) for k in pending[0]}
            pending = []
    if pending and not drop_last:
        yield {k: np.stack([r[k] for r in pending]) for k in pending[0]}


def iter_ragged_batches(docs: Iterable, batch_docs: int,
                        tokens_dtype=np.int32) -> Iterator[Dict[str, np.ndarray]]:
    """Ragged delivery for consumers that pack on-device: groups of
    ``batch_docs`` documents as one flat token buffer plus offsets.

    Yields ``{'tokens': (total,), 'offsets': (n+1,) int64, 'lengths': (n,)
    int32}`` - document ``i`` is ``tokens[offsets[i]:offsets[i+1]]``.  The
    final group may hold fewer documents.  Empty/None documents are kept as
    zero-length spans (the consumer sees the stream's true document count).
    """
    if batch_docs < 1:
        raise PetastormTpuError("batch_docs must be >= 1")
    tokens_dtype = np.dtype(tokens_dtype)
    group: List[np.ndarray] = []

    def _flush():
        lengths = np.asarray([len(d) for d in group], dtype=np.int32)
        offsets = np.zeros(len(group) + 1, dtype=np.int64)
        np.cumsum(lengths, out=offsets[1:])
        flat = (np.concatenate(group).astype(tokens_dtype, copy=False)
                if offsets[-1] else np.empty(0, dtype=tokens_dtype))
        return {"tokens": flat, "offsets": offsets, "lengths": lengths}

    for doc in docs:
        doc = (np.empty(0, dtype=tokens_dtype) if doc is None
               else np.asarray(doc).ravel())
        group.append(doc)
        if len(group) == batch_docs:
            yield _flush()
            group = []
    if group:
        yield _flush()


#: field emission order for the packed-stream digest (fixed, so the digest
#: never depends on dict ordering)
PACKED_FIELDS = ("tokens", "segment_ids", "positions", "loss_mask")


def packed_stream_digest(blocks: Iterable[Dict[str, np.ndarray]],
                         crc: int = 0) -> int:
    """Order-sensitive crc32 chain over a packed block stream - the packed
    analog of :class:`petastorm_tpu.seeding.StreamDigest`: two runs whose
    packed streams are bit-identical produce equal chains, in O(1) diff.

    Folds each block's shape and the four packed columns' bytes in the
    fixed :data:`PACKED_FIELDS` order; pass the previous return value as
    ``crc`` to chain across calls.
    """
    for block in blocks:
        rows, seq_len = np.asarray(block["tokens"]).shape
        crc = zlib.crc32(struct.pack("<2q", rows, seq_len), crc)
        for name in PACKED_FIELDS:
            col = np.ascontiguousarray(np.asarray(block[name]))
            crc = zlib.crc32(col.tobytes(), crc)
    return crc
