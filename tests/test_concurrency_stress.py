"""Concurrency scaling under oversubscription (VERDICT round-1 weak #5 / next #6).

This host has few cores, so 8-16 workers here exercise CONTENTION, ordering,
and leak behavior rather than speedup - exactly the properties that must hold
on real many-core TPU hosts.  Reference analog: the pool tests at
tests/test_workers_pool.py:19-60 (ventilate/consume across pool types).
"""

import collections
import gc

import numpy as np
import pytest

from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.schema import Field, Schema

ROWS = 192  # 48 rowgroups x 4 rows


@pytest.fixture(scope="module")
def ds(tmp_path_factory):
    url = str(tmp_path_factory.mktemp("stress") / "ds")
    schema = Schema("Stress", [
        Field("id", np.int64),
        Field("payload", np.float32, (64,), NdarrayCodec()),
    ])
    write_dataset(url, schema,
                  [{"id": i, "payload": np.full(64, i, np.float32)}
                   for i in range(ROWS)],
                  row_group_size_rows=4)
    return url


@pytest.mark.parametrize("workers", [8, 16])
def test_thread_pool_oversubscribed_no_loss_no_dup(ds, workers):
    """16 threads on a small host: heavy GIL churn, out-of-order completion -
    the multiset and the ordinal-exact cursor must both survive."""
    for epochs in (1, 3):
        with make_batch_reader(ds, reader_pool_type="thread",
                               workers_count=workers, shuffle_seed=2,
                               num_epochs=epochs) as r:
            seen = [int(v) for b in r.iter_batches() for v in b.columns["id"]]
            state = r.state_dict()
        counts = collections.Counter(seen)
        assert sorted(counts) == list(range(ROWS))
        assert set(counts.values()) == {epochs}
        assert state["ordinal_exact"]
        assert state["position"] == epochs * 48  # exhausted = exact prefix


def test_process_pool_shm_arena_returns_to_baseline(ds):
    """8 spawn workers hammer the shm arena across 3 epochs; after the
    consumer drops its zero-copy views, every block must be back (no leak,
    no fragmentation lockup)."""
    with make_batch_reader(ds, reader_pool_type="process", workers_count=8,
                           num_epochs=3, shuffle_seed=3) as r:
        diag0 = r.diagnostics
        if not diag0.get("shm_transport"):
            pytest.skip("native shm arena unavailable on this host")
        baseline = diag0["shm_free_bytes"]
        seen = []
        for b in r.iter_batches():
            seen.append(np.asarray(b.columns["id"]).copy())
            del b
        gc.collect()
        diag = r.diagnostics
        assert diag["shm_free_bytes"] == baseline, "arena leaked blocks"
    counts = collections.Counter(int(v) for a in seen for v in a)
    assert sorted(counts) == list(range(ROWS))
    assert set(counts.values()) == {3}


def test_shard_mode_epoch_oversubscribed_no_loss(ds):
    """shard_mode='epoch' re-deals rowgroup ownership every epoch; under an
    oversubscribed thread pool the per-epoch partition property must hold
    regardless of completion order: the shards' union covers every row
    exactly once per epoch (so exactly num_epochs times overall), and
    ownership actually changes between epochs."""
    shards, epochs = 2, 2
    union = []
    for s in range(shards):
        with make_batch_reader(ds, reader_pool_type="thread", workers_count=8,
                               cur_shard=s, shard_count=shards,
                               shard_mode="epoch", shuffle_seed=7,
                               num_epochs=epochs) as r:
            union.extend(int(v) for b in r.iter_batches()
                         for v in b.columns["id"])
    counts = collections.Counter(union)
    assert sorted(counts) == list(range(ROWS))
    assert set(counts.values()) == {epochs}

    # the re-deal is real: shard 0's epoch-0 and epoch-1 rowgroup sets differ
    from petastorm_tpu.etl.metadata import open_dataset
    from petastorm_tpu.plan import ReadPlan

    plan = ReadPlan(open_dataset(ds).row_groups, shuffle_seed=7,
                    shard_index=0, shard_count=2, shard_mode="epoch")
    e0 = {it.row_group.global_index for it in plan.epoch_items(0)}
    e1 = {it.row_group.global_index for it in plan.epoch_items(1)}
    assert e0 != e1


def test_native_decode_fanout_matches_single_thread(tmp_path):
    """The batched native decoder's internal thread fan-out (nthreads=16,
    oversubscribed here) must be bit-identical to nthreads=1."""
    pytest.importorskip("cv2")
    from petastorm_tpu.native import image as native_image
    from petastorm_tpu.test_util.synthetic import synthetic_jpeg_bytes

    if not native_image.available():
        pytest.skip("native image library unavailable")
    bufs = synthetic_jpeg_bytes(64, 64, 96, quality=90)
    import pyarrow as pa

    col = pa.array(bufs, type=pa.binary())
    out1 = np.empty((64, 64, 96, 3), np.uint8)
    out16 = np.empty((64, 64, 96, 3), np.uint8)
    assert native_image.decode_column_native(col, out1, nthreads=1)
    assert native_image.decode_column_native(col, out16, nthreads=16)
    np.testing.assert_array_equal(out1, out16)

    # and the coefficient (entropy-only) fan-out too
    p1, q1, l1 = native_image.read_jpeg_coefficients_column(bufs, nthreads=1)
    p16, q16, l16 = native_image.read_jpeg_coefficients_column(bufs, nthreads=16)
    assert l1 == l16
    np.testing.assert_array_equal(q1, q16)
    for a, b in zip(p1, p16):
        np.testing.assert_array_equal(a, b)


def test_concurrency_fuzz_smoke(tmp_path):
    """A bounded slice of tools/concurrency_fuzz.py runs in CI: a dozen
    seeded random configurations (pool flavor x workers x epochs x
    consumption pattern), each asserting the exact-multiset invariant.
    The open-ended version is `python tools/concurrency_fuzz.py`."""
    import collections
    import random

    from petastorm_tpu.reader import make_batch_reader
    from tools import concurrency_fuzz as fuzz

    datasets = fuzz.build_datasets(str(tmp_path))
    for seed in range(12):
        rnd = random.Random(seed)
        url = rnd.choice(datasets)
        epochs = rnd.randint(1, 2)
        cfg = dict(reader_pool_type=rnd.choice(["thread", "thread", "serial"]),
                   workers_count=rnd.choice([1, 4, 8]),
                   num_epochs=epochs,
                   shuffle_row_groups=rnd.random() < 0.8,
                   shuffle_seed=rnd.randint(0, 999),
                   results_queue_size=rnd.choice([2, 10]))
        mode = rnd.choice(["plain", "resume", "shards"])
        if mode == "plain":
            seen = fuzz.run_plain(make_batch_reader, url, cfg)
        elif mode == "resume":
            seen = fuzz.run_resume(make_batch_reader, url, cfg, rnd)
        else:
            seen = fuzz.run_shards(make_batch_reader, url, cfg, rnd)
        counts = collections.Counter(seen)
        assert sorted(counts) == list(range(fuzz.ROWS)), (seed, mode, cfg)
        assert set(counts.values()) == {epochs}, (seed, mode, cfg)


def test_scaling_microbench_smoke(tmp_path):
    """The committed scaling microbench runs end-to-end and reports one JSON
    line per worker count."""
    import json

    from petastorm_tpu.benchmark import scaling

    url = str(tmp_path / "ds")
    scaling.build_dataset(url, rows=32, height=32, width=32)
    results = [scaling.measure(url, "thread", w, epochs=1) for w in (1, 8)]
    for res in results:
        assert res["samples"] == 32 and res["samples_per_sec"] > 0
        json.dumps(res)  # serializable
