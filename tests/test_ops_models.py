"""Tests for on-device ops and consumer models (CPU backend; pallas via interpret)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from petastorm_tpu.ops.normalize import _choose_block, normalize_images


def test_normalize_xla_path_matches_numpy():
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(0, 255, (4, 8, 8, 3), dtype=np.uint8))
    mean, std = (0.5, 0.4, 0.3), (0.2, 0.25, 0.3)
    out = np.asarray(normalize_images(imgs, mean, std, out_dtype=jnp.float32))
    want = (np.asarray(imgs, np.float32) / 255.0 - np.array(mean, np.float32)) \
        / np.array(std, np.float32)
    np.testing.assert_allclose(out, want, atol=1e-5)


def test_normalize_pallas_kernel_interpret_matches():
    # run the actual pallas kernel in interpret mode on CPU
    from petastorm_tpu.ops import normalize as nz

    rng = np.random.default_rng(1)
    n, h, w, c = 8, 16, 8, 3  # L = 16*8*3 = 384 -> 128-divisible
    imgs = rng.integers(0, 255, (n, h, w, c), dtype=np.uint8)
    length = h * w * c
    std = np.array((0.2, 0.25, 0.3), np.float32)
    mean = np.array((0.5, 0.4, 0.3), np.float32)
    scale = np.tile(1.0 / (255.0 * std), length // c)[None, :]
    bias = np.tile(-mean / std, length // c)[None, :]
    block = _choose_block(n, length)
    assert block is not None

    from jax.experimental import pallas as pl

    out = pl.pallas_call(
        nz._normalize_kernel,
        out_shape=jax.ShapeDtypeStruct((n, length), jnp.float32),
        grid=(n // block[0], length // block[1]),
        in_specs=[pl.BlockSpec(block, lambda i, j: (i, j)),
                  pl.BlockSpec((1, block[1]), lambda i, j: (0, j)),
                  pl.BlockSpec((1, block[1]), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec(block, lambda i, j: (i, j)),
        interpret=True,
    )(imgs.reshape(n, length), jnp.asarray(scale), jnp.asarray(bias))
    want = (imgs.reshape(n, length).astype(np.float32) / 255.0
            - np.tile(mean, length // c)) / np.tile(std, length // c)
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)


def test_normalize_rejects_bad_inputs():
    with pytest.raises(TypeError):
        normalize_images(jnp.zeros((2, 4, 4, 3), jnp.float32))
    with pytest.raises(ValueError):
        normalize_images(jnp.zeros((2, 4, 4, 3), jnp.uint8), mean=(0.5, 0.5))


def test_choose_block_constraints():
    assert _choose_block(8, 1024) is not None
    assert _choose_block(7, 1024) is None     # rows not 8-divisible
    assert _choose_block(8, 100) is None      # cols not 128-divisible


def test_mlp_forward():
    from petastorm_tpu.models import MLP

    model = MLP(features=(16,), num_classes=10)
    x = jnp.zeros((4, 28, 28), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(params, x)
    assert out.shape == (4, 10)


def test_resnet_tiny_forward():
    # tiny stage config to keep CPU compile fast; exercises the block wiring
    from petastorm_tpu.models.resnet import ResNet

    model = ResNet(stage_sizes=[1, 1], num_classes=7, num_filters=8,
                   dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(params, x)
    assert out.shape == (2, 7)


def test_graft_entry_shapes():
    # entry() must return (jittable fn, example args) - trace without executing
    import sys
    sys.path.insert(0, "/root/repo")
    from __graft_entry__ import entry

    fn, args = entry()
    shape = jax.eval_shape(fn, *args)
    assert shape.shape == (8, 1000)


def test_random_crop_flip_augmentation():
    """On-device batched augmentation: correct geometry, per-image
    randomness, deterministic per key, pixels preserved (no interpolation)."""
    from petastorm_tpu.ops import random_crop, random_crop_flip, random_flip

    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(0, 255, (16, 12, 10, 3), dtype=np.uint8))
    key = jax.random.PRNGKey(7)

    crops = random_crop(imgs, key, (8, 6))
    assert crops.shape == (16, 8, 6, 3) and crops.dtype == jnp.uint8
    # every crop is a contiguous window of its source image
    src = np.asarray(imgs)
    for i, c in enumerate(np.asarray(crops)):
        found = any(np.array_equal(src[i, y:y + 8, x:x + 6], c)
                    for y in range(5) for x in range(5))
        assert found, i
    # distinct offsets across the batch (overwhelmingly likely)
    assert len({c.tobytes() for c in np.asarray(crops)}) > 1

    flipped = random_flip(imgs, key)
    f = np.asarray(flipped)
    states = {True: 0, False: 0}
    for i in range(16):
        if np.array_equal(f[i], src[i]):
            states[False] += 1
        else:
            assert np.array_equal(f[i], src[i, :, ::-1])
            states[True] += 1
    assert states[True] > 0 and states[False] > 0  # both outcomes occur

    both = random_crop_flip(imgs, key, crop_hw=(8, 6))
    assert both.shape == (16, 8, 6, 3)
    # deterministic per key, and the key actually drives the outcome
    assert np.array_equal(np.asarray(both),
                          np.asarray(random_crop_flip(imgs, key, crop_hw=(8, 6))))
    other = random_crop_flip(imgs, jax.random.PRNGKey(8), crop_hw=(8, 6))
    assert not np.array_equal(np.asarray(both), np.asarray(other))

    with pytest.raises(ValueError, match="larger than"):
        random_crop(imgs, key, (20, 6))


# -- resize / random-resized-crop (on-chip ImageNet preprocessing) ------------


def test_resize_images_uint8_roundtrip_and_identity():
    import jax.numpy as jnp

    from petastorm_tpu.ops import resize_images

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, (3, 32, 48, 3), dtype=np.uint8)
    out = resize_images(jnp.asarray(imgs), (16, 24))
    assert out.shape == (3, 16, 24, 3) and out.dtype == jnp.uint8
    # same-size resize is (near-)identity
    same = np.asarray(resize_images(jnp.asarray(imgs), (32, 48)))
    assert np.abs(same.astype(int) - imgs.astype(int)).max() <= 1
    # constant image stays constant through antialiased resampling
    flat = np.full((1, 32, 48, 3), 77, np.uint8)
    out_flat = np.asarray(resize_images(jnp.asarray(flat), (20, 20)))
    assert np.abs(out_flat.astype(int) - 77).max() <= 1


def test_random_resized_crop_static_shapes_and_determinism():
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.ops import random_resized_crop

    rng = np.random.default_rng(1)
    imgs = jnp.asarray(rng.integers(0, 255, (4, 40, 56, 3), dtype=np.uint8))
    key = jax.random.PRNGKey(3)
    a = random_resized_crop(imgs, key, (24, 24))
    assert a.shape == (4, 24, 24, 3) and a.dtype == jnp.uint8
    b = random_resized_crop(imgs, key, (24, 24))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # same key
    c = random_resized_crop(imgs, jax.random.PRNGKey(4), (24, 24))
    assert not np.array_equal(np.asarray(a), np.asarray(c))      # new key


def test_random_resized_crop_full_scale_equals_resize():
    """scale=(1,1), ratio=(1,1) on a square image pins the crop to the whole
    frame: the op must agree with a plain antialiased resize."""
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.ops import random_resized_crop, resize_images

    rng = np.random.default_rng(2)
    imgs = jnp.asarray(rng.integers(0, 255, (2, 32, 32, 3), dtype=np.uint8))
    got = np.asarray(random_resized_crop(imgs, jax.random.PRNGKey(0), (16, 16),
                                         scale=(1.0, 1.0), ratio=(1.0, 1.0),
                                         antialias=True))
    want = np.asarray(resize_images(imgs, (16, 16)))
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1


def test_random_resized_crop_constant_image_constant_output():
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.ops import random_resized_crop

    flat = jnp.full((3, 48, 48, 3), 130, jnp.uint8)
    out = np.asarray(random_resized_crop(flat, jax.random.PRNGKey(9), (20, 20)))
    assert np.abs(out.astype(int) - 130).max() <= 1


def test_mixup_blend_and_label_pairing():
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.ops import mixup

    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.standard_normal((8, 8, 8, 3)).astype(np.float32))
    labels = jnp.arange(8)
    key = jax.random.PRNGKey(1)
    mixed, la, lb, lam = mixup(imgs, labels, key, alpha=0.4)
    lam_f = float(lam)
    assert 0.5 <= lam_f <= 1.0  # dominant-first convention
    # la is the original labels; lb is a permutation of them
    np.testing.assert_array_equal(np.asarray(la), np.arange(8))
    assert sorted(np.asarray(lb).tolist()) == list(range(8))
    # the blend is exactly lam*a + (1-lam)*b for the paired images
    b_idx = np.asarray(lb)  # the permutation used
    want = lam_f * np.asarray(imgs) + (1 - lam_f) * np.asarray(imgs)[b_idx]
    np.testing.assert_allclose(np.asarray(mixed), want, rtol=1e-5, atol=1e-5)
    # deterministic per key
    mixed2, _, _, _ = mixup(imgs, labels, key, alpha=0.4)
    np.testing.assert_array_equal(np.asarray(mixed), np.asarray(mixed2))


def test_mixup_uint8_roundtrip():
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.ops import mixup

    imgs = jnp.asarray(np.random.default_rng(1).integers(
        0, 255, (4, 6, 6, 3), dtype=np.uint8))
    mixed, _, _, _ = mixup(imgs, jnp.arange(4), jax.random.PRNGKey(0))
    assert mixed.dtype == jnp.uint8 and mixed.shape == imgs.shape


def test_cutmix_box_area_matches_lambda():
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.ops import cutmix

    n, h, w = 6, 32, 32
    imgs = jnp.asarray(np.random.default_rng(2).integers(
        0, 255, (n, h, w, 3), dtype=np.uint8))
    labels = jnp.arange(n)
    mixed, la, lb, lam = cutmix(imgs, labels, jax.random.PRNGKey(7))
    assert mixed.dtype == jnp.uint8
    perm = np.asarray(lb)
    src, dst = np.asarray(imgs), np.asarray(mixed)
    # count pixels equal to the partner but not to self (unambiguous on
    # random uint8 content): that fraction is the pasted box = 1 - lam.
    # Fixed points of the permutation (partner IS self) carry no signal -
    # exclude those images from the measurement entirely.
    moved = perm != np.arange(n)
    assert moved.any()
    partner = src[perm]
    in_box = ((dst == partner).all(axis=-1)
              & ~(partner == src).all(axis=-1))[moved]
    frac = in_box.sum() / in_box.size
    assert abs((1 - float(lam)) - frac) < 0.05
    np.testing.assert_array_equal(np.asarray(la), np.arange(n))
    assert sorted(perm.tolist()) == list(range(n))
