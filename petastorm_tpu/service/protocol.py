"""Wire protocol for the disaggregated ingest service.

Lifts ``pool.py``'s ventilate/results contract onto length-prefixed socket
frames: the objects crossing the wire are the exact objects the in-process
pools already move - :class:`~petastorm_tpu.pool.VentilatedItem` in,
``_Ok``-shaped results / picklable ``_Failure`` envelopes out - so the
client executor and the remote workers reuse the pool semantics (ordinals,
attempt counts, failure classification) unchanged.

Frame format: a 4-byte big-endian payload length followed by a pickled
message.  Messages are plain dicts tagged by ``"t"``:

======================  =======================================================
``client_hello``        client -> dispatcher: client_id, pickled worker
                        factory, hostname, shm capability, requeue budget,
                        ``resume`` flag (reconnect of a known client)
``enqueue``             client -> dispatcher: one VentilatedItem
``resync``              client -> dispatcher after a reconnect: every item
                        still in the client's in-flight ledger (dispatcher
                        dedups by ordinal against its own state)
``ack``                 client -> dispatcher: delivered ordinals (frees the
                        dispatcher's redelivery buffer)
``client_stats``        client -> dispatcher: consumer starved-seconds delta
                        (the ``queue.results_empty_wait_s`` signal the
                        autotune controller uses, repurposed as fleet-size
                        pressure - Dispatcher.scaling_signal)
``bye``                 client -> dispatcher: clean goodbye (purge state)
``worker_hello``        worker -> dispatcher: worker name, capacity, hostname
``heartbeat``           worker -> dispatcher: busy count + telemetry counter
                        deltas (folded into the dispatcher's ``service.fleet.*``
                        series)
``result``/``failure``  worker -> dispatcher -> client: one work item's
                        outcome (payload-encoded batch, or a pool._Failure)
``job``                 dispatcher -> worker: a client's pickled worker
                        factory (sent once per (worker, client) pair)
``job_done``            dispatcher -> worker: drop that client's factory
``work``                dispatcher -> worker: one assigned VentilatedItem
``requeued``            dispatcher -> client: an in-flight item was requeued
                        off a dead worker (accounting notice)
``stats?``/``stats``    any -> dispatcher: state snapshot (CLI, tests)
======================  =======================================================

Result payloads: ``("pickle", value)`` is the portable form (plain frame
payloads for remote workers).  ``("shm", arena_name, ShmBatchRef)`` is the
local fast path reusing :mod:`petastorm_tpu.native.transport`'s batch
encoders: a worker co-located with its client encodes the batch into a
named shared-memory arena and ships only the descriptor; the client
attaches the arena by name and decodes zero-copy views whose leases free
the blocks cross-process.  Armed only when both ends share a host AND the
native transport plane is available (python >= 3.12 PEP 688, like the
process pool's shm transport).
"""

from __future__ import annotations

import pickle
import select
import socket
import struct
import threading
import time
from typing import Any, Dict, Optional, Tuple

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.errors import PetastormTpuError

#: protocol version, checked at hello time (bumped on incompatible change)
PROTOCOL_VERSION = 1

_LEN = struct.Struct("!I")
#: frames larger than this are refused (a decoded rowgroup batch is tens of
#: MB; anything approaching this is a corrupt length prefix, not data)
MAX_FRAME_BYTES = 1 << 30


class FrameClosedError(PetastormTpuError):
    """The peer closed the connection (EOF mid-stream or before a frame)."""


class FrameSocket:
    """A socket speaking length-prefixed pickle frames.

    ``send`` is thread-safe (one lock per socket: the dispatcher's pump and
    reply paths send to the same worker from different threads).  ``recv``
    has a single consumer per socket (each connection gets one reader
    thread) and keeps partial frames across timeouts.
    """

    def __init__(self, sock: socket.socket):
        try:
            # small control frames must not sit in Nagle buffers behind a
            # large result frame; best-effort (AF_UNIX sockets refuse it)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        # blocking mode, permanently: recv timeouts use select (see _fill),
        # so a send can never inherit a recv timeout and die mid-frame
        sock.settimeout(None)
        self._sock = sock
        self._send_lock = threading.Lock()
        self._buf = bytearray()
        self._closed = False
        #: cumulative frame bytes (telemetry: service.frame_bytes_*)
        self.bytes_sent = 0
        self.bytes_received = 0

    def send(self, msg: Dict[str, Any]) -> int:
        """Pickle + frame + sendall; returns the frame size in bytes.
        Raises OSError when the connection is gone."""
        payload = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
        if len(payload) > MAX_FRAME_BYTES:
            raise PetastormTpuError(
                f"frame of {len(payload)} bytes exceeds MAX_FRAME_BYTES")
        frame = _LEN.pack(len(payload)) + payload
        with self._send_lock:
            if self._closed:
                raise OSError("frame socket is closed")
            self._sock.sendall(frame)
        self.bytes_sent += len(frame)
        return len(frame)

    def recv(self, timeout: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Next message, or None on timeout (partial frames are kept and
        completed by later calls).  Raises FrameClosedError on EOF."""
        need = _LEN.size
        header = self._fill(need, timeout)
        if header is None:
            return None
        (length,) = _LEN.unpack(bytes(self._buf[:need]))
        if length > MAX_FRAME_BYTES:
            raise PetastormTpuError(
                f"incoming frame claims {length} bytes (corrupt stream?)")
        body = self._fill(need + length, timeout)
        if body is None:
            return None
        payload = bytes(self._buf[need:need + length])
        del self._buf[:need + length]
        self.bytes_received += need + length
        return pickle.loads(payload)

    def _fill(self, n: int, timeout: Optional[float]):
        """Grow the buffer to ``n`` bytes; None on timeout, raises on EOF.

        Timeouts come from ``select``, NOT ``settimeout``: a socket timeout
        is socket-global, so setting one for recv would also arm it for a
        concurrent ``sendall`` on another thread - which can then raise
        after a PARTIAL write of a large frame and permanently desync the
        length-prefixed stream.  The socket stays blocking throughout;
        ``recv`` is only called when select reports readability."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while len(self._buf) < n:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
            else:
                remaining = None
            try:
                readable, _, _ = select.select([self._sock], [], [],
                                               remaining)
                if not readable:
                    return None
                chunk = self._sock.recv(min(1 << 20, n - len(self._buf)))
            except OSError as exc:
                raise FrameClosedError(f"connection lost: {exc}") from exc
            if not chunk:
                raise FrameClosedError("peer closed the connection")
            self._buf.extend(chunk)
        return self._buf

    def close(self) -> None:
        """Shutdown + close; a blocked peer recv sees EOF immediately."""
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def connect_frames(address: Tuple[str, int],
                   timeout: float = 10.0) -> FrameSocket:
    """Open a FrameSocket to ``(host, port)`` (connect-timeout bounded)."""
    sock = socket.create_connection(address, timeout=timeout)
    sock.settimeout(None)
    return FrameSocket(sock)


def parse_address(address) -> Tuple[str, int]:
    """'host:port' / (host, port) -> (host, port).  The one place the CLI,
    client and tests agree on the address syntax."""
    if isinstance(address, (tuple, list)) and len(address) == 2:
        return str(address[0]), int(address[1])
    if isinstance(address, str) and ":" in address:
        host, _, port = address.rpartition(":")
        return host or "127.0.0.1", int(port)
    raise PetastormTpuError(
        f"service address must be 'host:port' or (host, port); got {address!r}")


# -- result payload encoding --------------------------------------------------

def shm_transport_available() -> bool:
    """True when the native arena transport can carry local-fast-path
    payloads in this process (same gate as the process pool's shm plane)."""
    from petastorm_tpu.native import is_available

    return is_available()


def encode_result(value: Any, arena=None, stop_check=None) -> Tuple:
    """Worker-side payload encoding.

    With a live ``arena`` (local fast path negotiated) ColumnBatches go
    through :func:`petastorm_tpu.native.transport.encode_batch` - one
    producer-side copy into shared memory, a small descriptor on the wire.
    Everything else (remote clients, object columns, full arena fallback)
    ships ``("pickle", value)`` - the plain frame payload.
    """
    if arena is not None and isinstance(value, ColumnBatch):
        from petastorm_tpu.native.transport import ShmBatchRef, encode_batch

        ref = encode_batch(arena, value, stop_check=stop_check)
        if isinstance(ref, ShmBatchRef):
            return ("shm", arena.name, ref)
        value = ref  # encode fell back (object columns / arena full)
    return ("pickle", value)


class PayloadDecoder:
    """Client-side payload decoding; caches attached arenas by name so the
    local fast path attaches each worker's arena once, not per batch."""

    def __init__(self):
        self._arenas: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def decode(self, payload: Tuple) -> Any:
        """Rebuild one result payload (``("pickle", v)`` passthrough;
        ``("shm", ...)`` attaches the named arena and decodes zero-copy)."""
        kind = payload[0]
        if kind == "pickle":
            return payload[1]
        if kind == "shm":
            from petastorm_tpu.native import SharedArena
            from petastorm_tpu.native.transport import decode_batch

            _, name, ref = payload
            with self._lock:
                arena = self._arenas.get(name)
                if arena is None:
                    arena = SharedArena.attach(name)
                    self._arenas[name] = arena
            return decode_batch(arena, ref)
        raise PetastormTpuError(f"unknown payload kind {kind!r}")

    def close(self) -> None:
        """Detach every cached arena (held zero-copy views stay valid
        until collected, like the process pool's arena close)."""
        with self._lock:
            for arena in self._arenas.values():
                try:
                    arena.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            self._arenas.clear()
